"""Seeded fleet chaos drill: randomized faults, deterministic schedule,
checked invariants.

SIGKILL drills (tests/test_fleet_serving.py, bench ``fleet``) prove the
fleet survives CLEAN deaths; this module composes every failure mode the
repo can inject into one reproducible storm against a live 3-server
fleet under sustained mixed load:

  - **SIGKILL + restart** — the clean death, now with the server coming
    BACK on the same port (the pool's stale half-open sockets are the
    satellite-1 case).
  - **SIGSTOP / SIGCONT** — the gray failure: the process is alive (TCP
    accepts, heartbeats stale) but serves nothing; only a deadline
    saves the caller, and the late response after SIGCONT must be
    discarded, not cross-wired.
  - **Wire faults** (interop/netfaults.py) — refused / reset /
    black-hole / slow / torn-frame armed at the client seams
    mid-drill, and at the server seams via a child bounced with
    ``hyperspace.system.faultInjection.*`` conf.
  - **Maintenance churn** — every child runs lease-elected maintenance
    cycles while the drill appends source data, so exactly-once
    execution is contested, not vacuous.
  - **Build-host death** — ``kill-build-host`` runs a concurrent
    2-host multi-host index build (parallel/multihost_build.py) and
    SIGKILLs one of its hosts once claims exist; the survivor must
    finish a byte-identical index with exactly one journalled commit
    while the serving fleet's own invariants keep holding.

The schedule is a PURE function of the seed (:func:`build_schedule`):
same seed ⇒ identical event list, which is what makes a chaos failure
reproducible instead of an anecdote.  Execution timing is wall-clock
(events fire at their offsets), but no invariant depends on timing —
they are end-state properties:

  1. zero lost requests: every request the load threads sent got an
     answer (retry/hedge/failover absorbed every fault);
  2. bit-equal answers: every answer matches the host-side reference;
  3. exactly-once maintenance: the appended data's refresh landed in
     the lifecycle journal with outcome ``done`` exactly once;
  4. metrics accounting: ``client.hedge.wins ≤ client.hedge.sent``,
     ``client.failover ≤ client.retry``, breaker closes ≤ opens, and
     the ``client.breaker.open_now`` gauge within [0, servers];
  5. every ``kill-build-host`` drill completed: a host really died,
     the survivor's index is byte-identical to the single-host
     baseline, and the claim journal shows exactly one commit.

Entry points: ``tools/chaos.py`` (CLI), the bench ``chaos`` section,
and tests/test_chaos.py (smoke + schedule determinism).
"""

from __future__ import annotations

import json
import os
import random
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_CHILD = r"""
import json, os, sys, threading, time
from hyperspace_tpu import Hyperspace, HyperspaceSession
from hyperspace_tpu.interop import QueryServer
from hyperspace_tpu.io import faults

system_path, port, conf_json = sys.argv[1], int(sys.argv[2]), sys.argv[3]
s = HyperspaceSession(system_path=system_path)
for key, value in json.loads(conf_json).items():
    s.conf.set(key, value)
# Conf set after construction: re-apply the fault arming the session
# constructor would have done — this is how a bounced child comes back
# with a wire fault armed.
faults.install_from_conf(s.conf)
hs = Hyperspace(s)
server = QueryServer(s, port=port, handle_sigterm=True).start()

def maintain():
    while True:
        try:
            hs.maintenance_cycle()
        except BaseException:
            pass
        time.sleep(0.25)

threading.Thread(target=maintain, daemon=True).start()
print(json.dumps({"port": server.address[1], "pid": os.getpid()}),
      flush=True)
server.drained.wait()
sys.exit(0)
"""

# Client-seam wire faults the schedule can arm in the DRIVER process
# (site, kind); shaping comes from the plan defaults scaled for a drill.
_CLIENT_FAULTS: List[Tuple[str, str]] = [
    ("net.connect", "refused"),
    ("net.connect", "black-hole"),
    ("net.send", "reset"),
    ("net.send", "torn-frame"),
    ("net.recv", "black-hole"),
    ("net.recv", "slow"),
]
# Server-seam faults a bounced child comes back armed with.
_SERVER_FAULTS: List[Tuple[str, str]] = [
    ("net.send", "torn-frame"),
    ("net.send", "reset"),
    ("net.accept", "reset"),
]


def build_schedule(seed: int, duration_s: float,
                   servers: int) -> List[Dict[str, Any]]:
    """The drill's event list — a pure function of its arguments (fixed
    seed ⇒ identical schedule).  Events target one server at a time
    with recovery built in, so the invariants stay achievable: the
    fleet is degraded continuously but never fully dark."""
    rng = random.Random(int(seed))
    events: List[Dict[str, Any]] = []
    t = min(1.0, duration_s * 0.15)  # let the warm fleet serve first
    appended = False
    while t < duration_s * 0.9:
        roll = rng.random()
        target = rng.randrange(servers)
        if not appended and t >= duration_s * 0.35:
            events.append({"t": round(t, 3), "op": "append"})
            appended = True
            t += duration_s * 0.05
            continue
        if roll < 0.30:
            events.append({"t": round(t, 3), "op": "kill",
                           "server": target,
                           "down_s": round(rng.uniform(0.3, 0.8), 3)})
            t += 1.2
        elif roll < 0.55:
            events.append({"t": round(t, 3), "op": "stop",
                           "server": target,
                           "stop_s": round(rng.uniform(0.4, 1.0), 3)})
            t += 1.4
        elif roll < 0.72:
            site, kind = _CLIENT_FAULTS[
                rng.randrange(len(_CLIENT_FAULTS))]
            events.append({"t": round(t, 3), "op": "client-fault",
                           "site": site, "kind": kind,
                           "at": rng.randrange(1, 4),
                           "count": rng.randrange(1, 4)})
            t += 0.8
        elif roll < 0.80:
            # Concurrent multi-host index build with one of ITS hosts
            # SIGKILLed mid-route: the claim protocol (not the serving
            # fleet) must absorb this one — the survivor finishes the
            # byte-identical index while the drill's load keeps running.
            events.append({"t": round(t, 3), "op": "kill-build-host",
                           "victim": rng.randrange(2)})
            t += 1.6
        else:
            site, kind = _SERVER_FAULTS[
                rng.randrange(len(_SERVER_FAULTS))]
            events.append({"t": round(t, 3), "op": "bounce-armed",
                           "server": target, "site": site, "kind": kind,
                           "at": rng.randrange(2, 6),
                           "count": rng.randrange(1, 3)})
            t += 1.4
    if not appended:
        events.append({"t": round(duration_s * 0.5, 3), "op": "append"})
        events.sort(key=lambda e: e["t"])
    return events


def _build_drill(workdir: str, src: str, tag: int,
                 victim: int) -> Dict[str, Any]:
    """One ``kill-build-host`` drill: a 2-host multi-host build of
    ``src`` with host ``victim`` SIGKILLed once claims exist, graded
    byte-equal against a single-host build of the same snapshot and
    exactly-once against its claim journal."""
    import hashlib

    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig
    from hyperspace_tpu.io.parquet import bucket_id_of_file
    from hyperspace_tpu.lifecycle import journal as lifecycle_journal
    from hyperspace_tpu.lifecycle.lease import WorkClaims
    from hyperspace_tpu.parallel import multihost_build

    def build(path: str, hosts: int):
        sess = HyperspaceSession(system_path=path)
        sess.conf.num_buckets = 4
        sess.conf.multihost_build_hosts = hosts
        sess.conf.multihost_build_claim_ttl_s = 1.0
        sess.conf.multihost_build_poll_s = 0.02
        Hyperspace(sess).create_index(
            sess.read.parquet(src), IndexConfig("bix", ["k"], ["v"]))
        return sess

    def digests(sess) -> Dict[int, List[str]]:
        entry = sess.index_collection_manager.get_index("bix")
        out: Dict[int, List[str]] = {}
        for fi in entry.content.file_infos():
            with open(fi.name, "rb") as fh:
                out.setdefault(bucket_id_of_file(fi.name), []).append(
                    hashlib.sha256(fh.read()).hexdigest())
        return {b: sorted(v) for b, v in out.items()}

    base = build(os.path.join(workdir, f"bix-base-{tag}"), 0)
    want = digests(base)

    killed: Dict[str, Any] = {}
    orig = multihost_build.spawn_hosts

    def spawn_and_kill(conf, build_id, n):
        procs = orig(conf, build_id, n)
        store = multihost_build._store(conf, build_id)

        def reaper():
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if store.list_keys(WorkClaims.PREFIX):
                    break
                time.sleep(0.02)
            p = procs[min(victim, len(procs) - 1)]
            if p.poll() is None:
                try:
                    os.kill(p.pid, signal.SIGKILL)
                except OSError:
                    pass
            killed["pid"] = p.pid

        threading.Thread(target=reaper, daemon=True).start()
        return procs

    multihost_build.spawn_hosts = spawn_and_kill
    try:
        mh = build(os.path.join(workdir, f"bix-mh-{tag}"), 2)
    finally:
        multihost_build.spawn_hosts = orig
    bit_equal = digests(mh) == want
    commits = sum(
        1 for r in lifecycle_journal.records(mh.conf)
        if r.get("decision") == "claim" and r.get("mode") == "commit")
    return {"tag": tag, "victim": victim, "killed": bool(killed),
            "bit_equal": bit_equal, "commits": commits,
            "ok": bool(killed) and bit_equal and commits == 1}


def _alert_drill(session, deadline_s: float = 30.0) -> Dict[str, Any]:
    """The SLO-alert invariant (docs/16): armed wire faults must FIRE
    the availability fast-burn alert with an incident bundle captured,
    and disarming must RESOLVE it.  Runs an in-process server on the
    driver session so the alert engine, the serve counters, and the
    armed ``net.send`` seam all live in one metrics registry; the
    probe client speaks the wire protocol over a RAW socket so the
    armed seam tears only the SERVER's sends, not the probe's."""
    from hyperspace_tpu.interop.server import QueryServer
    from hyperspace_tpu.io import faults
    from hyperspace_tpu.telemetry import alerts as alerts_mod
    from hyperspace_tpu.telemetry import flight_recorder

    out: Dict[str, Any] = {"fired": False, "resolved": False,
                           "bundle_ok": False, "ok": False}
    # Tiny windows so the fast-burn rule decides in drill time, not SRE
    # time; pending/resolve damping of 1 keeps the round-trip short.
    for key, value in (
            ("hyperspace.alerts.enabled", True),
            ("hyperspace.alerts.intervalS", 0.1),
            ("hyperspace.alerts.availabilityTarget", 0.9),
            ("hyperspace.alerts.fastShortS", 0.4),
            ("hyperspace.alerts.fastLongS", 0.8),
            ("hyperspace.alerts.fastFactor", 1.5),
            ("hyperspace.alerts.pendingEvals", 1),
            ("hyperspace.alerts.resolveEvals", 1)):
        session.conf.set(key, value)

    def probe(port: int, read: bool = True,
              timeout_s: float = 1.5) -> None:
        # Fire-and-forget during the fault phase (read=False): the
        # armed seam eats the response anyway, and not blocking on a
        # read that will never come keeps the bad-event rate high.
        sock = socket.create_connection(("127.0.0.1", port),
                                        timeout=timeout_s)
        try:
            sock.sendall(b'{"verb": "metrics"}\n')
            if read:
                sock.recv(65536)
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def state_of(engine, name: str) -> str:
        return engine.current_states().get(name, {}).get("state", "")

    server = QueryServer(session, port=0).start()  # starts the engine
    engine = alerts_mod.engine_for(session)
    port = server.address[1]
    deadline = time.monotonic() + deadline_s
    try:
        # Good traffic first: the burn windows need a baseline.
        settle = time.monotonic() + 0.6
        while time.monotonic() < settle:
            probe(port)
            time.sleep(0.02)
        # Arm the wire fault: every response send black-holes, so each
        # probe lands as a ``serve.send_timeouts`` bad event.
        faults.install(faults.FaultPlan(
            site="net.send", kind="black-hole", at=1, count=10 ** 6,
            hang_s=0.01))
        while (state_of(engine, "availability") != "firing"
               and time.monotonic() < deadline):
            try:
                probe(port, read=False)
            except OSError:
                pass  # the fault eats the answer — that IS the drill
            time.sleep(0.02)
        out["fired"] = state_of(engine, "availability") == "firing"
        # The bundle commits right AFTER the state flips (capture runs
        # outside the engine's state lock), so give it a beat to land.
        bundle_key = ""
        while not bundle_key and time.monotonic() < deadline:
            bundle_key = engine.current_states().get(
                "availability", {}).get("bundle_key") or ""
            if not bundle_key:
                time.sleep(0.05)
        out["bundle_key"] = bundle_key
        faults.clear()
        out["bundle_ok"] = bool(bundle_key) and any(
            b.get("key") == bundle_key and "incident" in b
            for b in flight_recorder.bundles(session.conf))
        # Disarm + good traffic: the alert must come back down.
        while (state_of(engine, "availability") == "firing"
               and time.monotonic() < deadline):
            try:
                probe(port)
            except OSError:
                pass
            time.sleep(0.02)
        out["resolved"] = \
            state_of(engine, "availability") in ("resolved", "")
    finally:
        faults.clear()
        try:
            server.stop()
        except Exception as exc:  # noqa: BLE001 — teardown best-effort,
            out["teardown_error"] = str(exc)  # but visible in the report
        engine.stop()
        session.conf.set("hyperspace.alerts.enabled", False)
    out["ok"] = (out["fired"] and out["bundle_ok"] and out["resolved"])
    return out


class _Fleet:
    """The drill's process harness: spawn/kill/stop/bounce children on
    stable ports over one shared index tree."""

    def __init__(self, system_path: str, servers: int,
                 base_conf: Dict[str, Any]) -> None:
        self.system_path = system_path
        self.base_conf = base_conf
        self.procs: List[Optional[subprocess.Popen]] = [None] * servers
        self.ports: List[int] = [0] * servers
        self.pids: List[int] = [0] * servers

    def spawn(self, i: int, extra_conf: Optional[Dict[str, Any]] = None,
              timeout_s: float = 60.0) -> None:
        conf = dict(self.base_conf)
        if extra_conf:
            conf.update(extra_conf)
        proc = subprocess.Popen(
            [sys.executable, "-c", _CHILD, self.system_path,
             str(self.ports[i]), json.dumps(conf)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(
                f"chaos child {i} failed to start: {proc.stderr.read()}")
        info = json.loads(line)
        self.procs[i] = proc
        self.ports[i] = info["port"]
        self.pids[i] = info["pid"]

    def kill(self, i: int) -> None:
        proc = self.procs[i]
        if proc is not None:
            try:
                os.kill(self.pids[i], signal.SIGKILL)
            except OSError:
                pass
            proc.wait(timeout=30)

    def stop_cont(self, i: int, stop_s: float) -> None:
        try:
            os.kill(self.pids[i], signal.SIGSTOP)
            time.sleep(stop_s)
        finally:
            try:
                os.kill(self.pids[i], signal.SIGCONT)
            except OSError:
                pass

    def endpoints(self) -> List[Tuple[str, int]]:
        return [("127.0.0.1", p) for p in self.ports]

    def teardown(self) -> None:
        for i, proc in enumerate(self.procs):
            if proc is None:
                continue
            try:
                os.kill(self.pids[i], signal.SIGCONT)
            except OSError:
                pass
            try:
                proc.kill()
                proc.wait(timeout=30)
            except OSError:
                pass


def _percentile(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(round(q * (len(ordered) - 1))))
    return ordered[idx]


def run_chaos(seed: int = 0, duration_s: float = 6.0, servers: int = 3,
              workdir: Optional[str] = None, load_threads: int = 2,
              rows: int = 400, deadline_ms: float = 20000.0,
              lease_ttl_s: float = 1.0) -> Dict[str, Any]:
    """Run the drill; returns the report dict (key ``ok`` plus
    ``violations`` naming any invariant that failed — the caller
    decides whether to raise)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from hyperspace_tpu import Hyperspace, HyperspaceSession, IndexConfig
    from hyperspace_tpu.interop import FleetQueryClient
    from hyperspace_tpu.interop import netfaults
    from hyperspace_tpu.io import faults
    from hyperspace_tpu.lifecycle import journal as lifecycle_journal
    from hyperspace_tpu.telemetry import metrics

    owns_workdir = workdir is None
    if owns_workdir:
        workdir = tempfile.mkdtemp(prefix="hs_chaos_")
    data = os.path.join(workdir, "src")
    os.makedirs(data, exist_ok=True)
    n = int(rows)
    pq.write_table(pa.table({
        "k": pa.array(np.arange(n, dtype=np.int64)),
        "v": pa.array(np.arange(n, dtype=np.int64) * 3 + 1),
    }), os.path.join(data, "part-00000000.parquet"))
    # A STABLE snapshot for the kill-build-host drills: the mid-drill
    # append mutates ``data``, and the build drill's byte-equality
    # baseline must see the same files as its 2-host leg.
    bsrc = os.path.join(workdir, "bsrc")
    os.makedirs(bsrc, exist_ok=True)
    # hslint: allow[io-seam] drill-source snapshot copy, not index data
    shutil.copy(os.path.join(data, "part-00000000.parquet"),
                os.path.join(bsrc, "part-00000000.parquet"))
    # The mid-drill append adds keys >= n, so every load-thread answer
    # stays bit-equal across the append: point probes stay below n and
    # the aggregate filters to k < n.  The appended rows exist to make
    # the maintenance refresh contested, not to move the answers.
    expected = {k: 3 * k + 1 for k in range(n)}
    expected_sum = sum(expected.values())

    system_path = os.path.join(workdir, "ix")
    s = HyperspaceSession(system_path=system_path)
    s.conf.num_buckets = 4
    s.conf.set("hyperspace.fleet.telemetry.enabled", True)
    s.conf.set("hyperspace.fleet.telemetry.publishIntervalS", 0.2)
    s.conf.set("hyperspace.lifecycle.lease.enabled", True)
    s.conf.set("hyperspace.lifecycle.lease.ttlS", lease_ttl_s)
    hs = Hyperspace(s)
    hs.create_index(s.read.parquet(data), IndexConfig("cix", ["k"], ["v"]))

    base_conf = {
        "hyperspace.fleet.telemetry.enabled": True,
        "hyperspace.fleet.telemetry.publishIntervalS": 0.2,
        "hyperspace.lifecycle.lease.enabled": True,
        "hyperspace.lifecycle.lease.ttlS": lease_ttl_s,
    }
    schedule = build_schedule(seed, duration_s, servers)
    report: Dict[str, Any] = {"seed": int(seed),
                              "duration_s": float(duration_s),
                              "servers": int(servers),
                              "schedule": schedule}
    c0 = {name: metrics.registry().counter(name) for name in (
        "client.retry", "client.failover", "client.hedge.sent",
        "client.hedge.wins", "client.breaker.open",
        "client.breaker.close", "client.pool.evicted")}

    fleet = _Fleet(system_path, servers, base_conf)
    stop = threading.Event()
    build_drills: List[Dict[str, Any]] = []
    build_state: Dict[str, Any] = {"thread": None, "count": 0,
                                   "skipped": 0}
    stats_lock = threading.Lock()
    stats = {"sent": 0, "answered": 0, "mismatch": 0, "lost": 0}
    clean_lat: List[float] = []
    fault_lat: List[float] = []
    in_fault_phase = threading.Event()

    def point_spec(k: int) -> Dict[str, Any]:
        return {"source": {"format": "parquet", "path": data},
                "filter": {"op": "==", "col": "k", "value": int(k)},
                "select": ["k", "v"]}

    agg_spec = {"source": {"format": "parquet", "path": data},
                "filter": {"op": "<", "col": "k", "value": n},
                "aggs": {"t": ["v", "sum"]}}

    fc = None
    try:
        for i in range(servers):
            fleet.spawn(i)
        # Pay each child's cold first-query cost (plan compile, index
        # open) OUTSIDE the measured windows, per endpoint — otherwise
        # the clean baseline is empty or dominated by warm-up.
        from hyperspace_tpu.interop import QueryClient
        for address in fleet.endpoints():
            warm = QueryClient(address)
            try:
                warm.query(point_spec(0))
                warm.query(agg_spec)
            finally:
                warm.close()
        fc = FleetQueryClient(
            fleet.endpoints(), conf=s.conf,
            max_attempts=max(6, 2 * servers),
            hedge_enabled=True, breaker_enabled=True,
            breaker_failures=3, breaker_cooldown_ms=500.0)

        def load(worker: int) -> None:
            lrng = random.Random(seed * 1000 + worker)
            while not stop.is_set():
                k = lrng.randrange(n)
                mixed = lrng.random() < 0.1
                spec = agg_spec if mixed else point_spec(k)
                t0 = time.monotonic()
                try:
                    table = fc.query(spec, deadline_ms=deadline_ms)
                except Exception:  # noqa: BLE001 — a lost request is
                    with stats_lock:  # the invariant, not a crash
                        stats["sent"] += 1
                        stats["lost"] += 1
                    continue
                elapsed = (time.monotonic() - t0) * 1000.0
                got = table.column("t" if mixed else "v").to_pylist()
                want = [expected_sum] if mixed else [expected[k]]
                with stats_lock:
                    stats["sent"] += 1
                    stats["answered"] += 1
                    if got != want:
                        stats["mismatch"] += 1
                    (fault_lat if in_fault_phase.is_set()
                     else clean_lat).append(elapsed)

        threads = [threading.Thread(target=load, args=(w,), daemon=True)
                   for w in range(load_threads)]
        for t in threads:
            t.start()

        # Clean warm-up: a latency baseline before any fault fires.
        time.sleep(max(0.5, schedule[0]["t"] if schedule else 0.5))
        in_fault_phase.set()
        t_start = time.monotonic()
        for event in schedule:
            delay = event["t"] - (time.monotonic() - t_start)
            if delay > 0:
                time.sleep(delay)
            op = event["op"]
            if op == "append":
                extra = pa.table({
                    "k": pa.array(np.arange(n, n + 50, dtype=np.int64)),
                    "v": pa.array(
                        np.arange(n, n + 50, dtype=np.int64) * 3 + 1),
                })
                # Write-then-rename: a server scanning the source dir
                # mid-append must see the whole file or no file, never
                # a torn parquet footer.
                tmp = os.path.join(workdir, "part-00010000.parquet.tmp")
                pq.write_table(extra, tmp)
                faults.atomic_replace(tmp, os.path.join(
                    data, "part-00010000.parquet"), "data.write")
            elif op == "kill":
                fleet.kill(event["server"])
                time.sleep(event["down_s"])
                fleet.spawn(event["server"])
            elif op == "stop":
                fleet.stop_cont(event["server"], event["stop_s"])
            elif op == "client-fault":
                faults.install(faults.FaultPlan(
                    site=event["site"], kind=event["kind"],
                    at=event["at"], count=event["count"],
                    latency_ms=40.0, hang_s=0.3))
                time.sleep(0.4)
                faults.clear()
            elif op == "kill-build-host":
                prev = build_state["thread"]
                if prev is not None and prev.is_alive():
                    build_state["skipped"] += 1
                else:
                    tag = build_state["count"]
                    build_state["count"] += 1

                    def _drill(tag=tag, victim=event["victim"]):
                        try:
                            build_drills.append(
                                _build_drill(workdir, bsrc, tag, victim))
                        except Exception as exc:  # noqa: BLE001 — a
                            # crashed drill IS the violation, not ours
                            build_drills.append(
                                {"tag": tag, "ok": False,
                                 "error": str(exc)})

                    th = threading.Thread(target=_drill, daemon=True)
                    build_state["thread"] = th
                    th.start()
            elif op == "bounce-armed":
                fleet.kill(event["server"])
                fleet.spawn(event["server"], extra_conf={
                    "hyperspace.system.faultInjection.enabled": True,
                    "hyperspace.system.faultInjection.site":
                        event["site"],
                    "hyperspace.system.faultInjection.kind":
                        event["kind"],
                    "hyperspace.system.faultInjection.at": event["at"],
                    "hyperspace.system.faultInjection.count":
                        event["count"],
                })
        # Let the fleet settle and the last retries land.
        time.sleep(1.0)
        th = build_state["thread"]
        if th is not None:
            th.join(timeout=90.0)
        stop.set()
        for t in threads:
            t.join(timeout=deadline_ms / 1000.0 + 5.0)

        # Drive maintenance to completion from the driver too: the
        # appended data's refresh must land EXACTLY once fleet-wide.
        refresh_done = 0
        deadline = time.monotonic() + lease_ttl_s + 15.0
        while time.monotonic() < deadline:
            try:
                hs.maintenance_cycle()
            except Exception as exc:  # noqa: BLE001 — contested cycles
                # may lose CAS races; the journal decides who executed.
                report["driver_maintenance_error"] = str(exc)
            refresh_done = sum(
                1 for r in lifecycle_journal.records(s.conf)
                if r.get("decision") == "refresh"
                and r.get("outcome") == "done"
                and r.get("index") == "cix")
            if refresh_done:
                break
            time.sleep(0.3)
        report["maintenance_refresh_done"] = refresh_done
    finally:
        stop.set()
        faults.clear()
        netfaults.clear_parked()
        # Gauge before close: close() zeroes open_now (no client, no
        # routing table), and the invariant grades the drill's view.
        open_now = float(
            metrics.snapshot().get("client.breaker.open_now", 0.0) or 0.0)
        if fc is not None:
            fc.close()
        fleet.teardown()

    deltas = {name: metrics.registry().counter(name) - base
              for name, base in c0.items()}
    report.update({
        "sent": stats["sent"], "answered": stats["answered"],
        "lost": stats["lost"], "mismatch": stats["mismatch"],
        "clean_p50_ms": round(_percentile(clean_lat, 0.50), 2),
        "clean_p99_ms": round(_percentile(clean_lat, 0.99), 2),
        "fault_p99_ms": round(_percentile(fault_lat, 0.99), 2),
        "hedge_sent": deltas["client.hedge.sent"],
        "hedge_wins": deltas["client.hedge.wins"],
        "hedge_win_rate": round(
            deltas["client.hedge.wins"]
            / max(1.0, deltas["client.hedge.sent"]), 3),
        "breaker_opens": deltas["client.breaker.open"],
        "breaker_closes": deltas["client.breaker.close"],
        "breaker_open_now": open_now,
        "pool_evicted": deltas["client.pool.evicted"],
        "retries": deltas["client.retry"],
        "failovers": deltas["client.failover"],
        "build_drills": build_drills,
        "build_drills_skipped": build_state["skipped"],
    })
    # SLO-alert invariant, after the fleet is torn down: the driver's
    # own serve counters are untouched by the storm above, so the
    # availability objective grades EXACTLY the drill's armed fault.
    try:
        report["alert_drill"] = _alert_drill(s)
    except Exception as exc:  # noqa: BLE001 — a crashed drill IS the
        report["alert_drill"] = {"ok": False,  # violation, not ours
                                 "error": str(exc)}
    violations: List[str] = []
    if stats["lost"]:
        violations.append(f"{stats['lost']} lost request(s)")
    if stats["mismatch"]:
        violations.append(f"{stats['mismatch']} non-bit-equal answer(s)")
    if stats["sent"] != stats["answered"] + stats["lost"]:
        violations.append("request accounting does not add up")
    if report["maintenance_refresh_done"] != 1:
        violations.append(
            f"maintenance refresh executed "
            f"{report['maintenance_refresh_done']}x (want exactly 1)")
    if deltas["client.hedge.wins"] > deltas["client.hedge.sent"]:
        violations.append("hedge wins exceed hedges sent")
    if deltas["client.failover"] > deltas["client.retry"]:
        violations.append("failovers exceed retries")
    if deltas["client.breaker.close"] > deltas["client.breaker.open"]:
        violations.append("breaker closes exceed opens")
    if not 0 <= open_now <= servers:
        violations.append(
            f"breaker open_now gauge {open_now} outside [0, {servers}]")
    bad_builds = sum(1 for d in build_drills if not d.get("ok"))
    if bad_builds:
        violations.append(
            f"{bad_builds} kill-build-host drill(s) failed "
            f"(non-bit-equal, missing kill, or commits != 1)")
    if not report["alert_drill"].get("ok"):
        ad = report["alert_drill"]
        violations.append(
            "alert drill failed: "
            f"fired={ad.get('fired')} bundle_ok={ad.get('bundle_ok')} "
            f"resolved={ad.get('resolved')} "
            f"error={ad.get('error', '')!r}")
    report["violations"] = violations
    report["ok"] = not violations
    return report


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="Seeded fleet chaos drill (see interop/chaos.py)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--duration", type=float, default=6.0)
    parser.add_argument("--servers", type=int, default=3)
    parser.add_argument("--threads", type=int, default=2)
    parser.add_argument("--schedule-only", action="store_true",
                        help="print the deterministic schedule and exit")
    args = parser.parse_args(argv)
    if args.schedule_only:
        print(json.dumps(build_schedule(
            args.seed, args.duration, args.servers), indent=2))
        return 0
    report = run_chaos(seed=args.seed, duration_s=args.duration,
                       servers=args.servers, load_threads=args.threads)
    print(json.dumps(report, indent=2, default=str))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
