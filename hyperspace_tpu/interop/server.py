"""Arrow-IPC query server: one JSON request line in, one IPC stream out.

Wire protocol (deliberately minimal so any language can speak it with a
socket plus an Arrow library — no HTTP/gRPC dependency):

  client -> server   one JSON object (the interop/query.py spec),
                     UTF-8, terminated by a newline; may carry a
                     client-minted trace context (``trace_id`` /
                     ``request_id``, 16 hex chars each) the server
                     adopts — malformed ids are replaced by
                     server-minted ones, never rejected
  server -> client   the status line ``OK trace=<trace_id>\\n`` followed
                     by an Arrow IPC STREAM of the result
                     (self-delimiting), or
                     ``ERR <CODE> <message> trace=<trace_id>\\n`` and
                     the connection closes — every response echoes the
                     adopted/minted trace id, so a failure is
                     correlatable from either side (the flight
                     recorder's ``slow_queries``/``trace`` verbs answer
                     for it afterwards)

Error codes split RETRYABLE conditions from permanent ones:

  ``BUSY``      retryable — the server shed the request (admission queue
                full, connection capacity, overload watermark, draining)
  ``DEADLINE``  retryable — the request's deadline expired before the
                result was ready
  ``BADREQ``    permanent — the request itself is malformed
  ``FAILED``    permanent — the engine failed executing a valid request

Pre-taxonomy servers sent bare ``ERR <message>``; :func:`parse_wire_error`
(used by :class:`QueryClient`) still accepts that form, mapping it to
``FAILED``.

Connections are PIPELINED: after a successful response the client may send
the next request on the same connection (an error closes it, keeping
framing unambiguous).  Execution is ADMISSION-CONTROLLED (ROADMAP item 2):
socket IO runs on per-connection threads (bounded by
``hyperspace.serving.maxConnections`` — beyond it the accept loop answers
``ERR BUSY`` without spawning a thread), while query execution runs on a
fixed pool of ``hyperspace.serving.workers`` threads fed by a bounded
admission queue (``hyperspace.serving.queueDepth``).  When the queue is
full — or the process is past a memory/queue-wait watermark — new
requests shed FAST with ``ERR BUSY`` instead of piling onto a saturated
server: under overload the answer degrades to "retry later", never to a
hang, a thread leak, or a torn frame (only the connection's own handler
thread ever writes to its socket, one complete response per request).

Per-request deadlines (spec key ``deadline_ms``, or the conf default
``hyperspace.serving.defaultDeadlineMs``) propagate into
``dataset.collect`` via utils/deadline.py and abort cleanly at executor
phase boundaries; expiry surfaces as ``ERR DEADLINE``.  Repeat queries
skip the optimizer via the plan cache (execution/plan_cache.py), keyed
by the advisor's structural fingerprint + literal digest.  ``drain()``
(or SIGTERM with ``handle_sigterm=True``) stops accepting, finishes
in-flight requests within ``hyperspace.serving.drainGraceS``, then
closes.

The server executes against ONE session, so enabled indexes and conf
govern rewrites exactly as for local use — this is the parity surface for
the reference's py4j bindings / .NET sample
(python/hyperspace/hyperspace.py:9, examples/csharp/Program.cs): a JVM or
.NET client sends the JSON spec and reads the stream with its own Arrow
implementation.
"""

from __future__ import annotations

import json
import os
import queue
import random
import socket
import socketserver
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import pyarrow as pa

from hyperspace_tpu.interop import netfaults

MAX_REQUEST_BYTES = 1 << 20  # a query spec, not a data upload


REQUEST_TIMEOUT_S = 30.0  # an idle connection must not pin a thread + fd

# -- wire error taxonomy ------------------------------------------------------
ERR_BUSY = "BUSY"
ERR_DEADLINE = "DEADLINE"
ERR_BADREQ = "BADREQ"
ERR_FAILED = "FAILED"
KNOWN_WIRE_CODES = (ERR_BUSY, ERR_DEADLINE, ERR_BADREQ, ERR_FAILED)
RETRYABLE_WIRE_CODES = frozenset({ERR_BUSY, ERR_DEADLINE})


class WireError(Exception):
    """Server-side: an error with an explicit wire code (the handler maps
    everything else through :func:`_classify_error`).  ``retry_after_ms``
    rides BUSY sheds as a ``retry-after-ms=<n>`` token on the status
    line — the server's backoff hint, derived from the queue-wait
    EWMA."""

    def __init__(self, code: str, message: str,
                 retry_after_ms: Optional[int] = None) -> None:
        super().__init__(message)
        self.code = code
        self.message = message
        self.retry_after_ms = retry_after_ms


class QueryFailedError(RuntimeError):
    """Client-side: the server answered ``ERR ...``.  ``code`` is one of
    ``BUSY``/``DEADLINE``/``BADREQ``/``FAILED`` (bare pre-taxonomy errors
    map to ``FAILED``); ``retryable`` is True for overload/deadline sheds
    — back off and retry on a FRESH connection (errors close the one they
    arrived on).  ``trace_id`` is the server-echoed trace context (None
    against a pre-trace server): quote it to ``slow_queries()`` / the
    ``trace`` verb to pull the request's full flight record."""

    def __init__(self, code: str, message: str, payload: str,
                 trace_id: Optional[str] = None,
                 retry_after_ms: Optional[int] = None) -> None:
        super().__init__(f"Query failed: {payload}")
        self.code = code
        self.message = message
        self.trace_id = trace_id
        #: Server backoff hint from a ``retry-after-ms=<n>`` status-line
        #: token (BUSY sheds; None against a pre-hint server).
        self.retry_after_ms = retry_after_ms

    @property
    def retryable(self) -> bool:
        return self.code in RETRYABLE_WIRE_CODES


class ServerBusyError(QueryFailedError):
    """The server shed this request (``ERR BUSY``): overload, not a bug.
    Retry with backoff on a new connection — ``retry_after_ms`` is the
    server's suggested wait, derived from its recent queue-wait EWMA
    (None when the server predates the hint)."""


_TRACE_ECHO_RE = None  # compiled lazily; interop/query.py owns the format


def _split_trace_echo(text: str) -> Tuple[str, Optional[str]]:
    """Strip a trailing ``trace=<16 hex>`` token (the server's trace-id
    echo) off a status line, returning ``(rest, trace_id-or-None)``."""
    global _TRACE_ECHO_RE
    if _TRACE_ECHO_RE is None:
        import re

        _TRACE_ECHO_RE = re.compile(r"^(.*?)\s*\btrace=([0-9a-f]{16})\s*$")
    m = _TRACE_ECHO_RE.match(text)
    if m is None:
        return text, None
    return m.group(1), m.group(2)


_RETRY_AFTER_RE = None  # compiled lazily, like the trace echo


def _split_retry_after(text: str) -> Tuple[str, Optional[int]]:
    """Strip a trailing ``retry-after-ms=<n>`` token (the BUSY backoff
    hint) off a status line, returning ``(rest, ms-or-None)``."""
    global _RETRY_AFTER_RE
    if _RETRY_AFTER_RE is None:
        import re

        _RETRY_AFTER_RE = re.compile(
            r"^(.*?)\s*\bretry-after-ms=(\d+)\s*$")
    m = _RETRY_AFTER_RE.match(text)
    if m is None:
        return text, None
    return m.group(1), int(m.group(2))


def parse_wire_error(line: str) -> QueryFailedError:
    """An ``ERR ...`` status line → the typed client error.  Accepts both
    the coded form (``ERR BUSY queue full``) and the pre-taxonomy bare
    form (``ERR something broke`` → code FAILED), so a new client keeps
    working against an old server; a trailing ``trace=<id>`` echo and a
    ``retry-after-ms=<n>`` hint are lifted into ``.trace_id`` /
    ``.retry_after_ms`` either way (old bare ``ERR BUSY`` lines still
    parse, with both None)."""
    payload = line[4:] if line.startswith("ERR ") else line
    stripped, trace_id = _split_trace_echo(payload)
    stripped, retry_after_ms = _split_retry_after(stripped)
    code, _, rest = stripped.partition(" ")
    if code in KNOWN_WIRE_CODES and rest:
        cls = ServerBusyError if code == ERR_BUSY else QueryFailedError
        return cls(code, rest, payload, trace_id, retry_after_ms)
    return QueryFailedError(ERR_FAILED, stripped, payload, trace_id,
                            retry_after_ms)


def _classify_error(exc: BaseException) -> Tuple[str, str]:
    """(wire code, message) for an exception crossing the wire boundary."""
    from hyperspace_tpu.exceptions import DeadlineExceededError

    if isinstance(exc, WireError):
        return exc.code, exc.message
    if isinstance(exc, QueryFailedError):
        # A proxied upstream error keeps its code across this hop —
        # BUSY stays retryable (and keeps its retry-after hint) through
        # the front door instead of degrading to permanent FAILED.
        return exc.code, exc.message
    if isinstance(exc, DeadlineExceededError):
        return ERR_DEADLINE, str(exc)
    if isinstance(exc, ValueError):
        # The spec decoders (interop/query.py, the SQL front end) raise
        # ValueError for malformed requests — the client's fault.
        return ERR_BADREQ, str(exc)
    return ERR_FAILED, f"{type(exc).__name__}: {exc}"


def _current_rss_mb() -> float:
    """CURRENT resident set in MB (Linux /proc; falls back to the POSIX
    peak, which can only over-shed — the conservative failure mode for an
    overload watermark)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE") / float(1 << 20)
    except Exception:  # noqa: BLE001 — non-Linux
        try:
            import resource

            return resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss / 1024.0
        except Exception:  # noqa: BLE001
            return 0.0


# -- the bounded worker pool --------------------------------------------------
class _Job:
    """One admitted request: the execute closure plus its rendezvous.
    Workers compute; the connection's handler thread does ALL socket IO —
    that single-writer discipline is what makes torn frames impossible."""

    __slots__ = ("fn", "kind", "deadline_at", "enqueued_t", "done",
                 "result", "error", "report", "abandoned",
                 "trace_id", "request_id", "root_span", "queue_wait_ms",
                 "tenant")

    def __init__(self, fn: Callable[[], pa.Table], kind: str,
                 deadline_at: Optional[float], trace_id: str = "",
                 request_id: str = "", tenant: str = "") -> None:
        self.fn = fn
        self.kind = kind
        self.tenant = tenant  # wire tenant id ("" = untagged)
        self.deadline_at = deadline_at  # absolute time.monotonic(), or None
        self.enqueued_t = time.monotonic()
        self.done = threading.Event()
        self.result: Optional[pa.Table] = None
        self.error: Optional[BaseException] = None
        self.report = None  # the query's run report, for the verb surface
        self.abandoned = False  # handler gave up waiting; discard result
        self.trace_id = trace_id      # wire trace context (adopted or
        self.request_id = request_id  # minted by the handler)
        self.root_span = None    # the serve.request Span when tracing on
        self.queue_wait_ms: Optional[float] = None


class _WorkerPool:
    """Fixed worker threads over a bounded admission queue — the hard cap
    on concurrent query execution, and the seam every shed decision goes
    through."""

    _EWMA_ALPHA = 0.2

    def __init__(self, session, workers: int, queue_depth: int) -> None:
        self._session = session
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, queue_depth))
        self._threads: list = []
        self._stop_sentinel = object()
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._active = 0  # jobs executing right now
        self._queued_or_active = 0  # admitted and not yet finished
        # Requests whose RESPONSE is not yet fully written.  Workers only
        # compute; the connection handler streams the result afterwards —
        # drain() must wait for that write too, or a SIGTERM between
        # "worker done" and "stream flushed" tears the frame mid-send.
        self._open_requests = 0
        self._queue_wait_ewma_ms = 0.0
        self._rss_at = 0.0
        self._rss_mb = 0.0
        # tenant id -> queued-or-active count, for the per-tenant quota
        # (``hyperspace.serving.tenant.maxQueued``): a hot tenant sheds
        # against ITS count while everyone else keeps being admitted.
        self._tenant_queued: Dict[str, int] = {}
        self.draining = False
        self.workers = max(1, int(workers))

    def start(self) -> None:
        for i in range(self.workers):
            t = threading.Thread(target=self._run,
                                 name=f"hs-serve-worker-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    # -- admission ---------------------------------------------------------
    def retry_after_hint_ms(self) -> int:
        """The backoff a shed client should take before retrying: about
        one recent queue wait (the EWMA the latency watermark also
        reads), floored at 100 ms so an idle-queue shed (drain,
        connection cap) still suggests a real pause, capped at 30 s."""
        with self._lock:
            ewma = self._queue_wait_ewma_ms
        return int(max(100.0, min(30_000.0, ewma * 2.0)))

    def _shed(self, reason: str, message: str) -> None:
        from hyperspace_tpu.telemetry import metrics

        metrics.inc("serve.shed")
        metrics.inc(f"serve.shed.{reason}")
        raise WireError(ERR_BUSY, message,
                        retry_after_ms=self.retry_after_hint_ms())

    def submit(self, job: _Job, conf) -> None:
        """Admit ``job`` or shed it with a retryable ``ERR BUSY``."""
        from hyperspace_tpu.telemetry import metrics

        if self.draining:
            self._shed("draining", "server is draining; retry elsewhere")
        rss_mark = float(getattr(conf, "serving_shed_rss_watermark_mb", 0.0))
        if rss_mark > 0:
            now = time.monotonic()
            if now - self._rss_at > 0.2:  # memoize: a stat per ~5 admits
                self._rss_mb = _current_rss_mb()
                self._rss_at = now
            if self._rss_mb > rss_mark:
                self._shed("memory",
                           f"memory watermark: rss {self._rss_mb:.0f} MB > "
                           f"{rss_mark:.0f} MB; retry later")
        wait_mark = float(getattr(conf,
                                  "serving_shed_queue_wait_watermark_ms",
                                  0.0))
        if wait_mark > 0 and self._queue_wait_ewma_ms > wait_mark \
                and self._queue.qsize() > 0:
            self._shed("latency",
                       f"queue-wait watermark: recent wait "
                       f"{self._queue_wait_ewma_ms:.0f} ms > "
                       f"{wait_mark:.0f} ms; retry later")
        # Count BEFORE enqueueing: a worker can finish the job before this
        # thread resumes, and wait_idle must never observe a transient
        # zero while work is genuinely in flight.  The per-tenant quota
        # rides the same critical section so a tenant's count and the
        # global count can never disagree.
        quota = int(getattr(conf, "serving_tenant_max_queued", 0))
        tenant_over = False
        with self._lock:
            if quota > 0 and job.tenant and \
                    self._tenant_queued.get(job.tenant, 0) >= quota:
                tenant_over = True
            else:
                self._queued_or_active += 1
                if job.tenant:
                    self._tenant_queued[job.tenant] = \
                        self._tenant_queued.get(job.tenant, 0) + 1
        if tenant_over:
            metrics.inc(f"serve.tenant.{job.tenant}.shed")
            self._shed("tenant",
                       f"tenant {job.tenant!r} is at its queued quota "
                       f"({quota}); retry later")
        if job.tenant:
            metrics.set_gauge(f"serve.tenant.{job.tenant}.queued",
                              self._tenant_queued.get(job.tenant, 0))
        try:
            self._queue.put_nowait(job)
        except queue.Full:
            with self._idle:
                self._queued_or_active -= 1
                self._release_tenant(job)
                self._idle.notify_all()
            self._shed("queue_full",
                       f"admission queue full "
                       f"(depth {self._queue.maxsize}); retry later")
        metrics.inc("serve.admitted")
        metrics.set_gauge("serve.queue_depth", self._queue.qsize())

    def _release_tenant(self, job: _Job) -> None:
        """Drop one from the job's tenant count (caller holds the lock)."""
        if not job.tenant:
            return
        n = self._tenant_queued.get(job.tenant, 1) - 1
        if n <= 0:
            # hslint: allow[lock-discipline] caller holds self._idle/_lock
            self._tenant_queued.pop(job.tenant, None)
        else:
            # hslint: allow[lock-discipline] caller holds self._idle/_lock
            self._tenant_queued[job.tenant] = n

    def tenant_snapshot(self) -> Dict[str, int]:
        """tenant id -> queued-or-active right now (the ``tenants``
        verb's live column)."""
        with self._lock:
            return dict(self._tenant_queued)

    # -- workers -----------------------------------------------------------
    def _run(self) -> None:
        from hyperspace_tpu.exceptions import DeadlineExceededError
        from hyperspace_tpu.telemetry import metrics
        from hyperspace_tpu.telemetry import trace
        from hyperspace_tpu.utils import deadline as _deadline

        while True:
            item = self._queue.get()
            if item is self._stop_sentinel:
                return
            job: _Job = item
            now = time.monotonic()
            wait_ms = (now - job.enqueued_t) * 1000.0
            job.queue_wait_ms = wait_ms
            metrics.observe("serve.queue_wait_ms", wait_ms)
            metrics.set_gauge("serve.queue_depth", self._queue.qsize())
            with self._lock:
                # The EWMA is a read-modify-write shared across workers;
                # unlocked, two workers interleaving lose updates and the
                # latency watermark sheds on stale numbers.
                self._queue_wait_ewma_ms += self._EWMA_ALPHA * (
                    wait_ms - self._queue_wait_ewma_ms)
                self._active += 1
                metrics.set_gauge("serve.inflight", self._active)
            try:
                if job.abandoned:
                    pass  # handler already answered; don't spend compute
                elif job.deadline_at is not None and now > job.deadline_at:
                    # Expired while QUEUED: zero execution spent on it.
                    job.error = DeadlineExceededError(
                        f"deadline expired after {wait_ms:.0f} ms in the "
                        f"admission queue")
                else:
                    budget = None if job.deadline_at is None \
                        else job.deadline_at - time.monotonic()
                    # This worker's report thread-local could still hold
                    # a PREVIOUS request's report; clear it so a query
                    # that dies before collect() cannot be flight-
                    # recorded against a stale report.
                    self._session.last_run_report_value = None
                    try:
                        # The wire trace context rides the worker's
                        # context: collect() sees a served request, and
                        # the root span carries the ids to the sinks.
                        with trace.request_scope(job.trace_id,
                                                 job.request_id):
                            with trace.span(
                                    "serve.request", kind=job.kind,
                                    trace_id=job.trace_id,
                                    request_id=job.request_id) as sp:
                                if isinstance(sp, trace.Span):
                                    job.root_span = sp
                                with _deadline.scope(budget):
                                    job.result = job.fn()
                                sp.set(queue_wait_ms=round(wait_ms, 1))
                    finally:
                        # The run report lands in this WORKER's
                        # thread-local (success OR failure — the flight
                        # recorder wants the failed query's report too);
                        # hand it to the connection so the
                        # last_run_report verb keeps its
                        # query-then-ask-same-connection contract.
                        job.report = self._session.last_run_report_value
            except BaseException as e:  # noqa: BLE001 — a worker must
                # survive anything a query can throw; the error crosses
                # the wire instead (the handler classifies it).
                job.error = e
            finally:
                # Flight-record BEFORE done.set(): the job's span tree /
                # report are final here, and recording first means a
                # record exists by the time the handler can answer — no
                # live-Span serialization race, no torn record.  The
                # worker owns every ADMITTED job's record (including
                # abandoned ones, whose handler answered DEADLINE long
                # before this abort landed); the handler records only
                # requests that never reached a worker (sheds, BADREQ).
                self._record_flight(job)
                job.done.set()
                with self._idle:
                    self._active -= 1
                    self._queued_or_active -= 1
                    self._release_tenant(job)
                    tenant_left = self._tenant_queued.get(job.tenant, 0) \
                        if job.tenant else 0
                    metrics.set_gauge("serve.inflight", self._active)
                    self._idle.notify_all()
                if job.tenant:
                    metrics.set_gauge(f"serve.tenant.{job.tenant}.queued",
                                      tenant_left)

    def _record_flight(self, job: _Job) -> None:
        """One completed job → one flight-recorder offer (+ the latency
        histogram's exemplar link when the record was retained)."""
        from hyperspace_tpu.telemetry import flight_recorder, metrics

        if job.abandoned:
            # The CLIENT saw ERR DEADLINE regardless of what the aborted
            # execution eventually produced — record what was answered.
            outcome = ERR_DEADLINE
            error = ("abandoned: deadline passed before the result was "
                     "ready")
        elif job.error is not None:
            outcome, raw = _classify_error(job.error)
            error = str(raw).replace("\n", " ")[:500]
        else:
            outcome, error = "OK", ""
        latency_ms = (time.monotonic() - job.enqueued_t) * 1000.0
        retained = flight_recorder.record(
            self._session.conf, kind=job.kind, outcome=outcome,
            latency_ms=latency_ms, trace_id=job.trace_id,
            request_id=job.request_id, queue_wait_ms=job.queue_wait_ms,
            error=error, span=job.root_span, report=job.report)
        if not job.abandoned and job.error is None:
            metrics.observe("serve.latency_ms", latency_ms,
                            exemplar=job.trace_id if retained else None)

    # -- request accounting (handler threads) -------------------------------
    def request_started(self) -> None:
        with self._idle:
            self._open_requests += 1

    def request_finished(self) -> None:
        with self._idle:
            self._open_requests -= 1
            self._idle.notify_all()

    # -- lifecycle ---------------------------------------------------------
    def wait_idle(self, grace_s: float) -> bool:
        """Block until every admitted job finished AND every in-flight
        response is fully written, or ``grace_s`` passed.  Returns True
        when the pool drained clean."""
        deadline_at = time.monotonic() + max(0.0, grace_s)
        with self._idle:
            while self._queued_or_active > 0 or self._open_requests > 0:
                left = deadline_at - time.monotonic()
                if left <= 0:
                    return False
                self._idle.wait(left)
        return True

    def stop(self, timeout_s: float = 5.0) -> None:
        for _ in self._threads:
            self._queue.put(self._stop_sentinel)
        for t in self._threads:
            t.join(timeout=timeout_s)
        self._threads.clear()


# -- the connection handler ---------------------------------------------------
class _Responder:
    """The request→response engine shared by BOTH accept paths — the
    threaded per-connection handler and the async event loop's
    dispatchers: parse, verb-or-admit, stream the answer, classify
    errors.  Subclasses provide ``server`` (the inner server state:
    session / pool / plan_cache / proxy_client), ``connection`` (the
    socket) and ``wfile`` (a buffered binary writer); everything else —
    including the per-connection ``last_run_report`` contract — lives
    here, which is what keeps the two io modes bit-equal on the
    wire."""

    server: Any = None
    connection: Any = None
    wfile: Any = None

    def _init_responder(self) -> None:
        # The most recent run report of a query served on THIS connection
        # (queries execute on pool workers, so the session's thread-local
        # cannot answer the last_run_report verb anymore).
        self._last_report = None
        # The currently admitted job (None between requests / before
        # admission): the error path uses it to tell "a worker owns this
        # request's flight record" from "record it here".
        self._cur_job = None

    def _respond_one(self, line: bytes, conf) -> bool:
        from hyperspace_tpu.interop.query import (
            mint_trace_id,
            pop_trace_context,
        )
        from hyperspace_tpu.telemetry import flight_recorder, metrics

        t0 = time.monotonic()
        trace_id: Optional[str] = None
        request_id: Optional[str] = None
        kind = "unknown"
        is_verb = False
        self._cur_job = None  # the admitted job, for the error path
        try:
            spec = self._parse(line)
            # Adopt the client's trace context — or mint one for a
            # missing/malformed id (a bad trace id must never reject the
            # request).  Every response echoes the id, so the client can
            # quote it to slow_queries()/the trace verb afterwards.
            trace_id, request_id, adopted = pop_trace_context(spec)
            if adopted:
                metrics.inc("serve.trace.adopted")
            else:
                metrics.inc("serve.trace.minted")
            # The session-scoped tenant id rides every request as a spec
            # key; popped here so neither verbs nor the query decoders
            # ever see it.  Quota enforcement happens at admission.
            tenant = spec.pop("tenant", "")
            if tenant is None:
                tenant = ""
            if not isinstance(tenant, str):
                raise WireError(ERR_BADREQ, '"tenant" must be a string')
            is_verb = "verb" in spec
            if is_verb:
                # Observability verbs answer INLINE on the connection
                # thread: they read process state, never the executor, and
                # must keep working while the admission queue is slammed —
                # an operator debugging an overload needs `metrics` most
                # exactly then.
                table = _serve_verb(self.server.session, spec,
                                    self._last_report,
                                    pool=self.server.pool)
            else:
                kind = "sql" if "sql" in spec else "spec"
                table = self._execute_admitted(spec, conf, trace_id,
                                               request_id, tenant)
        except Exception as exc:  # -> coded wire error, connection closes
            if trace_id is None:
                trace_id, request_id = mint_trace_id(), mint_trace_id()
                metrics.inc("serve.trace.minted")
            code, raw = _classify_error(exc)
            msg = str(raw).replace("\n", " ")[:500]
            metrics.inc("serve.errors")
            metrics.inc(f"serve.err.{code.lower()}")
            if code == ERR_DEADLINE:
                metrics.inc("serve.deadline.expired")
            if not is_verb and self._cur_job is None:
                # Sheds and malformed requests never reach a worker, so
                # the handler is the only place that can record them.
                # Admitted jobs (incl. abandoned deadline expiries) are
                # recorded by their worker, with the span tree/report.
                flight_recorder.record(
                    conf, kind=kind, outcome=code,
                    latency_ms=(time.monotonic() - t0) * 1000.0,
                    trace_id=trace_id, request_id=request_id, error=msg)
            retry_ms = getattr(exc, "retry_after_ms", None)
            hint = f" retry-after-ms={int(retry_ms)}" \
                if retry_ms is not None else ""
            try:
                self.connection.settimeout(
                    float(conf.serving_send_timeout_s))
                self.wfile.write(
                    f"ERR {code} {msg}{hint} trace={trace_id}\n"
                    .encode("utf-8"))
            except OSError:
                pass
            return False
        # The send side gets its OWN timeout: REQUEST_TIMEOUT_S historically
        # guarded only the read, so a dead client that stopped READING
        # mid-Arrow-stream pinned its thread on a full send buffer forever.
        try:
            self.connection.settimeout(float(conf.serving_send_timeout_s))
            if netfaults.armed():
                # Wire-fault detour: materialize the whole frame so the
                # net.send seam can tear it at an exact byte boundary.
                # Gated on an armed net plan — the zero-fault hot path
                # never pays the extra copy.
                import io as _io

                buf = _io.BytesIO()
                buf.write(f"OK trace={trace_id}\n".encode("utf-8"))
                with pa.ipc.new_stream(buf, table.schema) as writer:
                    writer.write_table(table)
                netfaults.send_all(self.connection, buf.getvalue())
            else:
                self.wfile.write(f"OK trace={trace_id}\n".encode("utf-8"))
                with pa.ipc.new_stream(self.wfile, table.schema) as writer:
                    writer.write_table(table)
                self.wfile.flush()
            metrics.inc("serve.ok")
            return True
        except TimeoutError:
            metrics.inc("serve.send_timeouts")
            return False  # dead reader: free the thread, drop the socket
        except OSError:
            return False  # client hung up mid-response

    def _parse(self, line: bytes) -> Dict[str, Any]:
        if len(line) > MAX_REQUEST_BYTES or not line.endswith(b"\n"):
            raise WireError(
                ERR_BADREQ,
                f"request exceeds {MAX_REQUEST_BYTES} bytes or is not "
                f"newline-terminated")
        try:
            spec = json.loads(line.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise WireError(ERR_BADREQ, f"request is not JSON: {e}")
        if not isinstance(spec, dict):
            # A bare JSON string/array is valid JSON — and `"sql" in
            # spec` on a string would substring-match.
            raise WireError(ERR_BADREQ, "request must be a JSON object")
        return spec

    def _execute_admitted(self, spec: Dict[str, Any], conf,
                          trace_id: str, request_id: str,
                          tenant: str = "") -> pa.Table:
        from hyperspace_tpu.exceptions import DeadlineExceededError

        deadline_ms = spec.pop("deadline_ms", None)
        if deadline_ms is None:
            default_ms = float(conf.serving_default_deadline_ms or 0.0)
            deadline_ms = default_ms if default_ms > 0 else None
        elif not isinstance(deadline_ms, (int, float)) or \
                isinstance(deadline_ms, bool) or deadline_ms <= 0:
            raise WireError(ERR_BADREQ,
                            f'"deadline_ms" must be a positive number, '
                            f'got {deadline_ms!r}')
        deadline_at = None if deadline_ms is None \
            else time.monotonic() + float(deadline_ms) / 1000.0
        fn, kind = self._make_query_fn(spec)
        job = _Job(fn, kind, deadline_at, trace_id=trace_id,
                   request_id=request_id, tenant=tenant)
        self.server.pool.submit(job, conf)  # raises WireError(BUSY) = shed
        self._cur_job = job  # admitted: its worker owns the flight record
        if deadline_at is None:
            job.done.wait()
        else:
            left = max(0.0, deadline_at - time.monotonic())
            if not job.done.wait(left):
                # The deadline is a RESPONSE contract, enforced here even
                # when the worker is mid-phase: answer DEADLINE the moment
                # it passes — the deadline contextvar aborts the work at
                # its next phase boundary, and the abandoned flag discards
                # the orphan result (and skips the job entirely if it was
                # still queued).
                job.abandoned = True
                raise DeadlineExceededError(
                    "deadline exceeded before the result was ready (the "
                    "query aborts at its next phase boundary)")
        if job.error is not None:
            raise job.error
        if job.report is not None:
            self._last_report = job.report
        return job.result

    def _make_query_fn(self, spec: Dict[str, Any]):
        """Validate the request SHAPE on the connection thread (BADREQ
        without consuming a queue slot), return the execute closure the
        worker runs."""
        session = self.server.session
        plan_cache = self.server.plan_cache
        proxy = getattr(self.server, "proxy_client", None)
        if proxy is not None:
            # Proxy mode: this server is a FRONT DOOR for non-Python
            # clients — queries forward through the fleet client (load
            # routing, failover, retry-after backoff) while verbs keep
            # answering locally.  Shape validation is the backend's job;
            # its coded errors come back as-is (_classify_error keeps
            # the upstream code, so BUSY stays retryable end-to-end).
            forward = dict(spec)

            def run_proxy() -> pa.Table:
                return proxy.query(forward)

            return run_proxy, ("sql" if "sql" in spec else "spec")
        if "sql" in spec:
            # {"sql": "SELECT ...", "tables": {name: parquet_dir}} —
            # SQL text over the wire, the reference corpus's native
            # form (goldstandard/PlanStabilitySuite.scala:81-283).
            if not isinstance(spec["sql"], str):
                raise WireError(ERR_BADREQ, '"sql" must be a string')
            tables = spec.get("tables", {})
            if not isinstance(tables, dict) or not all(
                    isinstance(v, str) for v in tables.values()):
                raise WireError(
                    ERR_BADREQ,
                    '"tables" must map names to parquet directory paths '
                    'over the wire')

            def run() -> pa.Table:
                from hyperspace_tpu.sql import sql as run_sql

                ds = run_sql(session, spec["sql"], tables=tables)
                return ds.collect(plan_cache=plan_cache)

            return run, "sql"

        def run_spec() -> pa.Table:
            from hyperspace_tpu.interop.query import dataset_from_spec

            return dataset_from_spec(session, spec).collect(
                plan_cache=plan_cache)

        return run_spec, "spec"


class _Handler(_Responder, socketserver.StreamRequestHandler):
    """The THREADED accept path's per-connection shell: blocking reads
    with the idle timeout, one handler thread per connection."""

    timeout = REQUEST_TIMEOUT_S  # initial value; per-phase settimeout below

    def setup(self) -> None:
        super().setup()
        self._init_responder()

    def handle(self) -> None:
        # Pipelined: serve requests until EOF, idle timeout, or an error
        # response (errors close the connection so framing stays
        # unambiguous for simple clients).
        while self._serve_one():
            pass

    def _serve_one(self) -> bool:
        from hyperspace_tpu.telemetry import metrics

        conf = self.server.session.conf
        try:
            self.connection.settimeout(
                float(conf.serving_request_timeout_s))
            line = self.rfile.readline(MAX_REQUEST_BYTES + 1)
        except (TimeoutError, OSError):
            return False
        if not line:
            return False  # clean EOF between requests
        metrics.inc("serve.requests")
        # The request is in flight from here until its response is fully
        # written: drain()'s wait_idle blocks on this accounting, so a
        # SIGTERM mid-stream cannot exit the process between the worker
        # finishing a result and this thread flushing it (torn frame).
        pool = self.server.pool
        pool.request_started()
        try:
            return self._respond_one(line, conf)
        finally:
            pool.request_finished()


class _AsyncResponder(_Responder):
    """One async connection's responder: same engine, socket-backed
    writer, reused across the connection's pipelined requests (the
    ``last_run_report`` contract is per connection)."""

    def __init__(self, server, sock: socket.socket) -> None:
        self.server = server
        self.connection = sock
        self.wfile = sock.makefile("wb")
        self._init_responder()


class _AsyncConn:
    """Selector-side state of one async connection."""

    __slots__ = ("sock", "buf", "responder")

    def __init__(self, server, sock: socket.socket) -> None:
        self.sock = sock
        self.buf = b""
        self.responder = _AsyncResponder(server, sock)


def _reject_connection(server, request: socket.socket) -> None:
    """Answer ``ERR BUSY`` to a connection past the cap and close it —
    shared by both accept paths, always bounded (1 s send timeout)."""
    from hyperspace_tpu.interop.query import mint_trace_id
    from hyperspace_tpu.telemetry import flight_recorder, metrics

    metrics.inc("serve.shed")
    metrics.inc("serve.shed.connections")
    # No request line was read, so there is no client trace context to
    # adopt — record the shed under minted ids so the tail still shows
    # it happened.
    flight_recorder.record(
        server.session.conf, kind="unknown", outcome=ERR_BUSY,
        latency_ms=0.0, trace_id=mint_trace_id(),
        request_id=mint_trace_id(), error="connection capacity reached")
    hint = server.pool.retry_after_hint_ms()
    try:
        request.settimeout(1.0)
        request.sendall(
            f"ERR {ERR_BUSY} connection capacity reached; "
            f"retry later retry-after-ms={hint}\n".encode("utf-8"))
    except OSError:
        pass


class _AsyncIOLoop:
    """The selector accept path (``hyperspace.serving.ioMode=async``):
    ONE event-loop thread owns accept plus request reads for EVERY
    connection, so thousands of mostly-idle sockets cost one thread
    instead of one each.  Complete request lines hand off to a small
    dispatcher pool that runs the SAME :class:`_Responder` engine as the
    threaded path — admission, verbs, deadlines, and error taxonomy are
    shared code, which is what makes the two io modes bit-equal on the
    wire.

    Single-writer discipline, async flavor: while a response is in
    flight its socket is UNREGISTERED from the selector — the
    dispatcher is the connection's only writer, and the loop never
    reads ahead of an unfinished response, so pipelining stays ordered
    and frames cannot tear.  Finished connections return through the
    requeue + wakeup pipe (the loop thread owns all selector state).

    The event loop itself must never block: hslint's
    blocking-discipline rule covers ``_event_loop`` / ``_on_accept`` /
    ``_on_readable`` / ``_on_wakeup`` exactly like the threaded accept
    loop, so a store read or a sleep slipping in fails the lint, not
    production."""

    def __init__(self, outer: "QueryServer", server) -> None:
        import selectors

        self._outer = outer
        self._server = server
        self._sel = selectors.DefaultSelector()
        self._listener: socket.socket = server.socket
        self._ready: "queue.Queue" = queue.Queue()
        self._requeue: "queue.Queue" = queue.Queue()
        self._wake_r, self._wake_w = socket.socketpair()
        self._stop = threading.Event()
        self._loop_thread: Optional[threading.Thread] = None
        self._dispatchers: list = []
        self._conns: set = set()  # loop-thread-owned

    def start(self) -> None:
        self._listener.setblocking(False)
        self._wake_r.setblocking(False)
        self._sel.register(self._listener, _read_event(), "accept")
        self._sel.register(self._wake_r, _read_event(), "wakeup")
        self._loop_thread = threading.Thread(
            target=self._event_loop, name="hs-serve-io", daemon=True)
        self._loop_thread.start()
        # Concurrent responses are bounded by the dispatcher count: the
        # pool's workers plus headroom so inline verbs keep answering
        # while every worker slot is executing.
        n = self._server.pool.workers + 4
        for i in range(n):
            t = threading.Thread(target=self._dispatch,
                                 name=f"hs-serve-dispatch-{i}",
                                 daemon=True)
            t.start()
            self._dispatchers.append(t)

    # -- the event loop (block-free; see hslint blocking-discipline) --------
    def _event_loop(self) -> None:
        while not self._stop.is_set():
            try:
                events = self._sel.select(timeout=0.2)
            except OSError:
                continue
            for key, _mask in events:
                tag = key.data
                if tag == "accept":
                    self._on_accept()
                elif tag == "wakeup":
                    self._on_wakeup()
                else:
                    self._on_readable(tag)

    def _on_accept(self) -> None:
        try:
            sock, _addr = self._listener.accept()
        except OSError:
            return
        if not netfaults.on_accept(sock):
            return  # consumed by an armed net.accept fault (block-free)
        if not self._outer._acquire_conn():
            # Reject IN the loop, bounded send — same contract as the
            # threaded accept loop's early ERR BUSY.
            _reject_connection(self._server, sock)
            try:
                sock.close()
            except OSError:
                pass
            return
        sock.setblocking(False)
        conn = _AsyncConn(self._server, sock)
        self._conns.add(conn)
        self._sel.register(sock, _read_event(), conn)

    def _on_readable(self, conn: _AsyncConn) -> None:
        try:
            data = conn.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(conn, registered=True)
            return
        if not data:
            self._drop(conn, registered=True)  # clean EOF
            return
        conn.buf += data
        if b"\n" in conn.buf or len(conn.buf) > MAX_REQUEST_BYTES:
            self._sel.unregister(conn.sock)
            self._hand_off(conn)

    def _on_wakeup(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            return
        while True:
            try:
                conn, keep = self._requeue.get_nowait()
            except queue.Empty:
                break
            if not keep or self._stop.is_set():
                self._drop(conn, registered=False)
            elif b"\n" in conn.buf:
                # The client pipelined ahead: the next request is already
                # buffered, so no readiness event will ever fire for it.
                self._hand_off(conn)
            else:
                try:
                    conn.sock.setblocking(False)
                    self._sel.register(conn.sock, _read_event(), conn)
                except (OSError, ValueError):
                    self._drop(conn, registered=False)

    def _hand_off(self, conn: _AsyncConn) -> None:
        line, sep, rest = conn.buf.partition(b"\n")
        conn.buf = rest
        self._ready.put_nowait((conn, line + sep))

    def _drop(self, conn: _AsyncConn, registered: bool) -> None:
        if conn not in self._conns:
            return
        self._conns.discard(conn)
        if registered:
            try:
                self._sel.unregister(conn.sock)
            except (KeyError, ValueError):
                pass
        try:
            conn.responder.wfile.close()
        except OSError:
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self._outer._release_conn()

    # -- dispatchers (one response at a time per connection) -----------------
    def _dispatch(self) -> None:
        from hyperspace_tpu.telemetry import metrics

        while True:
            item = self._ready.get()
            if item is None:
                return
            conn, line = item
            pool = self._server.pool
            metrics.inc("serve.requests")
            pool.request_started()
            keep = False
            try:
                keep = conn.responder._respond_one(
                    line, self._server.session.conf)
            except Exception:  # noqa: BLE001 — a dispatcher must survive
                keep = False   # anything a response path can throw
            finally:
                pool.request_finished()
            self._requeue.put((conn, keep))
            try:
                self._wake_w.send(b"x")
            except OSError:
                pass

    # -- lifecycle -----------------------------------------------------------
    def stop_accepting(self) -> None:
        """Phase one of drain/stop: end the event loop (no new accepts,
        no new request reads).  In-flight dispatcher responses keep
        running — wait_idle covers them."""
        self._stop.set()
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5)
            self._loop_thread = None

    def close(self) -> None:
        """Phase two: stop dispatchers and close every connection."""
        self.stop_accepting()
        for _ in self._dispatchers:
            self._ready.put(None)
        for t in self._dispatchers:
            t.join(timeout=5)
        self._dispatchers.clear()
        for conn in list(self._conns):
            self._drop(conn, registered=True)
        try:
            self._sel.close()
        except OSError:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass


def _read_event() -> int:
    import selectors

    return selectors.EVENT_READ


def _serve_verb(session, spec: Dict[str, Any],
                last_report=None, pool=None) -> pa.Table:
    """Non-query verbs of the wire protocol:

      {"verb": "metrics"}          -> (name, value) rows: counters/gauges
                                      flat, histograms flattened to
                                      name.count/name.sum/name.mean
      {"verb": "last_run_report"}  -> one row, column ``report_json`` —
                                      the most recent query report of
                                      THIS CONNECTION (query then ask on
                                      one connection; queries execute on
                                      pool workers, so the handler keeps
                                      the report per connection)
      {"verb": "workload"}         -> the captured advisor workload table
                                      (advisor/workload.py)
      {"verb": "perf_history",
       "index"?, "section"?,
       "limit"?}                    -> the persistent perf ledger
                                      (telemetry/perf_ledger.py): one row
                                      per recorded action/bench-section
                                      run under the serving session's
                                      systemPath; optional filters —
                                      ``index`` keeps action records for
                                      that index, ``section`` keeps
                                      bench records for that section,
                                      ``limit`` the most recent N
      {"verb": "build_report"}     -> one row, column ``report_json`` —
                                      the session's most recent action
                                      BuildReport (session-wide: builds
                                      are serialized by the log protocol)
      {"verb": "slow_queries"}     -> the flight recorder's retained ring
                                      (telemetry/flight_recorder.py):
                                      slow/error/deadline/shed requests
                                      plus sampled healthy ones, oldest
                                      first
      {"verb": "trace",
       "id": "<trace_id>"}         -> one row, column ``record_json`` —
                                      the full retained record (span
                                      tree, run report, outcome) of that
                                      trace id; the id every response
                                      echoes (``trace=``) and every
                                      client error carries
      {"verb": "doctor",
       "fleet"?: true}             -> the aggregated health report
                                      (telemetry/doctor.py): one row per
                                      check (columns check, status,
                                      summary, dataJson) plus the
                                      ``overall`` row — ok/warn/crit,
                                      worst check wins; ``fleet`` adds
                                      the cluster checks over the
                                      published heartbeats
      {"verb": "fleet_status"}     -> every published fleet heartbeat
                                      (telemetry/fleet.py): process
                                      identity, role, health grade,
                                      heartbeat age, freshness — the
                                      "which of my servers is sick"
                                      surface, answering inline so it
                                      works during overload
      {"verb": "alerts",
       "fleet"?: true}             -> current SLO alert states
                                      (telemetry/alerts.py): one row per
                                      objective — availability, latency,
                                      staleness, build-claim liveness —
                                      with state/severity/since and the
                                      incident-bundle key captured at
                                      firing; ``fleet`` merges every
                                      fresh heartbeat's active alerts
                                      with process attribution.
                                      Answers inline, so "am I paging"
                                      works during overload
      {"verb": "lifecycle"}        -> the lifecycle decision journal
                                      (lifecycle/journal.py): every
                                      maintenance-daemon decision —
                                      refresh mode chosen, advisor
                                      build/drop, backoff skip, or "did
                                      nothing, here's why" — oldest
                                      first (docs/19-lifecycle.md)
      {"verb": "tenants"}          -> per-tenant admission state: one
                                      row per tenant id seen (columns
                                      tenant, queued, shed) — ``queued``
                                      is the live queued-or-active
                                      count the quota
                                      (``hyperspace.serving.tenant
                                      .maxQueued``) grades, ``shed`` the
                                      tenant's lifetime quota sheds;
                                      answers inline, so a hot tenant's
                                      operator can see themselves
                                      shedding while it happens

    ``slow_queries`` and ``trace`` answer inline like ``metrics`` — an
    operator debugging an overloaded server needs exactly them while the
    admission queue is shedding.
    """
    verb = spec["verb"]
    if not isinstance(verb, str):
        raise ValueError('"verb" must be a string')
    if verb == "metrics":
        from hyperspace_tpu.telemetry import metrics as m

        names: list = []
        values: list = []

        def emit(name: str, value) -> None:
            if isinstance(value, (int, float)) and value is not None:
                names.append(name)
                values.append(float(value))

        for name, value in sorted(m.snapshot().items()):
            if isinstance(value, dict):  # histogram snapshot
                for part in ("count", "sum", "mean", "min", "max"):
                    if value.get(part) is not None:
                        emit(f"{name}.{part}", value[part])
            else:
                emit(name, value)
        return pa.table({"name": pa.array(names, type=pa.string()),
                         "value": pa.array(values, type=pa.float64())})
    if verb == "last_run_report":
        report = last_report if last_report is not None \
            else session.last_run_report_value
        payload = json.dumps(report.to_dict() if report is not None
                             else None)
        return pa.table({"report_json": pa.array([payload],
                                                 type=pa.string())})
    if verb == "workload":
        from hyperspace_tpu.advisor.workload import workload_table

        return workload_table(session.conf)
    if verb == "perf_history":
        from hyperspace_tpu.telemetry.perf_ledger import history_table

        index = spec.get("index")
        section = spec.get("section")
        limit = spec.get("limit")
        if index is not None and not isinstance(index, str):
            raise ValueError('"index" must be a string')
        if section is not None and not isinstance(section, str):
            raise ValueError('"section" must be a string')
        if limit is not None and (not isinstance(limit, int)
                                  or isinstance(limit, bool) or limit < 0):
            raise ValueError('"limit" must be a non-negative integer')
        return history_table(session.conf, index=index, section=section,
                             limit=limit)
    if verb == "build_report":
        report = session.last_build_report_value
        payload = json.dumps(report.to_dict() if report is not None
                             else None)
        return pa.table({"report_json": pa.array([payload],
                                                 type=pa.string())})
    if verb == "slow_queries":
        from hyperspace_tpu.telemetry.flight_recorder import (
            slow_queries_table,
        )

        return slow_queries_table(session.conf)
    if verb == "trace":
        from hyperspace_tpu.telemetry import flight_recorder

        trace_id = spec.get("id")
        if not isinstance(trace_id, str) or not trace_id:
            raise ValueError(
                'the trace verb needs {"id": "<trace_id>"} — the id a '
                'response echoed as trace=... or an error carried')
        rec = flight_recorder.recorder().find(trace_id.lower())
        if rec is None:
            raise ValueError(
                f"no retained flight record for trace id {trace_id!r} "
                f"(healthy requests are sampled; slow/error/shed ones "
                f"are always kept while they fit the ring)")
        return pa.table({"record_json": pa.array(
            [json.dumps(rec, default=str)], type=pa.string())})
    if verb == "doctor":
        from hyperspace_tpu.telemetry.doctor import doctor

        fleet = spec.get("fleet", False)
        if not isinstance(fleet, bool):
            raise ValueError('"fleet" must be a boolean')
        return doctor(session, fleet=fleet).table()
    if verb == "fleet_status":
        from hyperspace_tpu.telemetry.fleet import fleet_status_table

        return fleet_status_table(session.conf)
    if verb == "alerts":
        from hyperspace_tpu.telemetry.alerts import alerts_table

        fleet = spec.get("fleet", False)
        if not isinstance(fleet, bool):
            raise ValueError('"fleet" must be a boolean')
        return alerts_table(session, fleet=fleet)
    if verb == "lifecycle":
        from hyperspace_tpu.lifecycle.journal import history_table

        return history_table(session.conf)
    if verb == "tenants":
        from hyperspace_tpu.telemetry import metrics as m

        queued = pool.tenant_snapshot() if pool is not None else {}
        shed: Dict[str, float] = {}
        prefix, suffix = "serve.tenant.", ".shed"
        for name, value in m.snapshot().items():
            if name.startswith(prefix) and name.endswith(suffix) \
                    and not isinstance(value, dict):
                shed[name[len(prefix):-len(suffix)]] = float(value)
        tenants = sorted(set(queued) | set(shed))
        return pa.table({
            "tenant": pa.array(tenants, type=pa.string()),
            "queued": pa.array([int(queued.get(t, 0)) for t in tenants],
                               type=pa.int64()),
            "shed": pa.array([int(shed.get(t, 0)) for t in tenants],
                             type=pa.int64()),
        })
    raise ValueError(f"Unknown verb {verb!r}; expected metrics, "
                     f"last_run_report, workload, perf_history, "
                     f"build_report, slow_queries, trace, doctor, "
                     f"fleet_status, alerts, lifecycle, or tenants")


def _is_loopback(host: str) -> bool:
    if host == "localhost":
        return True
    if host == "":
        return False  # "" binds INADDR_ANY — every interface, most exposed
    import ipaddress

    try:
        return ipaddress.ip_address(host).is_loopback
    except ValueError:
        return False  # a hostname we can't classify: treat as remote


class QueryServer:
    """Admission-controlled threaded TCP server bound to ``session``.
    ``port=0`` picks an ephemeral port (read it back from ``.address``).

    Sizing comes from the session conf at construction
    (``hyperspace.serving.workers`` / ``.queueDepth`` /
    ``.maxConnections`` — see docs/07-interop.md); timeouts, deadlines,
    and shed watermarks are read live per request, so ``conf.set`` on a
    running server takes effect immediately.

    ``handle_sigterm=True`` installs a SIGTERM handler (main thread only)
    that runs :meth:`drain` in the background: stop accepting, let
    in-flight requests finish within ``hyperspace.serving.drainGraceS``,
    then close — ``drained`` is set when the shutdown completes, so a
    serving script can simply ``server.drained.wait()``.

    ``hyperspace.serving.ioMode=async`` swaps the threaded accept path
    for the selector event loop (:class:`_AsyncIOLoop`) — same wire
    behavior, one io thread for every connection.

    ``proxy_endpoints=[...]`` turns this server into a thin FRONT DOOR:
    queries forward through a :class:`FleetQueryClient` over those
    backends (least-loaded routing, failover, retry-after backoff), so
    a non-Python client pointed at the proxy gets fleet fault tolerance
    without reimplementing it; observability verbs still answer from
    THIS process."""

    def __init__(self, session, host: str = "127.0.0.1",
                 port: int = 0, allow_remote: bool = False,
                 handle_sigterm: bool = False,
                 proxy_endpoints: Optional[list] = None) -> None:
        # The server is UNAUTHENTICATED and reads any path the process can
        # access; binding a non-loopback interface exposes that to the
        # network.  Require the caller to say so explicitly.
        if not _is_loopback(host) and not allow_remote:
            raise ValueError(
                f"QueryServer binds {host!r}, a non-loopback interface, but "
                f"the protocol has no authentication: any peer that can "
                f"reach the port can read any file this process can.  Pass "
                f"allow_remote=True only behind a trusted network boundary.")

        outer = self

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

            def process_request(self, request, client_address):
                if not netfaults.on_accept(request):
                    return  # consumed by an armed net.accept fault
                if not outer._acquire_conn():
                    # Reject IN the accept loop — no handler thread is
                    # spawned, so a connection storm cannot grow the
                    # thread count past maxConnections + workers.
                    _reject_connection(self, request)
                    self.shutdown_request(request)
                    return
                super().process_request(request, client_address)

            def process_request_thread(self, request, client_address):
                try:
                    super().process_request_thread(request, client_address)
                finally:
                    outer._release_conn()

        self._server = _Server((host, port), _Handler)
        self._server.session = session
        conf = session.conf
        # Telemetry conf set between session construction and server
        # start must win before the FIRST request's serve.request span
        # opens (collect re-applies per query, but that is too late for
        # the worker's root span).
        from hyperspace_tpu.telemetry import trace as _trace

        _trace.configure_from_conf(conf)
        self._server.pool = _WorkerPool(
            session,
            workers=int(getattr(conf, "serving_workers", 4)),
            queue_depth=int(getattr(conf, "serving_queue_depth", 16)))
        if getattr(conf, "serving_plan_cache_enabled", True):
            from hyperspace_tpu.execution.plan_cache import PlanCache

            self._server.plan_cache = PlanCache(
                budget_bytes=int(getattr(conf, "serving_plan_cache_bytes",
                                         64 << 20)),
                ttl_s=float(conf.cache_expiry_seconds))
        else:
            self._server.plan_cache = None
        self._server.proxy_client = (
            FleetQueryClient(proxy_endpoints, conf=conf)
            if proxy_endpoints else None)
        self._io_mode = str(getattr(conf, "serving_io_mode",
                                    "threaded")).strip().lower()
        if self._io_mode not in ("threaded", "async"):
            raise ValueError(
                f"hyperspace.serving.ioMode must be 'threaded' or "
                f"'async', got {self._io_mode!r}")
        self._async: Optional[_AsyncIOLoop] = None
        self._max_connections = int(getattr(conf,
                                            "serving_max_connections", 64))
        self._conn_lock = threading.Lock()
        self._conn_count = 0
        self._thread: Optional[threading.Thread] = None
        self._draining = False
        self.drained = threading.Event()
        if handle_sigterm:
            self._install_sigterm()

    # -- connection accounting ---------------------------------------------
    def _acquire_conn(self) -> bool:
        if self._draining:
            return False
        with self._conn_lock:
            if self._max_connections > 0 and \
                    self._conn_count >= self._max_connections:
                return False
            self._conn_count += 1
        from hyperspace_tpu.telemetry import metrics

        metrics.set_gauge("serve.connections", self._conn_count)
        return True

    def _release_conn(self) -> None:
        with self._conn_lock:
            self._conn_count = max(0, self._conn_count - 1)
        from hyperspace_tpu.telemetry import metrics

        metrics.set_gauge("serve.connections", self._conn_count)

    # -- surface -------------------------------------------------------------
    @property
    def session(self):
        return self._server.session

    @property
    def pool(self) -> _WorkerPool:
        return self._server.pool

    @property
    def plan_cache(self):
        return self._server.plan_cache

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def start(self) -> "QueryServer":
        # A serving process publishes role "server" in its fleet
        # heartbeat (telemetry/fleet.py; conf-gated — maybe_start is a
        # no-op with fleet telemetry off, and never raises).  The
        # heartbeat carries this server's address so the front door can
        # match fleet rows to endpoints, and a fresh start clears any
        # draining flag a previous in-process server left behind.
        from hyperspace_tpu.telemetry import alerts, fleet

        fleet.set_process_role("server")
        host, port = self.address[0], self.address[1]
        fleet.set_serving_address(f"{host}:{port}")
        fleet.set_serving_draining(False)
        fleet.maybe_start(self.session)
        # The SLO alert engine watches this server's counters; same
        # conf-gated never-raises start (hyperspace.alerts.enabled).
        alerts.maybe_start(self.session)
        self._server.pool.start()
        if self._io_mode == "async":
            self._async = _AsyncIOLoop(self, self._server)
            self._async.start()
        else:
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name="hs-query-server", daemon=True)
            self._thread.start()
        return self

    def drain(self, grace_s: Optional[float] = None) -> bool:
        """Graceful shutdown: stop accepting new connections AND new
        requests (both shed ``ERR BUSY``), let in-flight requests finish
        within ``grace_s`` (default conf
        ``hyperspace.serving.drainGraceS``), then stop the workers and
        close the listener.  Returns True when everything in flight
        completed inside the grace window.  Idempotent."""
        from hyperspace_tpu.telemetry import metrics

        if self.drained.is_set():
            return True
        if grace_s is None:
            grace_s = float(getattr(self.session.conf,
                                    "serving_drain_grace_s", 10.0))
        self._draining = True
        self._server.pool.draining = True
        metrics.inc("serve.drains")
        # Park the maintenance daemon too: a refresh racing this drain
        # would keep the process alive past its grace window
        # (lifecycle/daemon.py; the latch is process-global).
        from hyperspace_tpu.lifecycle import daemon as _lifecycle_daemon

        _lifecycle_daemon.notify_drain()
        # Flag the fleet heartbeat as draining and publish immediately:
        # the front door skips draining rows, so new requests stop
        # routing here DURING the grace window instead of shedding BUSY
        # at the door (publish_once is fault-quiet / conf-gated).
        from hyperspace_tpu.telemetry import fleet as _fleet

        _fleet.set_serving_draining(True)
        _fleet.publish_once(self.session.conf)
        if self._async is not None:
            self._async.stop_accepting()
        elif self._thread is not None:
            self._server.shutdown()  # stop the accept loop
        clean = self._server.pool.wait_idle(grace_s)
        # Persist the flight recorder's ring (+ metrics snapshot +
        # perf-ledger tail) as a diagnostics bundle AFTER in-flight
        # requests finished — so a SIGTERM'd server leaves "what
        # happened" readable after restart.  dump_diagnostics never
        # raises and runs fault-quiet.
        from hyperspace_tpu.telemetry import flight_recorder

        flight_recorder.dump_diagnostics(self.session.conf)
        # Deregister the fleet heartbeat: a drained server is a PLANNED
        # exit, not a dead process — without this the fleet doctor would
        # page crit on every rolling restart.  The diagnostics bundle
        # above keeps the tail readable; SIGKILL skips this path, which
        # is exactly how a genuinely dead process IS flagged.
        _fleet.publisher_for(self.session).stop()
        self._server.pool.stop()
        if self._async is not None:
            self._async.close()
        if self._server.proxy_client is not None:
            self._server.proxy_client.close()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.drained.set()
        return clean

    def _install_sigterm(self) -> None:
        import signal

        def _on_term(signum, frame) -> None:
            threading.Thread(target=self.drain, name="hs-serve-drain",
                             daemon=True).start()

        try:
            signal.signal(signal.SIGTERM, _on_term)
        except ValueError:
            raise ValueError(
                "handle_sigterm=True requires constructing the "
                "QueryServer on the main thread (signal handlers are "
                "main-thread-only); call drain() from your own handler "
                "instead")

    def stop(self) -> None:
        # shutdown() blocks on serve_forever's exit handshake — calling it
        # on a never-started server would hang forever, so only do the
        # handshake when start() actually ran; server_close() alone
        # releases the socket either way.
        if self.drained.is_set():
            return
        if self._thread is not None:
            self._server.shutdown()
        self._server.pool.stop()
        if self._async is not None:
            self._async.close()
        if self._server.proxy_client is not None:
            self._server.proxy_client.close()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "QueryServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class MetricsScrapeServer:
    """Long-lived Prometheus scrape endpoint: ``GET /metrics`` serves the
    process metrics registry's text exposition
    (``telemetry/metrics.render_prometheus`` — the ``build.phase.*``,
    ``exec.*``, ``io.*``, ``serve.*`` catalog of docs/16-observability.md).

    This is the pull-based counterpart of the ``metrics`` verb: the verb
    answers an Arrow client once; this endpoint stays up for a scraper to
    poll on its own schedule — the ops surface ROADMAP item 2's serving
    layer reports through.  Same security posture as :class:`QueryServer`:
    loopback by default, ``allow_remote=True`` required to expose it
    (metrics leak workload shape, file counts, index names via series
    values).

    ``fleet=True`` (requires ``session``) serves the FLEET-merged
    exposition instead (telemetry/fleet.py): every fresh published
    heartbeat's series plus this process's live registry, each labeled
    ``process="<id>"`` — one scrape target answers for the whole fleet,
    and the label answers "which server is slow".

    >>> with MetricsScrapeServer(port=9109) as ms:
    ...     ...  # curl http://127.0.0.1:9109/metrics
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 allow_remote: bool = False, session=None,
                 fleet: bool = False) -> None:
        if not _is_loopback(host) and not allow_remote:
            raise ValueError(
                f"MetricsScrapeServer binds {host!r}, a non-loopback "
                f"interface, without authentication.  Pass "
                f"allow_remote=True only behind a trusted boundary.")
        if fleet and session is None:
            raise ValueError(
                "MetricsScrapeServer(fleet=True) needs session=... — the "
                "merged exposition reads the fleet heartbeats under that "
                "session's systemPath")
        scrape_conf = session.conf if session is not None else None
        import http.server

        class _MetricsHandler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 — stdlib contract
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                from hyperspace_tpu.telemetry import metrics as m

                if fleet:
                    from hyperspace_tpu.telemetry.fleet import (
                        render_fleet_prometheus,
                    )

                    body = render_fleet_prometheus(
                        scrape_conf).encode("utf-8")
                else:
                    body = m.registry().render_prometheus() \
                        .encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; "
                                 "charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args: object) -> None:
                pass  # a scrape per second must not spam stderr

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _MetricsHandler)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def start(self) -> "MetricsScrapeServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="hs-metrics-server", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "MetricsScrapeServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def request_query(address: Tuple[str, int],
                  spec: Dict[str, Any]) -> pa.Table:
    """Reference client (tests / Python callers): send ``spec``, return the
    result table.  Non-Python clients reimplement these ~10 lines with
    their socket + Arrow APIs."""
    with QueryClient(address) as client:
        return client.query(spec)


class QueryClient:
    """Persistent pipelined connection: successful ``query()`` calls ride
    one socket (the server answers each in order).  After an error
    response, a transport failure, or the server's idle timeout
    (``hyperspace.serving.requestTimeoutS`` between requests) the server
    closes the connection — the client marks itself broken and subsequent
    calls raise ``ConnectionError`` asking for a fresh client, rather
    than failing with a confusing empty-status error on the dead socket.

    Wire errors raise :class:`QueryFailedError` (a ``RuntimeError``)
    carrying ``.code`` and ``.retryable`` — ``BUSY``/``DEADLINE`` mean
    "back off and retry on a new connection", the overload contract of
    docs/07-interop.md.

    Every request carries a client-minted TRACE CONTEXT (``trace_id`` /
    ``request_id`` spec keys, 16 hex chars each) that the server adopts
    and echoes on the status line — so a failure is correlatable from
    either side: ``.last_trace_id`` after a call (and
    ``QueryFailedError.trace_id`` on errors) is the handle
    ``slow_queries()`` / the ``trace`` verb answer for.

    ``tenant`` stamps every spec sent on this connection with a tenant
    id (the per-tenant admission key of
    ``hyperspace.serving.tenant.maxQueued``); an explicit ``"tenant"``
    key in a spec wins."""

    def __init__(self, address: Tuple[str, int],
                 tenant: Optional[str] = None,
                 timeout_s: Optional[float] = None) -> None:
        self._sock = netfaults.connect(address, timeout=timeout_s)
        self._f = self._sock.makefile("rb")
        self._broken = False
        self.tenant = tenant
        #: trace id of the most recent query() — server-echoed when the
        #: server speaks the trace protocol, else the client-minted one.
        self.last_trace_id: Optional[str] = None

    def is_stale(self) -> bool:
        """True when the pooled socket is no longer usable: the server
        hung up (half-open TCP after a bounce — a nonblocking peek sees
        EOF or an error), or bytes are pending between requests (a
        protocol violation on a pipelined connection — e.g. a hedged
        loser's late response; reading a fresh request's answer from it
        would cross-wire responses)."""
        if self._broken:
            return True
        try:
            self._sock.setblocking(False)
            try:
                chunk = self._sock.recv(1, socket.MSG_PEEK)
            finally:
                self._sock.setblocking(True)
        except (BlockingIOError, InterruptedError):
            return False  # no pending data: the healthy idle state
        except OSError:
            return True  # reset/refused already latched on the socket
        # EOF (b"") or unexpected pending bytes: either way, not safe.
        return True

    def query(self, spec: Dict[str, Any],
              deadline_ms: Optional[float] = None,
              timeout_s: Optional[float] = None) -> pa.Table:
        from hyperspace_tpu.interop.query import mint_trace_id

        if self._broken:
            raise ConnectionError(
                "connection closed by an earlier error or timeout; open a "
                "new QueryClient")
        if deadline_ms is not None:
            spec = {**spec, "deadline_ms": deadline_ms}
        if isinstance(spec, dict):
            if self.tenant is not None and "tenant" not in spec:
                spec = {**spec, "tenant": self.tenant}
            if "trace_id" not in spec:
                spec = {**spec, "trace_id": mint_trace_id()}
            if "request_id" not in spec:
                spec = {**spec, "request_id": mint_trace_id()}
            self.last_trace_id = spec["trace_id"]
        else:
            # A malformed (non-object) spec still goes to the server —
            # whose BADREQ answer, not a client-side crash, is the
            # contract under test for such requests.
            self.last_trace_id = None
        try:
            if timeout_s is not None:
                # The whole exchange — send, status line, Arrow stream —
                # rides one socket timeout: a SIGSTOPped or partitioned
                # server surfaces as ConnectionError within the budget
                # instead of pinning the caller forever.
                self._sock.settimeout(timeout_s)
            netfaults.send_all(
                self._sock, json.dumps(spec).encode("utf-8") + b"\n")
            netfaults.before_recv()
            status = self._f.readline().decode("utf-8").rstrip("\n")
        except OSError as exc:
            self._broken = True
            raise ConnectionError(f"connection lost: {exc}") from exc
        if not status.startswith("OK"):
            # ERR (server closes) or EOF (idle timeout / server gone).
            self._broken = True
            if not status:
                raise ConnectionError(
                    "server closed the connection (idle timeout or "
                    "shutdown); open a new QueryClient")
            err = parse_wire_error(status)
            if err.trace_id is None:
                # Pre-trace server: the minted id still names the
                # request on THIS side of the wire.
                err.trace_id = self.last_trace_id
            else:
                self.last_trace_id = err.trace_id
            raise err
        _, echoed = _split_trace_echo(status[2:].strip())
        if echoed is not None:
            self.last_trace_id = echoed
        try:
            with pa.ipc.open_stream(self._f) as reader:
                return reader.read_all()
        except OSError as exc:
            self._broken = True
            raise ConnectionError(f"connection lost: {exc}") from exc
        except pa.ArrowInvalid as exc:
            # A truncated/garbled IPC stream after a clean OK line: the
            # connection died mid-frame (torn frame, reset, server
            # crash).  That is a TRANSPORT fault, not a query failure —
            # surface it retryable so the front door fails over instead
            # of raising a decoder error at the caller.
            self._broken = True
            raise ConnectionError(
                f"response stream torn mid-frame: {exc}") from exc

    def close(self) -> None:
        self._f.close()
        self._sock.close()

    def __enter__(self) -> "QueryClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _as_address(endpoint) -> Tuple[str, int]:
    """``"host:port"`` or ``(host, port)`` → ``(host, port)``."""
    if isinstance(endpoint, str):
        host, _, port = endpoint.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(
                f"endpoint {endpoint!r} is not 'host:port'")
        return host, int(port)
    host, port = endpoint
    return str(host), int(port)


class _Endpoint:
    """One server behind the front door: its address, a small pool of
    idle pipelined connections, the router's view of it (in-flight
    count, fleet-reported load, draining flag, penalty clock), and its
    circuit-breaker state (closed → open on consecutive failures →
    half-open probe after the cooldown)."""

    __slots__ = ("address", "label", "idle", "inflight", "penalized_until",
                 "load", "draining", "fresh", "lock",
                 "breaker_state", "breaker_fails", "breaker_until")

    MAX_IDLE = 4  # idle pipelined connections kept per endpoint

    def __init__(self, endpoint) -> None:
        self.address = _as_address(endpoint)
        self.label = f"{self.address[0]}:{self.address[1]}"
        self.idle: List[QueryClient] = []
        self.inflight = 0
        self.penalized_until = 0.0   # monotonic; routing skips until then
        self.load: Optional[float] = None  # fleet-reported queue+inflight
        self.draining = False
        self.fresh = True  # no fleet row ⇒ assume routable (fleet is opt-in)
        self.lock = threading.Lock()
        self.breaker_state = "closed"   # closed | open | half-open
        self.breaker_fails = 0          # consecutive failures while closed
        self.breaker_until = 0.0        # monotonic; open until then

    def acquire(self, tenant: Optional[str],
                timeout_s: Optional[float] = None) -> QueryClient:
        """Pop a VALIDATED idle connection or dial a new one.  Pooled
        sockets are peeked on checkout: a restarted server leaves
        half-open TCP behind, and handing that to a caller turns a
        routine bounce into a spurious reset charged to retry
        accounting — evict it silently instead
        (``client.pool.evicted``).  The connect happens OUTSIDE the
        lock (it blocks); in-flight is rolled back when the dial fails
        so a dead endpoint doesn't look busy forever."""
        from hyperspace_tpu.telemetry import metrics

        with self.lock:
            self.inflight += 1
        while True:
            with self.lock:
                client = self.idle.pop() if self.idle else None
            if client is None:
                break
            if client.is_stale():
                metrics.inc("client.pool.evicted")
                try:
                    client.close()
                except OSError:
                    pass
                continue
            client.tenant = tenant
            return client
        try:
            return QueryClient(self.address, tenant=tenant,
                               timeout_s=timeout_s)
        except OSError:
            with self.lock:
                self.inflight -= 1
            raise

    # -- circuit breaker -----------------------------------------------------
    def breaker_blocked(self, now: float) -> bool:
        """True when routing should avoid this endpoint: breaker open
        inside its cooldown, or a half-open probe already in flight."""
        with self.lock:
            if self.breaker_state == "open":
                return now < self.breaker_until
            return self.breaker_state == "half-open"

    def breaker_on_pick(self, now: float) -> bool:
        """Transition open → half-open when the cooldown has expired and
        this endpoint was actually PICKED (the probe request).  Returns
        True on the transition so the caller can count it."""
        with self.lock:
            if self.breaker_state == "open" and now >= self.breaker_until:
                self.breaker_state = "half-open"
                return True
        return False

    def breaker_failure(self, threshold: int, cooldown_s: float) -> bool:
        """Record a retryable/transport failure.  Returns True when this
        failure OPENED the breaker (threshold reached, or the half-open
        probe failed)."""
        now = time.monotonic()
        with self.lock:
            if self.breaker_state == "half-open":
                self.breaker_state = "open"
                self.breaker_until = now + cooldown_s
                return True
            self.breaker_fails += 1
            if self.breaker_state == "closed" \
                    and self.breaker_fails >= max(1, threshold):
                self.breaker_state = "open"
                self.breaker_until = now + cooldown_s
                return True
        return False

    def breaker_success(self) -> bool:
        """Record a served request.  Returns True when this success
        CLOSED a non-closed breaker (the half-open probe came back)."""
        with self.lock:
            was = self.breaker_state
            self.breaker_state = "closed"
            self.breaker_fails = 0
            return was != "closed"

    def release(self, client: QueryClient) -> None:
        with self.lock:
            self.inflight -= 1
            if len(self.idle) < self.MAX_IDLE:
                self.idle.append(client)
                return
        client.close()

    def discard(self, client: QueryClient) -> None:
        with self.lock:
            self.inflight -= 1
        try:
            client.close()
        except OSError:
            pass

    def close_idle(self) -> None:
        with self.lock:
            idle, self.idle = self.idle, []
        for client in idle:
            try:
                client.close()
            except OSError:
                pass


class FleetQueryClient:
    """Fault-tolerant front door over N :class:`QueryServer` endpoints.

    Routing is LEAST-LOADED: when fleet telemetry is on
    (``hyperspace.telemetry.fleet.enabled``), each server's heartbeat
    carries its address plus ``serve.inflight``/``serve.queue_depth``
    gauges and a ``draining`` flag; the router matches rows to endpoints
    by address, skips draining/stale rows, and sends each request to the
    least-loaded survivor (in-flight count breaks ties, round-robin
    breaks the rest).  Without fleet rows every endpoint is assumed
    routable and local in-flight counts carry the policy.

    Failure policy (the docs/07-interop.md retry contract):

      - RETRYABLE failures — ``BUSY``/``DEADLINE`` wire errors, plus
        transport faults (connection refused / reset / EOF) — retry on a
        DIFFERENT endpoint when one is available, with bounded jittered
        exponential backoff; a ``retry-after-ms`` hint from the server
        overrides the backoff step AND penalizes that endpoint for the
        hinted window so the next pick avoids it.
      - PERMANENT failures — ``BADREQ``/``FAILED`` — raise immediately;
        re-running a malformed or failing request elsewhere just fails
        N times.

    Retries increment ``client.retry`` (+ ``client.retry.<kind>``);
    a retry that lands on a different endpoint than the failed attempt
    increments ``client.failover``.  ``tenant`` stamps every spec for
    per-tenant admission on the servers.

    DEADLINE BUDGET: ``deadline_ms`` is ONE overall per-call budget —
    connect timeouts, socket read timeouts, backoff sleeps, the hedge
    delay, and the server-side deadline all spend from it, so the total
    elapsed across every failover attempt respects the caller's bound
    (per-attempt spending could overshoot it N-fold).

    CIRCUIT BREAKERS (``hyperspace.client.breaker.*``, default off):
    ``failures`` consecutive retryable/transport errors open an
    endpoint's breaker — routing avoids it for ``cooldownMs``, then ONE
    half-open probe request decides (success closes it, failure
    re-opens).  Transitions land on ``client.breaker.open`` /
    ``.half_open`` / ``.close`` counters and the
    ``client.breaker.open_now`` gauge the doctor's ``client`` check
    grades.

    HEDGED REQUESTS (``hyperspace.client.hedge.enabled``, default off):
    when the first attempt is slower than the hedge delay
    (``hedge.delayMs``, or 2× the client's latency EWMA when 0), a
    second attempt fires on a different survivor; the first response
    wins and the loser's late response is discarded by request_id
    (each attempt reads its own pipelined connection, so a late frame
    can never cross-wire onto a winner).  ``client.hedge.sent`` /
    ``client.hedge.wins`` count them.  Queries through this front door
    are reads — verbs and specs alike — which is what makes firing the
    same request twice safe.

    >>> with FleetQueryClient(["127.0.0.1:9001", "127.0.0.1:9002"],
    ...                       conf=session.conf) as fleet:
    ...     fleet.query({"index": "idx", "point": {"id": 7}})
    """

    def __init__(self, endpoints: Sequence[Union[str, Tuple[str, int]]],
                 conf=None, tenant: Optional[str] = None,
                 max_attempts: Optional[int] = None,
                 backoff_cap_ms: float = 2000.0,
                 status_refresh_s: float = 1.0,
                 hedge_enabled: Optional[bool] = None,
                 hedge_delay_ms: Optional[float] = None,
                 breaker_enabled: Optional[bool] = None,
                 breaker_failures: Optional[int] = None,
                 breaker_cooldown_ms: Optional[float] = None) -> None:
        if not endpoints:
            raise ValueError("FleetQueryClient needs at least one endpoint")
        self._endpoints = [_Endpoint(e) for e in endpoints]
        self._conf = conf
        self._tenant = tenant
        self._max_attempts = int(max_attempts if max_attempts is not None
                                 else max(3, len(self._endpoints)))
        self._backoff_cap_ms = float(backoff_cap_ms)
        self._status_refresh_s = float(status_refresh_s)
        self._status_stamp = 0.0  # monotonic; 0 forces a first refresh
        self._rr = 0
        self._lock = threading.Lock()  # guards _rr/_status_stamp/_lat_ewma
        # ONLY — never held across connect/send/sleep (lint:
        # lock-held-blocking)

        def _opt(value, key, default):
            return value if value is not None \
                else getattr(conf, key, default) if conf is not None \
                else default

        self._hedge_enabled = bool(
            _opt(hedge_enabled, "client_hedge_enabled", False))
        self._hedge_delay_ms = float(
            _opt(hedge_delay_ms, "client_hedge_delay_ms", 0.0))
        self._breaker_enabled = bool(
            _opt(breaker_enabled, "client_breaker_enabled", False))
        self._breaker_failures = int(
            _opt(breaker_failures, "client_breaker_failures", 5))
        self._breaker_cooldown_ms = float(
            _opt(breaker_cooldown_ms, "client_breaker_cooldown_ms", 2000.0))
        self._lat_ewma_ms = 0.0  # successful-request latency EWMA
        #: trace id of the most recent query() — same contract as
        #: :class:`QueryClient`.
        self.last_trace_id: Optional[str] = None

    # -- routing --------------------------------------------------------------
    def _refresh_status(self) -> None:
        """Fold fresh fleet heartbeats into the endpoint table (by the
        ``address`` snapshot field).  Cheap-throttled; fault-quiet —
        routing falls back to local in-flight counts on any failure."""
        if self._conf is None:
            return
        now = time.monotonic()
        with self._lock:
            if now - self._status_stamp < self._status_refresh_s:
                return
            self._status_stamp = now
        try:
            from hyperspace_tpu.telemetry import fleet

            rows = {}
            for snap in fleet.fresh_snapshots(self._conf):
                addr = str(snap.get("address", "") or "")
                if addr:
                    rows[addr] = snap
        except Exception:  # noqa: BLE001 — telemetry must not break routing
            return
        for ep in self._endpoints:
            snap = rows.get(ep.label)
            if snap is None:
                # No fresh row: leave it routable on local signals only
                # (fleet telemetry may simply be off on that server).
                ep.load = None
                ep.draining = False
                ep.fresh = True
                continue
            gauges = snap.get("metrics", {}).get("gauges", {})
            ep.load = (float(gauges.get("serve.inflight", 0.0)) +
                       float(gauges.get("serve.queue_depth", 0.0)))
            ep.draining = bool(snap.get("draining", False))
            ep.fresh = True

    def _pick(self, tried: set,
              exclude: Optional[set] = None) -> _Endpoint:
        """Least-loaded routable endpoint not yet tried this request;
        progressively relax (allow breaker-open/penalized, then tried)
        rather than fail a pick while any endpoint exists.  ``exclude``
        labels (the hedge's other attempt) are avoided at every tier
        but the last-resort one."""
        from hyperspace_tpu.telemetry import metrics

        self._refresh_status()
        now = time.monotonic()
        exclude = exclude or set()

        def _tier(skip_tried: bool = True, skip_draining: bool = True,
                  skip_penalized: bool = False,
                  skip_broken: bool = False) -> List[_Endpoint]:
            return [ep for ep in self._endpoints
                    if ep.label not in exclude
                    and (not skip_tried or ep.label not in tried)
                    and (not skip_draining or not ep.draining)
                    and (not skip_penalized or now >= ep.penalized_until)
                    and (not skip_broken
                         or not ep.breaker_blocked(now))]

        pool = (_tier(skip_penalized=True,
                      skip_broken=self._breaker_enabled)
                or _tier()
                or _tier(skip_draining=False)
                or [ep for ep in self._endpoints if ep.label not in exclude]
                or self._endpoints)

        def _load(ep: _Endpoint) -> float:
            base = ep.load if ep.load is not None else 0.0
            return base + ep.inflight

        low = min(_load(ep) for ep in pool)
        ties = [ep for ep in pool if _load(ep) <= low]
        with self._lock:
            self._rr += 1
            ep = ties[self._rr % len(ties)]
        if self._breaker_enabled and ep.breaker_on_pick(now):
            metrics.inc("client.breaker.half_open")
            self._breaker_gauge()
        return ep

    def _breaker_gauge(self) -> None:
        from hyperspace_tpu.telemetry import metrics

        metrics.set_gauge(
            "client.breaker.open_now",
            sum(1 for ep in self._endpoints
                if ep.breaker_state != "closed"))

    # -- request path ---------------------------------------------------------
    def query(self, spec: Dict[str, Any],
              deadline_ms: Optional[float] = None) -> pa.Table:
        deadline_at = (time.monotonic() + float(deadline_ms) / 1000.0
                       if deadline_ms is not None else None)
        if self._hedge_enabled and isinstance(spec, dict):
            return self._query_hedged(spec, deadline_ms, deadline_at)
        return self._query_attempts(spec, deadline_ms, deadline_at)

    @staticmethod
    def _remaining_ms(deadline_at: Optional[float]) -> Optional[float]:
        if deadline_at is None:
            return None
        return (deadline_at - time.monotonic()) * 1000.0

    def _observe_latency(self, elapsed_ms: float) -> None:
        with self._lock:
            self._lat_ewma_ms = elapsed_ms if self._lat_ewma_ms <= 0.0 \
                else 0.8 * self._lat_ewma_ms + 0.2 * elapsed_ms

    def _query_attempts(self, spec: Dict[str, Any],
                        deadline_ms: Optional[float],
                        deadline_at: Optional[float],
                        exclude: Optional[set] = None,
                        note: Optional[Dict[str, Any]] = None,
                        max_attempts: Optional[int] = None) -> pa.Table:
        """The retry/failover loop, spending from ONE deadline budget:
        every attempt's socket timeout, server-side deadline, and
        backoff sleep is bounded by what remains of ``deadline_ms`` —
        the budget is the caller's, not per-attempt."""
        from hyperspace_tpu.telemetry import metrics

        attempts_cap = int(max_attempts) if max_attempts is not None \
            else self._max_attempts
        last_exc: Optional[Exception] = None
        last_label: Optional[str] = None
        tried: set = set()
        for attempt in range(1, attempts_cap + 1):
            remaining = self._remaining_ms(deadline_at)
            if remaining is not None and remaining <= 1.0:
                break  # budget exhausted: surface the last failure
            if len(tried) >= len(self._endpoints):
                tried.clear()  # every endpoint failed once: start over
            ep = self._pick(tried, exclude=exclude)
            tried.add(ep.label)
            if note is not None:
                note.setdefault("labels", set()).add(ep.label)
            if last_label is not None and last_label != ep.label:
                # A retry routed AWAY from the endpoint that failed —
                # the failover event the drill test counts.
                metrics.inc("client.failover")
            # Spread the remaining budget over the attempts still
            # available (bounded by distinct endpoints): a GRAY failure
            # — server alive but serving nothing — otherwise eats the
            # whole budget in one socket timeout, leaving nothing to
            # fail over with.
            if remaining is not None:
                spread = max(1, min(attempts_cap - attempt + 1,
                                    len(self._endpoints)))
                timeout_s = remaining / 1000.0 / spread + 0.05
            else:
                timeout_s = None
            retry_after_ms: Optional[float] = None
            kind = "connection"
            t0 = time.monotonic()
            try:
                client = ep.acquire(self._tenant, timeout_s=timeout_s)
            except OSError as exc:
                last_exc = ConnectionError(
                    f"connect to {ep.label} failed: {exc}")
            else:
                try:
                    table = client.query(
                        spec,
                        deadline_ms=self._remaining_ms(deadline_at)
                        if deadline_at is not None else deadline_ms,
                        timeout_s=timeout_s)
                except QueryFailedError as exc:
                    # The server closes the connection after an ERR.
                    ep.discard(client)
                    self.last_trace_id = exc.trace_id
                    if not exc.retryable:
                        raise  # BADREQ/FAILED: same answer everywhere
                    kind = exc.code.lower()
                    retry_after_ms = exc.retry_after_ms
                    last_exc = exc
                except (ConnectionError, OSError) as exc:
                    ep.discard(client)
                    last_exc = exc
                else:
                    ep.release(client)
                    self.last_trace_id = client.last_trace_id
                    self._observe_latency(
                        (time.monotonic() - t0) * 1000.0)
                    if self._breaker_enabled and ep.breaker_success():
                        metrics.inc("client.breaker.close")
                        self._breaker_gauge()
                    return table
            metrics.inc("client.retry")
            metrics.inc(f"client.retry.{kind}")
            last_label = ep.label
            if self._breaker_enabled and ep.breaker_failure(
                    self._breaker_failures,
                    self._breaker_cooldown_ms / 1000.0):
                metrics.inc("client.breaker.open")
                self._breaker_gauge()
            # Penalize the failed endpoint for the server's hinted
            # window (or a nominal beat) so the next pick avoids it.
            ep.penalized_until = time.monotonic() + \
                (retry_after_ms or 100.0) / 1000.0
            if attempt < attempts_cap:
                if not self._backoff(attempt, retry_after_ms, deadline_at):
                    break  # no budget left to sleep AND attempt again
        if last_exc is None:
            last_exc = TimeoutError(
                f"deadline budget ({deadline_ms} ms) exhausted before "
                f"any attempt completed")
        raise last_exc

    def _query_hedged(self, spec: Dict[str, Any],
                      deadline_ms: Optional[float],
                      deadline_at: Optional[float]) -> pa.Table:
        """Run the attempts loop in a worker thread; when it is slower
        than the hedge delay, fire ONE extra single-attempt on a
        different survivor.  First response wins; the loser finishes
        reading its own connection in the background and its response
        is discarded by request_id."""
        from hyperspace_tpu.interop.query import mint_trace_id
        from hyperspace_tpu.telemetry import metrics

        lock = threading.Lock()
        done = threading.Event()
        state: Dict[str, Any] = {"winner": None, "table": None,
                                 "trace": None, "outstanding": 1}
        errs: Dict[str, Exception] = {}
        primary_note: Dict[str, Any] = {}

        def _runner(tag: str, req_spec: Dict[str, Any],
                    exclude: Optional[set], note: Optional[dict],
                    max_attempts: Optional[int] = None) -> None:
            try:
                # The hedge branch runs a SINGLE attempt: its job is
                # beating a slow primary, not re-running the whole retry
                # ladder in parallel.
                table = self._query_attempts(
                    req_spec, deadline_ms, deadline_at,
                    exclude=exclude, note=note, max_attempts=max_attempts)
            except Exception as exc:  # noqa: BLE001 — reported to caller
                with lock:
                    errs[tag] = exc
                    state["outstanding"] -= 1
                    if state["outstanding"] <= 0 \
                            and state["winner"] is None:
                        done.set()
            else:
                with lock:
                    state["outstanding"] -= 1
                    if state["winner"] is None:
                        state["winner"] = tag
                        state["table"] = table
                        state["trace"] = self.last_trace_id
                        done.set()
                    # else: the loser — its request_id lost the race and
                    # its fully-read response is dropped here.

        primary_spec = {**spec, "request_id": mint_trace_id()}
        threading.Thread(
            target=_runner, args=("primary", primary_spec, None,
                                  primary_note),
            name="hs-client-primary", daemon=True).start()

        delay_s = self._hedge_delay_s()
        remaining = self._remaining_ms(deadline_at)
        if remaining is not None:
            delay_s = min(delay_s, max(0.0, remaining / 1000.0))
        fired = False
        if not done.wait(delay_s) and len(self._endpoints) > 1:
            with lock:
                slow_primary = state["winner"] is None \
                    and state["outstanding"] > 0
                if slow_primary:
                    state["outstanding"] += 1
            if slow_primary:
                remaining = self._remaining_ms(deadline_at)
                if remaining is None or remaining > 5.0:
                    metrics.inc("client.hedge.sent")
                    fired = True
                    hedge_spec = {**spec, "request_id": mint_trace_id()}
                    threading.Thread(
                        target=_runner,
                        args=("hedge", hedge_spec,
                              set(primary_note.get("labels", set())),
                              None, 1),
                        name="hs-client-hedge", daemon=True).start()
                else:
                    with lock:
                        state["outstanding"] -= 1
        remaining = self._remaining_ms(deadline_at)
        # The attempts' socket timeouts are budget-bounded, so a small
        # grace past the deadline is enough for the threads to settle.
        done.wait(remaining / 1000.0 + 0.5 if remaining is not None
                  else None)
        with lock:
            if state["winner"] is not None:
                if fired and state["winner"] == "hedge":
                    metrics.inc("client.hedge.wins")
                self.last_trace_id = state["trace"]
                return state["table"]
            exc = errs.get("primary") or errs.get("hedge")
        if exc is not None:
            raise exc
        raise TimeoutError(
            f"deadline budget ({deadline_ms} ms) exhausted before any "
            f"attempt completed")

    def _hedge_delay_s(self) -> float:
        """The wait before hedging: the configured delay, or — when 0 —
        2× the latency EWMA clamped to [10 ms, 500 ms] (50 ms with no
        history yet)."""
        if self._hedge_delay_ms > 0.0:
            return self._hedge_delay_ms / 1000.0
        with self._lock:
            ewma = self._lat_ewma_ms
        if ewma <= 0.0:
            return 0.050
        return min(0.500, max(0.010, 2.0 * ewma / 1000.0))

    def _backoff(self, attempt: int, retry_after_ms: Optional[float],
                 deadline_at: Optional[float] = None) -> bool:
        """Jittered exponential backoff, honoring the server's
        ``retry-after-ms`` hint as the step when present — capped by
        what remains of the deadline budget.  Returns False when the
        budget cannot fund the sleep (the caller stops retrying)."""
        step = retry_after_ms if retry_after_ms is not None \
            else 50.0 * (2.0 ** (attempt - 1))
        delay_ms = min(self._backoff_cap_ms, step) * (0.5 + random.random())
        remaining = self._remaining_ms(deadline_at)
        if remaining is not None:
            if remaining <= 2.0:
                return False
            delay_ms = min(delay_ms, remaining - 1.0)
        time.sleep(delay_ms / 1000.0)
        return True

    def close(self) -> None:
        for ep in self._endpoints:
            ep.close_idle()
        if self._breaker_enabled:
            # The open-now gauge describes THIS client's live routing
            # table; with the client gone nothing is "open now" — a
            # stale nonzero would keep the doctor's client check
            # warning forever.
            from hyperspace_tpu.telemetry import metrics

            metrics.set_gauge("client.breaker.open_now", 0.0)

    def __enter__(self) -> "FleetQueryClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
