"""Arrow-IPC query server: one JSON request line in, one IPC stream out.

Wire protocol (deliberately minimal so any language can speak it with a
socket plus an Arrow library — no HTTP/gRPC dependency):

  client -> server   one JSON object (the interop/query.py spec),
                     UTF-8, terminated by a newline
  server -> client   the status line ``OK\\n`` followed by an Arrow IPC
                     STREAM of the result (self-delimiting), or
                     ``ERR <message>\\n`` and the connection closes

Connections are PIPELINED: after a successful response the client may send
the next request on the same connection (an error closes it, keeping
framing unambiguous).  Clients execute CONCURRENTLY — only the optimizer
step serializes (session-level state); a slow query does not stall other
connections.  The server executes against ONE session, so enabled indexes
and conf govern rewrites exactly as for local use — this is the parity
surface for the reference's py4j bindings / .NET sample
(python/hyperspace/hyperspace.py:9, examples/csharp/Program.cs): a JVM or
.NET client sends the JSON spec and reads the stream with its own Arrow
implementation.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Any, Dict, Optional, Tuple

import pyarrow as pa

MAX_REQUEST_BYTES = 1 << 20  # a query spec, not a data upload


REQUEST_TIMEOUT_S = 30.0  # an idle connection must not pin a thread + fd


class _Handler(socketserver.StreamRequestHandler):
    timeout = REQUEST_TIMEOUT_S  # StreamRequestHandler applies it pre-read

    def handle(self) -> None:
        # Pipelined: serve requests until EOF, idle timeout, or an error
        # response (errors close the connection so framing stays
        # unambiguous for simple clients).
        while self._serve_one():
            pass

    def _serve_one(self) -> bool:
        try:
            line = self.rfile.readline(MAX_REQUEST_BYTES + 1)
        except (TimeoutError, OSError):
            return False
        if not line:
            return False  # clean EOF between requests
        try:
            if len(line) > MAX_REQUEST_BYTES or not line.endswith(b"\n"):
                raise ValueError(
                    f"request exceeds {MAX_REQUEST_BYTES} bytes or is not "
                    f"newline-terminated")
            spec = json.loads(line.decode("utf-8"))
            if not isinstance(spec, dict):
                # A bare JSON string/array is valid JSON — and `"sql" in
                # spec` on a string would substring-match.
                raise ValueError("request must be a JSON object")
            # Concurrent execution is safe: the session serializes its
            # OPTIMIZE step internally (shared entry tags / schema memo);
            # the executor itself only reads shared state.
            if "verb" in spec:
                # Observability verbs: the PR 4 surface for remote clients
                # (docs/07-interop.md).  Same framing as queries — an
                # arrow table comes back — so existing clients need no
                # new code paths.
                table = _serve_verb(self.server.session, spec)
            elif "sql" in spec:
                # {"sql": "SELECT ...", "tables": {name: parquet_dir}} —
                # SQL text over the wire, the reference corpus's native
                # form (goldstandard/PlanStabilitySuite.scala:81-283).
                from hyperspace_tpu.sql import sql as run_sql

                if not isinstance(spec["sql"], str):
                    raise ValueError('"sql" must be a string')
                tables = spec.get("tables", {})
                if not isinstance(tables, dict) or not all(
                        isinstance(v, str) for v in tables.values()):
                    raise ValueError(
                        '"tables" must map names to parquet directory '
                        'paths over the wire')
                table = run_sql(self.server.session, spec["sql"],
                                tables=tables).collect()
            else:
                from hyperspace_tpu.interop.query import dataset_from_spec

                table = dataset_from_spec(
                    self.server.session, spec).collect()
        except Exception as exc:  # -> wire error, connection closes
            msg = str(exc).replace("\n", " ")[:500]
            try:
                self.wfile.write(f"ERR {msg}\n".encode("utf-8"))
            except OSError:
                pass
            return False
        try:
            self.wfile.write(b"OK\n")
            with pa.ipc.new_stream(self.wfile, table.schema) as writer:
                writer.write_table(table)
            self.wfile.flush()
            return True
        except OSError:
            return False  # client hung up mid-response


def _serve_verb(session, spec: Dict[str, Any]) -> pa.Table:
    """Non-query verbs of the wire protocol:

      {"verb": "metrics"}          -> (name, value) rows: counters/gauges
                                      flat, histograms flattened to
                                      name.count/name.sum/name.mean
      {"verb": "last_run_report"}  -> one row, column ``report_json`` —
                                      the serving session's most recent
                                      query report ON ANY THREAD is not
                                      knowable, so this returns the LAST
                                      report of the CONNECTION's thread
                                      (query then ask on one connection)
      {"verb": "workload"}         -> the captured advisor workload table
                                      (advisor/workload.py)
      {"verb": "perf_history"}     -> the persistent perf ledger
                                      (telemetry/perf_ledger.py): one row
                                      per recorded action/bench-section
                                      run under the serving session's
                                      systemPath
      {"verb": "build_report"}     -> one row, column ``report_json`` —
                                      the session's most recent action
                                      BuildReport (session-wide: builds
                                      are serialized by the log protocol)
    """
    verb = spec["verb"]
    if not isinstance(verb, str):
        raise ValueError('"verb" must be a string')
    if verb == "metrics":
        from hyperspace_tpu.telemetry import metrics as m

        names: list = []
        values: list = []

        def emit(name: str, value) -> None:
            if isinstance(value, (int, float)) and value is not None:
                names.append(name)
                values.append(float(value))

        for name, value in sorted(m.snapshot().items()):
            if isinstance(value, dict):  # histogram snapshot
                for part in ("count", "sum", "mean", "min", "max"):
                    if value.get(part) is not None:
                        emit(f"{name}.{part}", value[part])
            else:
                emit(name, value)
        return pa.table({"name": pa.array(names, type=pa.string()),
                         "value": pa.array(values, type=pa.float64())})
    if verb == "last_run_report":
        report = session.last_run_report_value
        payload = json.dumps(report.to_dict() if report is not None
                             else None)
        return pa.table({"report_json": pa.array([payload],
                                                 type=pa.string())})
    if verb == "workload":
        from hyperspace_tpu.advisor.workload import workload_table

        return workload_table(session.conf)
    if verb == "perf_history":
        from hyperspace_tpu.telemetry.perf_ledger import history_table

        return history_table(session.conf)
    if verb == "build_report":
        report = session.last_build_report_value
        payload = json.dumps(report.to_dict() if report is not None
                             else None)
        return pa.table({"report_json": pa.array([payload],
                                                 type=pa.string())})
    raise ValueError(f"Unknown verb {verb!r}; expected metrics, "
                     f"last_run_report, workload, perf_history, or "
                     f"build_report")


def _is_loopback(host: str) -> bool:
    if host == "localhost":
        return True
    if host == "":
        return False  # "" binds INADDR_ANY — every interface, most exposed
    import ipaddress

    try:
        return ipaddress.ip_address(host).is_loopback
    except ValueError:
        return False  # a hostname we can't classify: treat as remote


class QueryServer:
    """Threaded TCP server bound to ``session``.  ``port=0`` picks an
    ephemeral port (read it back from ``.address``)."""

    def __init__(self, session, host: str = "127.0.0.1",
                 port: int = 0, allow_remote: bool = False) -> None:
        # The server is UNAUTHENTICATED and reads any path the process can
        # access; binding a non-loopback interface exposes that to the
        # network.  Require the caller to say so explicitly.
        if not _is_loopback(host) and not allow_remote:
            raise ValueError(
                f"QueryServer binds {host!r}, a non-loopback interface, but "
                f"the protocol has no authentication: any peer that can "
                f"reach the port can read any file this process can.  Pass "
                f"allow_remote=True only behind a trusted network boundary.")

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self._server.session = session
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def start(self) -> "QueryServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="hs-query-server", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        # shutdown() blocks on serve_forever's exit handshake — calling it
        # on a never-started server would hang forever, so only do the
        # handshake when start() actually ran; server_close() alone
        # releases the socket either way.
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "QueryServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class MetricsScrapeServer:
    """Long-lived Prometheus scrape endpoint: ``GET /metrics`` serves the
    process metrics registry's text exposition
    (``telemetry/metrics.render_prometheus`` — the ``build.phase.*``,
    ``exec.*``, ``io.*`` catalog of docs/16-observability.md).

    This is the pull-based counterpart of the ``metrics`` verb: the verb
    answers an Arrow client once; this endpoint stays up for a scraper to
    poll on its own schedule — the ops surface ROADMAP item 2's serving
    layer reports through.  Same security posture as :class:`QueryServer`:
    loopback by default, ``allow_remote=True`` required to expose it
    (metrics leak workload shape, file counts, index names via series
    values).

    >>> with MetricsScrapeServer(port=9109) as ms:
    ...     ...  # curl http://127.0.0.1:9109/metrics
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 allow_remote: bool = False) -> None:
        if not _is_loopback(host) and not allow_remote:
            raise ValueError(
                f"MetricsScrapeServer binds {host!r}, a non-loopback "
                f"interface, without authentication.  Pass "
                f"allow_remote=True only behind a trusted boundary.")
        import http.server

        class _MetricsHandler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 — stdlib contract
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                from hyperspace_tpu.telemetry import metrics as m

                body = m.registry().render_prometheus().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; "
                                 "charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args: object) -> None:
                pass  # a scrape per second must not spam stderr

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _MetricsHandler)
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._server.server_address

    def start(self) -> "MetricsScrapeServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="hs-metrics-server", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self) -> "MetricsScrapeServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def request_query(address: Tuple[str, int],
                  spec: Dict[str, Any]) -> pa.Table:
    """Reference client (tests / Python callers): send ``spec``, return the
    result table.  Non-Python clients reimplement these ~10 lines with
    their socket + Arrow APIs."""
    with QueryClient(address) as client:
        return client.query(spec)


class QueryClient:
    """Persistent pipelined connection: successful ``query()`` calls ride
    one socket (the server answers each in order).  After an error
    response, a transport failure, or the server's idle timeout
    (REQUEST_TIMEOUT_S between requests) the server closes the connection
    — the client marks itself broken and subsequent calls raise
    ``ConnectionError`` asking for a fresh client, rather than failing
    with a confusing empty-status error on the dead socket."""

    def __init__(self, address: Tuple[str, int]) -> None:
        self._sock = socket.create_connection(address)
        self._f = self._sock.makefile("rb")
        self._broken = False

    def query(self, spec: Dict[str, Any]) -> pa.Table:
        if self._broken:
            raise ConnectionError(
                "connection closed by an earlier error or timeout; open a "
                "new QueryClient")
        try:
            self._sock.sendall(json.dumps(spec).encode("utf-8") + b"\n")
            status = self._f.readline().decode("utf-8").rstrip("\n")
        except OSError as exc:
            self._broken = True
            raise ConnectionError(f"connection lost: {exc}") from exc
        if not status.startswith("OK"):
            # ERR (server closes) or EOF (idle timeout / server gone).
            self._broken = True
            if not status:
                raise ConnectionError(
                    "server closed the connection (idle timeout or "
                    "shutdown); open a new QueryClient")
            raise RuntimeError(f"Query failed: {status}")
        with pa.ipc.open_stream(self._f) as reader:
            return reader.read_all()

    def close(self) -> None:
        self._f.close()
        self._sock.close()

    def __enter__(self) -> "QueryClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
