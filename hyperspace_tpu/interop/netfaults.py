"""Deterministic wire-fault injection at the interop socket seams.

The PR 1 fault injector (:mod:`hyperspace_tpu.io.faults`) covers every
storage seam; this module extends the same philosophy to the network
between :class:`~hyperspace_tpu.interop.server.FleetQueryClient`,
:class:`~hyperspace_tpu.interop.server.QueryServer`, and the proxy hop —
the one layer SIGKILL drills structurally cannot exercise, because a
killed process fails *cleanly* (RST on every socket) while real networks
fail *gray*: connections hang, frames tear mid-stream, latency balloons.

Four sites, armed exactly like store faults (``faults.install`` or the
``hyperspace.system.faultInjection.*`` conf keys, so subprocess fleets
arm them through a child's session conf):

``net.connect``
    :func:`connect` — the client dial.  ``refused`` raises
    ``ConnectionRefusedError``; ``reset`` raises
    ``ConnectionResetError``; ``black-hole`` hangs ``hang_s`` then
    raises ``TimeoutError`` (the SYN went nowhere); ``slow`` adds
    ``latency_ms`` before the real dial.
``net.send``
    :func:`send_all` — a framed send (the client's request line, or the
    server's status line + Arrow stream when a wire plan is armed).
    ``torn-frame`` delivers HALF the frame, then forces an RST — the
    peer sees a truncated stream, never a clean EOF; ``reset`` RSTs
    before any byte; ``black-hole`` hangs then times out; ``slow``
    delays then sends.
``net.recv``
    :func:`before_recv` — fired just before the client blocks on the
    response.  Same kinds as send (a recv-side ``torn-frame`` behaves
    as ``reset``: the torn bytes are the send side's job).
``net.accept``
    :func:`on_accept` — the server accept seam, shared by the threaded
    and async io modes.  ``reset`` RSTs the fresh connection;
    ``black-hole`` parks the socket open-but-silent (the client's own
    deadline must save it — the gray-failure case); other kinds pass
    through.  Never blocks: the async event loop calls this, and
    hslint's blocking-discipline rule covers that path.

Faults here raise ordinary ``OSError`` subclasses (never
``InjectedCrash``): a wire fault is survivable by design, and the whole
point is proving the retry/hedge/breaker machinery turns it into a
bit-equal answer from a survivor.

Disarmed cost is one ``is None`` check per seam call — and the server's
response path doesn't even reach that unless a wire plan is armed
(:func:`armed` gates the buffered-send detour).
"""

from __future__ import annotations

import socket
import struct
import time
from typing import List, Optional, Tuple

from hyperspace_tpu.io import faults

# Sockets parked by an armed ``net.accept`` black-hole: held here so the
# peer sees neither data nor FIN (a dropped reference would close the
# socket and helpfully RST the client — the opposite of a partition).
_PARKED: List[socket.socket] = []


def armed() -> bool:
    """True when the active fault plan targets a net.* site — the gate
    for the server's buffered-send detour (so the zero-fault hot path
    never pays the extra frame copy)."""
    plan = faults.active()
    return plan is not None and plan.site.startswith("net.")


def rst_close(sock: socket.socket) -> None:
    """Close with an RST instead of a FIN (SO_LINGER zero): the peer
    gets ``ECONNRESET`` mid-operation, exactly what a crashed kernel or
    a stateful middlebox timing out produces."""
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def clear_parked() -> None:
    """Release every black-holed socket (test/drill teardown)."""
    while _PARKED:
        try:
            _PARKED.pop().close()
        except OSError:
            pass


def connect(address: Tuple[str, int],
            timeout: Optional[float] = None) -> socket.socket:
    """``socket.create_connection`` with the ``net.connect`` seam."""
    plan = faults.net("net.connect")
    if plan is not None:
        if plan.kind == "refused":
            raise ConnectionRefusedError(
                f"injected: connection refused dialing {address}")
        if plan.kind in ("reset", "torn-frame"):
            raise ConnectionResetError(
                f"injected: connection reset dialing {address}")
        if plan.kind == "black-hole":
            time.sleep(max(0.0, plan.hang_s))
            raise TimeoutError(
                f"injected: black-hole dialing {address} (hung "
                f"{plan.hang_s:.3f}s)")
        # slow: the dial works, late.
        time.sleep(max(0.0, plan.latency_ms) / 1000.0)
    if timeout is not None:
        return socket.create_connection(address, timeout=timeout)
    return socket.create_connection(address)


def send_all(sock: socket.socket, data: bytes) -> None:
    """``sock.sendall(data)`` with the ``net.send`` seam.  ``torn-frame``
    lands exactly half the frame and then RSTs — the peer's decoder must
    see a truncated stream, never a short-but-valid one."""
    site = "net.send"
    plan = faults.net("net.send")
    if plan is None:
        sock.sendall(data)
        return
    if plan.kind == "slow":
        time.sleep(max(0.0, plan.latency_ms) / 1000.0)
        sock.sendall(data)
        return
    if plan.kind == "black-hole":
        time.sleep(max(0.0, plan.hang_s))
        raise TimeoutError(
            f"injected: black-hole at {site} (hung {plan.hang_s:.3f}s)")
    if plan.kind == "torn-frame":
        sock.sendall(data[:max(1, len(data) // 2)])
        rst_close(sock)
        raise ConnectionResetError(
            f"injected: torn frame at {site} — "
            f"{max(1, len(data) // 2)}/{len(data)} bytes landed, then RST")
    # reset / refused: the connection dies before any byte lands.
    rst_close(sock)
    raise ConnectionResetError(f"injected: connection reset at {site}")


def before_recv() -> None:
    """Client-side read seam, fired just before blocking on a response.
    ``slow`` delays the read; every failing kind surfaces as the
    exception a real dead/partitioned peer would produce."""
    site = "net.recv"
    plan = faults.net("net.recv")
    if plan is None:
        return
    if plan.kind == "slow":
        time.sleep(max(0.0, plan.latency_ms) / 1000.0)
        return
    if plan.kind == "black-hole":
        time.sleep(max(0.0, plan.hang_s))
        raise TimeoutError(
            f"injected: black-hole at {site} (hung {plan.hang_s:.3f}s)")
    raise ConnectionResetError(f"injected: connection reset at {site}")


def on_accept(sock: socket.socket) -> bool:
    """Server accept seam (both io modes).  Returns False when the
    connection was consumed by the fault (RST or parked) — the caller
    must not handle it further.  Block-free by contract: the async
    event loop calls this (hslint blocking-discipline)."""
    plan = faults.net("net.accept")
    if plan is None:
        return True
    if plan.kind in ("reset", "refused", "torn-frame"):
        rst_close(sock)
        return False
    if plan.kind == "black-hole":
        _PARKED.append(sock)  # open but silent: a partitioned server
        return False
    return True  # slow shapes the data path, not the accept
