"""Registry parsers: the single-source-of-truth artifacts the rules
check code against.

Everything here reads the checked-in sources with ``ast`` / text
parsing — never imports — so the registries are exactly what review
sees, not what a particular interpreter resolved.

  - conf registry: module-level ``NAME = "hyperspace..."`` constants in
    ``hyperspace_tpu/config.py`` plus its ``_FIELD_BY_KEY`` wiring
  - documented conf keys: the docs/02-configuration.md tables
  - telemetry catalog: the docs/16-observability.md metric and span
    tables (placeholder rows like ``rule.<slug>.applied`` become
    segment wildcards)
  - fault sites: the ``SITES`` tuple in ``hyperspace_tpu/io/faults.py``
  - wire codes: the ``ERR_* = "..."`` constants in
    ``hyperspace_tpu/interop/server.py``
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from hyperspace_tpu.lint.engine import LintContext

CONFIG_PATH = "hyperspace_tpu/config.py"
CONF_DOC_PATH = "docs/02-configuration.md"
OBS_DOC_PATH = "docs/16-observability.md"
FAULTS_PATH = "hyperspace_tpu/io/faults.py"
SERVER_PATH = "hyperspace_tpu/interop/server.py"

_CONF_KEY_RE = re.compile(r"^hyperspace\.[A-Za-z0-9_.]+$")
_DOC_KEY_RE = re.compile(r"`(hyperspace\.[A-Za-z0-9_.]+)`")


# ---------------------------------------------------------------------------
# Conf registry (config.py + docs/02)
# ---------------------------------------------------------------------------
def conf_registry(ctx: LintContext):
    """``(declared, wired, line_of, field_of)`` from config.py:
    ``declared`` maps key string -> constant name, ``wired`` is the set
    of key strings reachable through ``_FIELD_BY_KEY``, ``line_of`` maps
    key -> line, ``field_of`` maps key -> dataclass field name."""
    src = ctx.file(CONFIG_PATH)
    declared: Dict[str, str] = {}
    line_of: Dict[str, int] = {}
    wired: Set[str] = set()
    field_of: Dict[str, str] = {}
    if src is None or src.tree is None:
        return declared, wired, line_of, field_of
    const_to_key: Dict[str, str] = {}
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            value = node.value
            if isinstance(value, ast.Constant) and \
                    isinstance(value.value, str) and \
                    _CONF_KEY_RE.match(value.value):
                name = node.targets[0].id
                declared[value.value] = name
                line_of[value.value] = node.lineno
                const_to_key[name] = value.value
    # _FIELD_BY_KEY lives inside the dataclass body; keys are Name refs
    # to the module constants (or raw strings), values are field names.
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "_FIELD_BY_KEY" \
                and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                key = None
                if isinstance(k, ast.Name) and k.id in const_to_key:
                    key = const_to_key[k.id]
                elif isinstance(k, ast.Constant) and \
                        isinstance(k.value, str):
                    key = k.value
                if key is None:
                    continue
                wired.add(key)
                if isinstance(v, ast.Constant) and \
                        isinstance(v.value, str):
                    field_of[key] = v.value
    return declared, wired, line_of, field_of


def documented_conf_keys(ctx: LintContext) -> Dict[str, int]:
    """Conf keys documented in docs/02 TABLE ROWS (first cell), key ->
    line number."""
    text = ctx.read_doc(CONF_DOC_PATH) or ""
    out: Dict[str, int] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        if not line.lstrip().startswith("|"):
            continue
        first_cell = line.split("|")[1] if line.count("|") >= 2 else ""
        for m in _DOC_KEY_RE.finditer(first_cell):
            out.setdefault(m.group(1), i)
    return out


# ---------------------------------------------------------------------------
# Telemetry catalog (docs/16)
# ---------------------------------------------------------------------------
_TOKEN_RE = re.compile(r"`([A-Za-z0-9_.<>-]+)`")
_PLACEHOLDER_SEG_RE = re.compile(r"^<[A-Za-z0-9_]+>$")


def _expand_cell_tokens(cell: str) -> List[str]:
    """Backticked names from one table cell, expanding the catalog's
    leading-dot shorthand: ``advisor.capture.dropped`` / ``.errors``
    means advisor.capture.errors (the shorthand replaces that many
    trailing segments of the cell's first full token)."""
    tokens = _TOKEN_RE.findall(cell)
    out: List[str] = []
    anchor: Optional[str] = None
    for tok in tokens:
        if tok.startswith("."):
            if anchor is None:
                continue  # malformed; the reverse check will catch drift
            short = tok[1:].split(".")
            base = anchor.split(".")
            if len(short) >= len(base):
                continue
            out.append(".".join(base[:-len(short)] + short))
        else:
            out.append(tok)
            if anchor is None:
                anchor = tok
    return out


def _table_first_cells(text: str, start_heading: str,
                       stop_prefix: str = "#") -> List[Tuple[str, int]]:
    """(first-cell, line) of each table row between ``start_heading`` and
    the next heading."""
    lines = text.splitlines()
    out: List[Tuple[str, int]] = []
    in_section = False
    for i, line in enumerate(lines, start=1):
        if line.strip().startswith(start_heading):
            in_section = True
            continue
        if in_section and line.startswith(stop_prefix):
            break
        if in_section and line.lstrip().startswith("|") \
                and line.count("|") >= 2:
            cell = line.split("|")[1]
            if set(cell.strip()) <= {"-", ":", " "}:
                continue  # separator row
            out.append((cell, i))
    return out


def telemetry_catalog(ctx: LintContext):
    """``(metrics, spans)``: each a dict of catalog name (may contain
    ``<placeholder>`` segments) -> docs/16 line number."""
    text = ctx.read_doc(OBS_DOC_PATH) or ""
    metrics: Dict[str, int] = {}
    spans: Dict[str, int] = {}
    for cell, line in _table_first_cells(text, "| Metric "):
        for tok in _expand_cell_tokens(cell):
            metrics.setdefault(tok, line)
    for cell, line in _table_first_cells(text, "| Span "):
        for tok in _expand_cell_tokens(cell):
            spans.setdefault(tok, line)
    return metrics, spans


_MD_LINK_RE = re.compile(r"\[([^\]]+)\]\([^)]*\)")


def metric_help_entries() -> List[Tuple[str, str]]:
    """RUNTIME view of the docs/16 metric catalog for the Prometheus
    ``# HELP`` lines (``telemetry/metrics.render_prometheus``):
    ``(name-pattern, help-text)`` pairs, parsed from the same table the
    telemetry-catalog lint rule enforces — one registry, two consumers.
    Reads the repo-relative docs (no :class:`LintContext` needed); an
    installed package without ``docs/`` simply yields no entries."""
    root = __file__
    for _ in range(3):  # lint/catalog.py -> lint -> hyperspace_tpu -> repo
        root = os.path.dirname(root)
    try:
        with open(os.path.join(root, OBS_DOC_PATH),
                  "r", encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return []
    out: List[Tuple[str, str]] = []
    lines = text.splitlines()
    for cell, lineno in _table_first_cells(text, "| Metric "):
        row = lines[lineno - 1]
        cells = [c.strip() for c in row.split("|")]
        doc = cells[-2] if len(cells) >= 4 else ""
        doc = _MD_LINK_RE.sub(r"\1", doc).replace("`", "")
        doc = " ".join(doc.split())
        for tok in _expand_cell_tokens(cell):
            out.append((tok, doc))
    return out


def _segs(name: str) -> List[str]:
    return name.split(".")


def name_matches_entry(name: str, entry: str) -> bool:
    """Does a concrete-or-pattern usage name match a catalog entry?
    ``name`` segments of ``\\x00``-bearing text are wildcards (from
    f-strings); entry segments like ``<slug>`` are placeholders."""
    a, b = _segs(name), _segs(entry)
    if len(a) != len(b):
        return False
    for ua, ub in zip(a, b):
        if "\x00" in ua or _PLACEHOLDER_SEG_RE.match(ub):
            continue
        if ua != ub:
            return False
    return True


def entry_concrete(entry: str) -> bool:
    return not any(_PLACEHOLDER_SEG_RE.match(s) for s in _segs(entry))


# ---------------------------------------------------------------------------
# Fault sites (io/faults.py) and wire codes (interop/server.py)
# ---------------------------------------------------------------------------
def fault_sites(ctx: LintContext) -> Tuple[Set[str], int]:
    """The declared fault-site registry: the ``SITES`` tuple in
    io/faults.py, plus the line it is declared on."""
    src = ctx.file(FAULTS_PATH)
    if src is None or src.tree is None:
        return set(), 0
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "SITES":
            value = node.value
            if isinstance(value, (ast.Tuple, ast.List)):
                sites = {e.value for e in value.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str)}
                return sites, node.lineno
    return set(), 0


def wire_codes(ctx: LintContext) -> Set[str]:
    """The ERR taxonomy: values of module-level ``ERR_* = "..."``
    constants in interop/server.py."""
    src = ctx.file(SERVER_PATH)
    out: Set[str] = set()
    if src is None or src.tree is None:
        return out
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id.startswith("ERR_") \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            out.add(node.value.value)
    return out


# ---------------------------------------------------------------------------
# Bench-trace span check (the CI smoke's contract, ex-grep)
# ---------------------------------------------------------------------------
# Span kinds a toy bench run MUST leave in its JSONL trace: the end-to-end
# proof that tracing, the optimizer rules, the build profiler, the advisor,
# and the serving layer all actually emitted.  Kept next to the catalog
# parser so the list and the docs/16 taxonomy are checked together
# (lint --check-catalog --trace <file>).
REQUIRED_BENCH_SPANS = (
    "bench.setup",
    "bench.sf1_queries",
    "query.collect",
    "optimize",
    "optimize.rule.filter",
    "execute",
    "exec.scan",
    "io.read",
    "bench.advisor",
    "advisor.whatif",
    "bench.build_profile",
    "action.CreateAction",
    "build.phase.read",
    "build.phase.write",
    "build.phase.spill_route",
    "build.phase.spill_finish",
    "bench.multichip",
    "bench.serving",
    "serve.request",
    "bench.flight_recorder",
    "bench.alerts",
    "alert.evaluate",
    "alert.capture",
    "bench.fleet_obs",
    "fleet.publish",
    "bench.ingest",
    "lifecycle.cycle",
    "bench.timeline",
    "timeline.export",
    "doctor.run",
)


def check_trace(path: str, span_entries: Sequence[str]) -> List[str]:
    """Problems with a bench JSONL trace: required span kinds missing,
    and span names present in the trace but absent from the docs/16
    taxonomy (catalog drift the old CI grep could never see)."""
    import json as _json

    seen: Set[str] = set()

    def walk(span: dict) -> None:
        name = span.get("name")
        if isinstance(name, str):
            seen.add(name)
        for child in span.get("children", ()) or ():
            if isinstance(child, dict):
                walk(child)

    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    walk(_json.loads(line))
                except ValueError:
                    continue  # torn line (SIGTERM mid-write) — tolerated
    except OSError as e:
        return [f"cannot read trace {path}: {e}"]

    problems = [f"required span kind missing from trace: {name}"
                for name in REQUIRED_BENCH_SPANS if name not in seen]
    for name in sorted(seen):
        if not any(name_matches_entry(name, e) for e in span_entries):
            problems.append(
                f"trace span {name!r} is not in the docs/16 span taxonomy")
    return problems
