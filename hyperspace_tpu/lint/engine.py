"""The hslint rule engine: file loading, pragmas, baseline, reporting.

Design constraints:

  - **stdlib only** — the linter must run where the engine cannot (a CI
    step before dependencies install, a pre-commit hook); it parses the
    package with ``ast`` and never imports it.
  - **stable fingerprints** — a finding's identity is
    ``rule:path:ident`` where ``ident`` is a rule-chosen salient token
    (the conf key, the metric name, the function holding the bare
    except), NOT the line number, so a checked-in baseline survives
    unrelated edits above the finding.
  - **inline allowlist** — ``# hslint: allow[rule-a,rule-b] reason`` on
    the finding's line (or the line above) suppresses it; on a ``def``
    line it suppresses the whole function body for those rules.  The
    free-text reason is required by convention, not parsing.
  - **baseline** — ``.hslint-baseline.json`` at the repo root records
    grandfathered fingerprints.  A run fails only on NEW findings;
    entries that stopped firing are reported as expired so the file
    shrinks over time (``--update-baseline`` rewrites it).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

_PRAGMA_RE = re.compile(r"#\s*hslint:\s*allow\[([A-Za-z0-9_,\s-]+)\]")

# Directories never scanned (generated / VCS / caches).
_SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "node_modules",
              ".hypothesis"}


@dataclasses.dataclass
class Finding:
    """One rule violation at one site."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    ident: str  # stable salient token; fingerprint = rule:path:ident
    baselined: bool = False

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}:{self.path}:{self.ident}"

    def to_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "fingerprint": self.fingerprint,
            "baselined": self.baselined,
        }


class SourceFile:
    """One parsed python file: text, AST, and pragma index."""

    def __init__(self, root: str, relpath: str) -> None:
        self.relpath = relpath.replace(os.sep, "/")
        self.abspath = os.path.join(root, relpath)
        with open(self.abspath, "r", encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.parse_error: Optional[str] = None
        try:
            self.tree: Optional[ast.Module] = ast.parse(
                self.text, filename=self.relpath)
        except SyntaxError as e:  # surfaced as a finding by the runner
            self.tree = None
            self.parse_error = f"syntax error: {e.msg} (line {e.lineno})"
        # line (1-based) -> set of rule names allowed there ("*" = all)
        self.pragmas: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                self.pragmas[i] = rules or {"*"}
        # (start, end, rules) spans for pragmas sitting on a def/class line:
        # the allowance covers the whole body.
        self.pragma_spans: List[Tuple[int, int, Set[str]]] = []
        if self.tree is not None and self.pragmas:
            for node in ast.walk(self.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    rules = self.pragmas.get(node.lineno)
                    if rules:
                        end = getattr(node, "end_lineno", node.lineno)
                        self.pragma_spans.append((node.lineno, end, rules))

    def allows(self, rule: str, line: int) -> bool:
        for probe in (line, line - 1):
            rules = self.pragmas.get(probe)
            if rules and (rule in rules or "*" in rules):
                return True
        for start, end, rules in self.pragma_spans:
            if start <= line <= end and (rule in rules or "*" in rules):
                return True
        return False


class LintContext:
    """Everything a rule needs: the parsed file set plus doc loading."""

    def __init__(self, root: str, files: Sequence[SourceFile]) -> None:
        self.root = root
        self.files = list(files)
        self._by_path = {f.relpath: f for f in self.files}
        self._doc_cache: Dict[str, Optional[str]] = {}

    def file(self, relpath: str) -> Optional[SourceFile]:
        return self._by_path.get(relpath)

    def read_doc(self, relpath: str) -> Optional[str]:
        if relpath not in self._doc_cache:
            path = os.path.join(self.root, relpath)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    self._doc_cache[relpath] = f.read()
            except OSError:
                self._doc_cache[relpath] = None
        return self._doc_cache[relpath]

    def py_files(self, include=None, exclude=None) -> List[SourceFile]:
        """Files filtered by repo-relative prefix (or exact path).  A
        prefix ending in "/" matches the subtree; otherwise exact."""
        def matches(path: str, pats) -> bool:
            return any(path == p or (p.endswith("/") and path.startswith(p))
                       for p in pats)

        out = []
        for f in self.files:
            if include is not None and not matches(f.relpath, include):
                continue
            if exclude is not None and matches(f.relpath, exclude):
                continue
            out.append(f)
        return out


def discover_files(root: str) -> List[str]:
    """Repo-relative paths of every .py file under ``root``."""
    out: List[str] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.relpath(os.path.join(dirpath, fn), root))
    return out


def build_context(root: str,
                  relpaths: Optional[Iterable[str]] = None) -> LintContext:
    paths = list(relpaths) if relpaths is not None else discover_files(root)
    return LintContext(root, [SourceFile(root, p) for p in paths])


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------
BASELINE_NAME = ".hslint-baseline.json"


def load_baseline(path: str) -> Set[str]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return set()
    return set(data.get("entries", []))


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    from hyperspace_tpu.lint.rules import CATALOG_VERSION

    entries = sorted({f.fingerprint for f in findings})
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "catalog_version": CATALOG_VERSION,
                   "entries": entries}, f, indent=2)
        f.write("\n")


# ---------------------------------------------------------------------------
# Running
# ---------------------------------------------------------------------------
def run_lint(root: str, rule_names: Optional[Sequence[str]] = None,
             baseline: Optional[Set[str]] = None,
             ctx: Optional[LintContext] = None):
    """Run the selected rules over ``root``.

    Returns ``(findings, expired)``: findings sorted by path/line with
    ``baselined`` set on grandfathered ones, and the baseline
    fingerprints that no longer fire."""
    from hyperspace_tpu.lint.rules import all_rules

    rules = all_rules()
    if rule_names:
        unknown = set(rule_names) - {r.name for r in rules}
        if unknown:
            raise ValueError(
                f"unknown rule(s): {', '.join(sorted(unknown))}; "
                f"known: {', '.join(r.name for r in rules)}")
        rules = [r for r in rules if r.name in set(rule_names)]
    if ctx is None:
        ctx = build_context(root)

    findings: List[Finding] = []
    for f in ctx.files:
        if f.parse_error:
            findings.append(Finding("parse", f.relpath, 1, f.parse_error,
                                    ident="syntax"))
    for rule in rules:
        for finding in rule.run(ctx):
            src = ctx.file(finding.path)
            if src is not None and src.allows(finding.rule, finding.line):
                continue
            findings.append(finding)

    baseline = baseline or set()
    seen = set()
    for f in findings:
        if f.fingerprint in baseline:
            f.baselined = True
        seen.add(f.fingerprint)
    expired = sorted(baseline - seen)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.ident))
    return findings, expired


def render_human(findings: Sequence[Finding], expired: Sequence[str],
                 rule_names: Sequence[str]) -> str:
    lines: List[str] = []
    new = [f for f in findings if not f.baselined]
    old = [f for f in findings if f.baselined]
    for f in new:
        lines.append(f"{f.path}:{f.line}: [{f.rule}] {f.message}")
    if old:
        lines.append(f"({len(old)} baselined finding(s) suppressed; "
                     f"run with --show-baselined to list)")
    for fp in expired:
        lines.append(f"baseline entry no longer fires (remove it or run "
                     f"--update-baseline): {fp}")
    lines.append(
        f"hslint: {len(new)} new finding(s), {len(old)} baselined, "
        f"{len(expired)} expired baseline entr{'y' if len(expired) == 1 else 'ies'} "
        f"[rules: {', '.join(rule_names)}]")
    return "\n".join(lines)


def render_json(findings: Sequence[Finding], expired: Sequence[str],
                rule_names: Sequence[str], root: str) -> str:
    new = [f for f in findings if not f.baselined]
    return json.dumps({
        "version": 1,
        "root": root,
        "rules": list(rule_names),
        "findings": [f.to_dict() for f in findings],
        "new_count": len(new),
        "baselined_count": len(findings) - len(new),
        "expired_baseline": list(expired),
    }, indent=2)


# ---------------------------------------------------------------------------
# Shared AST helpers (used by several rules)
# ---------------------------------------------------------------------------
def call_name(node: ast.Call) -> str:
    """Dotted name of a call target: ``os.path.join`` -> "os.path.join",
    ``open`` -> "open"; "" when the callee is not a plain name chain."""
    parts: List[str] = []
    cur = node.func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def joined_pattern(node: ast.AST) -> Optional[str]:
    """An f-string as a dotted pattern: each interpolated piece becomes a
    ``\\x00`` marker (segment-level wildcard after splitting on ".").
    Returns None for non-JoinedStr nodes."""
    if not isinstance(node, ast.JoinedStr):
        return None
    parts: List[str] = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        else:
            parts.append("\x00")
    return "".join(parts)


def enclosing_function_name(tree: ast.Module, lineno: int) -> str:
    """Name of the innermost def containing ``lineno`` ("<module>" when
    none) — a line-stable ident component for baselining."""
    best = "<module>"
    best_span = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= lineno <= end:
                span = end - node.lineno
                if best_span is None or span < best_span:
                    best, best_span = node.name, span
    return best
