"""CLI: ``python -m hyperspace_tpu.lint``.

Exit codes: 0 clean (new findings all absent), 1 new violations (or a
failed --trace check), 2 usage/internal error.  ``--sarif`` adds a
side-channel artifact and changes no exit code; ``--fix`` applies the
mechanical hygiene autofixes (``--fix --dry-run`` previews the diff)
and exits by the POST-fix finding count.
"""

from __future__ import annotations

import argparse
import os
import sys

from hyperspace_tpu.lint import engine


def _detect_root(explicit: str | None) -> str:
    if explicit:
        return os.path.abspath(explicit)
    cwd = os.getcwd()
    if os.path.exists(os.path.join(cwd, "hyperspace_tpu", "config.py")):
        return cwd
    # Fall back to the repo the installed package lives in.
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m hyperspace_tpu.lint",
        description="AST-based invariant checker for the hyperspace-tpu "
                    "contracts (docs/18-static-analysis.md)")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detect)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--json", action="store_true", help="machine output")
    p.add_argument("--baseline", default=None,
                   help=f"baseline path (default <root>/"
                        f"{engine.BASELINE_NAME})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding as new")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to the current findings "
                        "and exit 0")
    p.add_argument("--show-baselined", action="store_true")
    p.add_argument("--fix", action="store_true",
                   help="apply the mechanical hygiene autofixes (dead/"
                        "duplicate/redundant imports, mutable default "
                        "args), then relint")
    p.add_argument("--dry-run", action="store_true",
                   help="with --fix: print the unified diff, write "
                        "nothing")
    p.add_argument("--sarif", default=None, metavar="PATH",
                   help="also write findings as SARIF 2.1.0 (CI PR "
                        "annotation); exit codes unchanged")
    p.add_argument("--check-catalog", action="store_true",
                   help="run only the telemetry-catalog rule (the docs/16 "
                        "contract); combine with --trace")
    p.add_argument("--trace", default=None, metavar="JSONL",
                   help="also verify a bench JSONL trace: required span "
                        "kinds present, every span in the docs/16 taxonomy")
    args = p.parse_args(argv)

    from hyperspace_tpu.lint.rules import all_rules

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name:22s} {r.description}")
        return 0

    rule_names = None
    if args.check_catalog:
        rule_names = ["telemetry-catalog"]
    elif args.rules:
        rule_names = [r.strip() for r in args.rules.split(",") if r.strip()]

    root = _detect_root(args.root)
    baseline_path = args.baseline or os.path.join(root, engine.BASELINE_NAME)
    baseline = set() if args.no_baseline \
        else engine.load_baseline(baseline_path)

    try:
        ctx = engine.build_context(root)
        findings, expired = engine.run_lint(root, rule_names, baseline,
                                            ctx=ctx)
    except ValueError as e:
        print(f"hslint: {e}", file=sys.stderr)
        return 2

    if args.fix:
        from hyperspace_tpu.lint import fix as fixer

        fixes = fixer.plan_fixes(ctx, findings)
        if args.dry_run:
            for fx in fixes:
                sys.stdout.write(fx.diff())
            print(f"hslint --fix --dry-run: {sum(len(f.applied) for f in fixes)} "
                  f"finding(s) fixable across {len(fixes)} file(s); "
                  f"nothing written")
            return 0
        fixer.apply_fixes(root, fixes)
        for fx in fixes:
            print(f"fixed {len(fx.applied)} finding(s) in {fx.relpath}")
        # Relint from disk: the exit code reflects the post-fix state,
        # and a fix that broke a file (syntax) surfaces immediately.
        ctx = engine.build_context(root)
        findings, expired = engine.run_lint(root, rule_names, baseline,
                                            ctx=ctx)

    if args.update_baseline:
        engine.write_baseline(baseline_path, findings)
        print(f"hslint: baseline rewritten with {len(findings)} "
              f"entr{'y' if len(findings) == 1 else 'ies'} at "
              f"{baseline_path}")
        return 0

    active = [r.name for r in rules] if rule_names is None else rule_names
    if args.sarif:
        from hyperspace_tpu.lint import sarif

        # A CI artifact at a user-chosen path, like the trace sink.
        # hslint: allow[io-seam] SARIF artifact, not index data
        with open(args.sarif, "w", encoding="utf-8") as f:
            f.write(sarif.render_sarif(
                findings, [r for r in rules if r.name in set(active)],
                root))
    trace_problems = []
    if args.trace:
        from hyperspace_tpu.lint import catalog

        _metrics, spans = catalog.telemetry_catalog(ctx)
        trace_problems = catalog.check_trace(args.trace, list(spans))

    if args.json:
        print(engine.render_json(findings, expired, active, root))
        if trace_problems:
            for prob in trace_problems:
                print(f"trace: {prob}", file=sys.stderr)
    else:
        shown = findings if args.show_baselined \
            else [f for f in findings if not f.baselined]
        if args.show_baselined:
            for f in shown:
                mark = " (baselined)" if f.baselined else ""
                print(f"{f.path}:{f.line}: [{f.rule}] {f.message}{mark}")
            new = [f for f in findings if not f.baselined]
            print(f"hslint: {len(new)} new, "
                  f"{len(findings) - len(new)} baselined")
            for fp in expired:
                print(f"expired baseline entry: {fp}")
        else:
            print(engine.render_human(findings, expired, active))
        for prob in trace_problems:
            print(f"trace: {prob}")

    new_count = sum(1 for f in findings if not f.baselined)
    return 1 if (new_count or trace_problems) else 0


if __name__ == "__main__":
    sys.exit(main())
