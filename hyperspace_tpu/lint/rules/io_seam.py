"""io-seam: file-mutating primitives must route through ``io/``.

PR 2's LogStore seam and PR 1's fault injector give the engine its
crash-consistency story — but only for IO that goes THROUGH them.  A
stray ``open(path, "w")`` / ``os.replace`` / ``shutil.rmtree`` in the
action or index layers mutates index/log state invisibly to the fault
matrix: the tests keep passing while the failure envelope silently
shrinks.  This rule flags write-side primitives outside the sanctioned
modules:

  - ``hyperspace_tpu/io/`` — the seam itself;
  - ``hyperspace_tpu/index/log_manager.py`` — the POSIX log backend,
    whose primitives are fault-wrapped in place;
  - ``hyperspace_tpu/sources/`` — lake-format writers for EXTERNAL
    metadata (Delta/Iceberg test fixtures), not index data;
  - ``hyperspace_tpu/native/`` — the compiler cache, not index data.

Read-only ``open(path)`` is allowed everywhere (reads cannot corrupt,
and the data-read fault sites live in the parquet readers).  A genuine
exception (a telemetry sink appending to a user-chosen path) carries an
inline ``# hslint: allow[io-seam] <reason>`` pragma.
"""

from __future__ import annotations

import ast
from typing import List

from hyperspace_tpu.lint.engine import (
    Finding,
    LintContext,
    call_name,
    const_str,
    enclosing_function_name,
)

_SCAN_INCLUDE = ("hyperspace_tpu/",)
_SCAN_EXCLUDE = (
    "hyperspace_tpu/io/",
    "hyperspace_tpu/index/log_manager.py",
    "hyperspace_tpu/sources/",
    "hyperspace_tpu/native/",
    "hyperspace_tpu/lint/",
)

_BANNED_CALLS = {
    "os.rename", "os.replace", "os.remove", "os.unlink", "os.rmdir",
    "os.truncate", "os.open",
    "shutil.rmtree", "shutil.move", "shutil.copy", "shutil.copy2",
    "shutil.copyfile", "shutil.copytree",
}
_WRITE_MODE_CHARS = set("wxa+")


def _open_write_mode(node: ast.Call) -> str:
    """The write-ish mode string of an ``open()`` call, or ""."""
    mode = None
    if len(node.args) >= 2:
        mode = const_str(node.args[1])
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = const_str(kw.value)
    if mode and set(mode) & _WRITE_MODE_CHARS:
        return mode
    return ""


class Rule:
    name = "io-seam"
    description = ("no direct file-mutation primitives outside io/ (the "
                   "LogStore seam and fault injector must see every write)")

    def run(self, ctx: LintContext) -> List[Finding]:
        findings: List[Finding] = []
        for src in ctx.py_files(include=_SCAN_INCLUDE,
                                exclude=_SCAN_EXCLUDE):
            if src.tree is None:
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                cname = call_name(node)
                fn = None
                if cname in _BANNED_CALLS:
                    fn = enclosing_function_name(src.tree, node.lineno)
                    findings.append(Finding(
                        self.name, src.relpath, node.lineno,
                        f"direct {cname}() in {fn}() bypasses the io/ seam "
                        f"(fault sites, retries, digests) — route through "
                        f"io/files.py or io/parquet.py",
                        ident=f"{cname}:{fn}"))
                elif cname == "open":
                    mode = _open_write_mode(node)
                    if mode:
                        fn = enclosing_function_name(src.tree, node.lineno)
                        findings.append(Finding(
                            self.name, src.relpath, node.lineno,
                            f"direct open(..., {mode!r}) in {fn}() bypasses "
                            f"the io/ seam — route writes through io/",
                            ident=f"open-write:{fn}"))
        return findings
