"""blocking-discipline: the serving path cannot stall behind a lock, an
accept loop, or an unchecked deadline.

Three call-graph-powered analyses (lint/callgraph.py — these are the
checks PR 8's single-module rules could not express):

  1. **lock-held blocking** — in the thread-shared modules
     (``interop/server.py``, ``telemetry/``,
     ``execution/plan_cache.py``), no blocking primitive may be
     REACHABLE while a lock is held: a socket send/recv, a LogStore
     ``put/read/list/delete``, ``time.sleep``, parquet/file IO, or a
     write-mode ``open``.  The query propagates the lexical with-lock
     context across call edges (cycle-tolerant), so a helper three
     frames deep that appends to the perf ledger still convicts the
     locked caller — the PR 8 EWMA lost-update shape, generalized from
     "mutate under the lock" to "never BLOCK under it".  A finding
     names the whole witness chain.
  2. **block-free paths** — the accept loop (``process_request``,
     ``_acquire_conn``/``_release_conn``) and the inline-verb surface
     (``_serve_verb``) must stay free of store/file IO*, sleeps, and
     query execution (``Executor.execute``/``collect``): they are what
     still answers while the admission queue sheds, so anything slow
     here is an outage amplifier.  (*The verb surface reads the perf
     ledger / decision journal by design — store READS are allowed
     there; the accept loop allows only its bounded, timeout-guarded
     reject send.)
  3. **deadline discipline** — the PR 9 exit-check bug class, caught
     statically: ``Executor._execute_node`` must open with a deadline
     check, ``Executor.execute`` must re-check AFTER the dispatch
     (entry-only checks all ran on the way down), the worker loop must
     establish a ``deadline.scope`` around job execution, and operator
     handlers (``Executor._execute_*``) may only be dispatched from
     inside executor.py — an external caller would bypass the checked
     dispatcher entirely.

Deliberate exceptions carry an entry in ALLOW below (reason required)
or an inline ``# hslint: allow[blocking-discipline] <reason>`` pragma.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.lint import callgraph
from hyperspace_tpu.lint.engine import Finding, LintContext

# Modules whose locks must never be held across a blocking call.
LOCKED_MODULES = (
    "hyperspace_tpu/interop/server.py",
    "hyperspace_tpu/telemetry/",
    "hyperspace_tpu/execution/plan_cache.py",
)

# (path, function qualname, check) -> reason.
ALLOW: Dict[Tuple[str, str, str], str] = {
    ("hyperspace_tpu/telemetry/trace.py", "JsonlTraceSink.emit",
     "lock-held-blocking"):
        "the sink lock EXISTS to serialize appends/rotation of one "
        "local line-buffered file; contention is bounded by trace "
        "volume, and the lock is private to the sink",
}

_SOCKET_METHODS = {"sendall", "recv", "recv_into", "accept", "connect"}
_STORE_METHODS = {"put", "put_if_absent", "put_if_generation_match",
                  "read", "list_keys", "delete"}
_STORE_READ_METHODS = {"read", "list_keys"}
_IO_PATHS = (
    "hyperspace_tpu/io/parquet.py",
    "hyperspace_tpu/io/files.py",
    "hyperspace_tpu/io/log_store.py",
    "hyperspace_tpu/io/avro.py",
)

_EXEC_TARGETS = (
    "hyperspace_tpu/execution/executor.py::Executor.execute",
    "hyperspace_tpu/dataset.py::Dataset.collect",
)


def _blocking_kind(site: callgraph.CallSite,
                   allow_store_reads: bool = False) -> Optional[str]:
    """What blocks at this call site ("" -> not blocking)."""
    n = site.name
    if n == "time.sleep" or n.endswith(".sleep"):
        return "time.sleep()"
    last = n.rsplit(".", 1)[-1]
    if "." in n and last in _SOCKET_METHODS:
        return f"socket .{last}()"
    if "." in n and last in _STORE_METHODS:
        receiver = n.rsplit(".", 1)[0].lower()
        if "store" in receiver:
            if allow_store_reads and last in _STORE_READ_METHODS:
                return None
            return f"store .{last}()"
    for t in site.targets:
        path, qual = t.split("::", 1)
        if path in _IO_PATHS:
            return f"io call {qual}()"
    if n == "open":
        return "open()"
    return None


class Rule:
    name = "blocking-discipline"
    description = ("no blocking call reachable under a lock; accept "
                   "loop and inline verbs block-free; every executor "
                   "dispatch path deadline-checked")

    def run(self, ctx: LintContext) -> List[Finding]:
        graph = callgraph.for_context(ctx)
        findings: List[Finding] = []
        self._check_lock_held(ctx, graph, findings)
        self._check_block_free(ctx, graph, findings)
        self._check_deadlines(ctx, graph, findings)
        return [f for f in findings if not self._allowed(f)]

    def _allowed(self, f: Finding) -> bool:
        parts = f.ident.split(":")
        check = parts[0]
        qual = parts[1] if len(parts) > 1 else ""
        return (f.path, qual, check) in ALLOW

    # -- 1: lock-held blocking ----------------------------------------------
    def _check_lock_held(self, ctx, graph, findings) -> None:
        for src in ctx.py_files(include=LOCKED_MODULES):
            if src.tree is None or \
                    src.relpath.startswith("hyperspace_tpu/lint/"):
                continue
            for info in graph.functions_in(src.relpath):
                for site in graph.sites_of(info.fid):
                    if not site.locks:
                        continue
                    kind = _blocking_kind(site)
                    if kind:
                        findings.append(Finding(
                            self.name, src.relpath, site.line,
                            f"[lock-held-blocking] {kind} while holding "
                            f"{self._lock_names(site)} in "
                            f"{info.qualname}() — every other thread "
                            f"needing the lock stalls behind the IO",
                            ident=f"lock-held-blocking:{info.qualname}:"
                                  f"{site.name}"))
                        continue
                    for target in site.targets:
                        hit = graph.find_path(
                            target, lambda s: bool(_blocking_kind(s)))
                        if hit is None:
                            continue
                        chain, blocked = hit
                        findings.append(Finding(
                            self.name, src.relpath, site.line,
                            f"[lock-held-blocking] "
                            f"{_blocking_kind(blocked)} reachable while "
                            f"holding {self._lock_names(site)}: "
                            f"{info.qualname} -> "
                            f"{callgraph.describe_chain(graph, chain, blocked)}",
                            ident=f"lock-held-blocking:{info.qualname}:"
                                  f"{site.name}"))
                        break

    @staticmethod
    def _lock_names(site: callgraph.CallSite) -> str:
        return ", ".join(lk.split(":", 1)[1] for lk in site.locks)

    # -- 2: block-free paths -------------------------------------------------
    def _check_block_free(self, ctx, graph, findings) -> None:
        server = "hyperspace_tpu/interop/server.py"
        contracts = []  # (info, allow_store_reads, allow_bounded_send, label)
        for info in graph.functions_in(server):
            if info.name in ("process_request", "_acquire_conn",
                             "_release_conn"):
                contracts.append((info, False, True,
                                  "the accept loop"))
            elif info.name == "_serve_verb":
                contracts.append((info, True, True,
                                  "the inline-verb surface"))
            elif info.name in ("_event_loop", "_on_accept",
                               "_on_readable", "_on_wakeup"):
                # The async accept path (serving.ioMode=async): ONE
                # thread owns every connection's reads, so anything
                # blocking here stalls the whole listener, not one
                # connection.  Socket ops are allowed (non-blocking fds
                # + the bounded reject send); stores and sleeps are not.
                contracts.append((info, False, True,
                                  "the async event loop"))
        for info, store_reads, bounded_send, label in contracts:
            hit = graph.find_path(
                info.fid,
                lambda s: self._forbidden_inline(s, store_reads,
                                                 bounded_send))
            if hit is None:
                continue
            chain, blocked = hit
            what = _blocking_kind(blocked, allow_store_reads=store_reads) \
                or f"query execution via {blocked.name}()"
            findings.append(Finding(
                self.name, info.path, info.lineno,
                f"[block-free] {what} reachable from {info.qualname}() — "
                f"{label} must answer while the admission queue sheds: "
                f"{callgraph.describe_chain(graph, chain, blocked)}",
                ident=f"block-free:{info.qualname}:{blocked.name}"))

    @staticmethod
    def _forbidden_inline(site: callgraph.CallSite, store_reads: bool,
                          bounded_send: bool) -> bool:
        if any(t in _EXEC_TARGETS for t in site.targets):
            return True
        kind = _blocking_kind(site, allow_store_reads=store_reads)
        if kind is None:
            return False
        if bounded_send and kind.startswith("socket"):
            # The reject send is deliberate and timeout-bounded.
            return False
        if kind == "open()":
            return False  # loopback /proc reads etc.; writes are io-seam's
        return True

    # -- 3: deadline discipline ----------------------------------------------
    def _check_deadlines(self, ctx, graph, findings) -> None:
        ex_path = "hyperspace_tpu/execution/executor.py"

        def is_check(site: callgraph.CallSite) -> bool:
            return site.name.endswith(".check") and \
                any("utils/deadline.py" in t for t in site.targets)

        node_fn = graph.function(ex_path, "Executor._execute_node")
        if node_fn is not None:
            first = node_fn.node.body[0] if node_fn.node.body else None
            entry_line = getattr(first, "lineno", -1)
            has_entry = any(
                is_check(s) and s.line <= entry_line + 1
                for s in graph.sites_of(node_fn.fid))
            if not has_entry:
                findings.append(Finding(
                    self.name, ex_path, node_fn.lineno,
                    "[deadline] Executor._execute_node must open with a "
                    "deadline.check() — operator ENTRY is the seam every "
                    "dispatch path funnels through",
                    ident="deadline:Executor._execute_node:entry"))
        exec_fn = graph.function(ex_path, "Executor.execute")
        if exec_fn is not None:
            dispatch_line = None
            for s in graph.sites_of(exec_fn.fid):
                if s.name.endswith("_execute_node"):
                    dispatch_line = s.line
                    break
            has_exit = dispatch_line is not None and any(
                is_check(s) and s.line > dispatch_line
                for s in graph.sites_of(exec_fn.fid))
            if not has_exit:
                findings.append(Finding(
                    self.name, ex_path, exec_fn.lineno,
                    "[deadline] Executor.execute must deadline-check "
                    "AFTER _execute_node returns (the PR 9 exit-check "
                    "class: entry-only checks all ran on the way down, "
                    "so an expiry inside a long scan never aborts the "
                    "work stacked above it)",
                    ident="deadline:Executor.execute:exit"))
        run_fn = graph.function("hyperspace_tpu/interop/server.py",
                                "_WorkerPool._run")
        if run_fn is not None:
            has_scope = any(
                s.name.endswith(".scope") and
                any("utils/deadline.py" in t for t in s.targets)
                for s in graph.sites_of(run_fn.fid))
            if not has_scope:
                findings.append(Finding(
                    self.name, "hyperspace_tpu/interop/server.py",
                    run_fn.lineno,
                    "[deadline] _WorkerPool._run must execute jobs under "
                    "a deadline.scope() — without it no executor check "
                    "downstream can ever fire for a served request",
                    ident="deadline:_WorkerPool._run:scope"))
        # Operator handlers are dispatched only from inside executor.py.
        for fid, info in graph.functions.items():
            if info.path != ex_path or \
                    not info.qualname.startswith("Executor._execute_") or \
                    info.qualname == "Executor._execute_node":
                continue
            for site in graph.callers_of(fid):
                caller_path = site.caller.split("::", 1)[0]
                if caller_path != ex_path:
                    findings.append(Finding(
                        self.name, caller_path, site.line,
                        f"[deadline] {site.name}() dispatches executor "
                        f"operator {info.qualname} from outside "
                        f"executor.py — bypassing the deadline-checked "
                        f"dispatcher (_execute_node)",
                        ident=f"deadline:{info.qualname}:external"))
        # The collect seam re-checks before execution fallbacks.
        collect = graph.function("hyperspace_tpu/dataset.py",
                                 "Dataset.collect")
        if collect is not None and not graph.reaches(collect.fid, is_check):
            findings.append(Finding(
                self.name, "hyperspace_tpu/dataset.py", collect.lineno,
                "[deadline] Dataset.collect must reach a deadline check "
                "at its planning seam (a request that expired in the "
                "queue should not plan, replan, and containment-probe "
                "first)",
                ident="deadline:Dataset.collect:planning"))
