"""telemetry-catalog: metric and span names used in code must appear in
the docs/16-observability.md catalog, and vice versa.

Usage collection is AST-based: ``metrics.inc/observe/set_gauge(...)``
calls and ``span(...)`` / ``trace.span(...)`` / ``Span(...)`` openings.
F-string names become segment patterns (``f"rule.{slug}.applied"``
matches the catalog row ``rule.<slug>.applied``); a name with NO literal
segment is refused outside the dynamic-emitter allowlist below, because
a fully dynamic name can neither be checked nor capped by the catalog.

The reverse direction — a catalog row no code emits — is what the old
CI span-grep could never test: deleting an emission site used to leave
the doc row lying.
"""

from __future__ import annotations

import ast
import difflib
import fnmatch
from typing import Dict, List, Optional, Set, Tuple

from hyperspace_tpu.lint import catalog
from hyperspace_tpu.lint.engine import (
    Finding,
    LintContext,
    const_str,
    joined_pattern,
)

_SCAN_INCLUDE = ("hyperspace_tpu/", "bench.py")
_SCAN_EXCLUDE = (
    "hyperspace_tpu/lint/",
    "hyperspace_tpu/telemetry/metrics.py",   # the registry itself
    "hyperspace_tpu/telemetry/trace.py",     # the span machinery itself
)

# Files allowed to emit metric names assembled from variables, with the
# concrete families they emit (counted as covering those catalog rows).
ALLOW_DYNAMIC: Dict[str, Tuple[str, ...]] = {
    # ByteBudgetLRU: one mechanism, two metric prefixes (docs/16).
    "hyperspace_tpu/execution/device_cache.py":
        ("cache.device.*", "serve.plan_cache.*"),
}

# Catalog rows computed, not emitted (metrics.snapshot() derives them).
DERIVED_METRICS = {"cache.device.hit_ratio"}

_METRIC_METHODS = {"inc", "observe", "set_gauge"}


def _display(name: str) -> str:
    return name.replace("\x00", "<?>")


def _extract_name(arg: ast.AST) -> Tuple[Optional[str], bool]:
    """(pattern-or-name, is_static).  ``is_static`` False means the arg
    was not a (f-)string literal at all."""
    s = const_str(arg)
    if s is not None:
        return s, True
    p = joined_pattern(arg)
    if p is not None:
        return p, True
    return None, False


class _Usage:
    __slots__ = ("name", "path", "line", "kind")

    def __init__(self, name: str, path: str, line: int, kind: str) -> None:
        self.name = name
        self.path = path
        self.line = line
        self.kind = kind  # "metric" | "span"


class Rule:
    name = "telemetry-catalog"
    description = ("metric/span names in code and the docs/16 catalog "
                   "agree in both directions")

    def run(self, ctx: LintContext) -> List[Finding]:
        metric_entries, span_entries = catalog.telemetry_catalog(ctx)
        findings: List[Finding] = []
        if not metric_entries or not span_entries:
            return [Finding(self.name, catalog.OBS_DOC_PATH, 1,
                            "could not parse the docs/16 metric/span tables",
                            ident="unparseable")]

        usages: List[_Usage] = []
        for src in ctx.py_files(include=_SCAN_INCLUDE,
                                exclude=_SCAN_EXCLUDE):
            if src.tree is None:
                continue
            dynamic_ok = src.relpath in ALLOW_DYNAMIC
            metric_bases, span_names, trace_bases = self._aliases(src.tree)
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                kind = None
                if isinstance(node.func, ast.Attribute):
                    base = node.func.value
                    if isinstance(base, ast.Name):
                        if node.func.attr in _METRIC_METHODS \
                                and base.id in metric_bases:
                            kind = "metric"
                        elif node.func.attr == "span" \
                                and base.id in trace_bases:
                            kind = "span"
                elif isinstance(node.func, ast.Name) \
                        and node.func.id in span_names:
                    kind = "span"
                if kind is None or not node.args:
                    continue
                name, static = self._check_one(
                    src, node, kind, dynamic_ok, findings)
                if name is not None:
                    usages.append(_Usage(name, src.relpath,
                                         node.lineno, kind))

        self._forward(usages, metric_entries, span_entries, findings)
        self._reverse(usages, metric_entries, span_entries, findings)
        return findings

    # -- collection helpers --------------------------------------------------
    def _aliases(self, tree: ast.Module):
        """Per-file alias sets: names that reach the metrics module, the
        ``span``/``Span`` callables, and the trace module."""
        metric_bases = {"metrics"}
        span_names: Set[str] = set()
        trace_bases = {"trace"}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                if node.module.endswith("telemetry") or \
                        node.module.endswith("telemetry.metrics"):
                    for a in node.names:
                        if a.name == "metrics" or \
                                node.module.endswith(".metrics"):
                            metric_bases.add(a.asname or a.name)
                if node.module.endswith("telemetry.trace") or \
                        node.module.endswith("telemetry"):
                    for a in node.names:
                        if a.name in ("span", "Span"):
                            span_names.add(a.asname or a.name)
                        if a.name == "trace":
                            trace_bases.add(a.asname or a.name)
        return metric_bases, span_names, trace_bases

    def _check_one(self, src, node: ast.Call, kind: str, dynamic_ok: bool,
                   findings: List[Finding]):
        from hyperspace_tpu.lint.engine import enclosing_function_name

        name, static = _extract_name(node.args[0])
        if not static:
            if not dynamic_ok:
                fn = enclosing_function_name(src.tree, node.lineno)
                findings.append(Finding(
                    self.name, src.relpath, node.lineno,
                    f"{kind} name is a runtime expression — use a literal "
                    f"or an allowlisted dynamic emitter "
                    f"(docs/18-static-analysis.md)",
                    ident=f"dynamic:{kind}:{fn}"))
            return None, False
        if name is not None and "\x00" in name:
            segs = name.split(".")
            if all("\x00" in s for s in segs):
                if not dynamic_ok:
                    findings.append(Finding(
                        self.name, src.relpath, node.lineno,
                        f"fully dynamic {kind} name (no literal segment) — "
                        f"the catalog cannot check or bound it",
                        ident=f"dynamic:{kind}:{_display(name)}"))
                return None, False
        if dynamic_ok:
            return None, False  # vouched for by the allowlist families
        return name, True

    # -- checks --------------------------------------------------------------
    def _forward(self, usages, metric_entries, span_entries, findings):
        for u in usages:
            entries = metric_entries if u.kind == "metric" else span_entries
            if any(catalog.name_matches_entry(u.name, e) for e in entries):
                continue
            close = difflib.get_close_matches(
                _display(u.name), list(entries), n=1, cutoff=0.8)
            hint = f" (did you mean {close[0]!r}?)" if close else ""
            doc = "docs/16 metric catalog" if u.kind == "metric" \
                else "docs/16 span taxonomy"
            findings.append(Finding(
                self.name, u.path, u.line,
                f"{u.kind} name {_display(u.name)!r} is not in the {doc}"
                f"{hint}",
                ident=f"uncataloged:{u.kind}:{_display(u.name)}"))

    def _reverse(self, usages, metric_entries, span_entries, findings):
        dynamic_globs = [g for globs in ALLOW_DYNAMIC.values() for g in globs]
        for kind, entries in (("metric", metric_entries),
                              ("span", span_entries)):
            for entry, line in sorted(entries.items()):
                if kind == "metric" and entry in DERIVED_METRICS:
                    continue
                if any(u.kind == kind
                       and catalog.name_matches_entry(u.name, entry)
                       for u in usages):
                    continue
                if any(fnmatch.fnmatchcase(entry, g)
                       for g in dynamic_globs):
                    continue
                findings.append(Finding(
                    self.name, catalog.OBS_DOC_PATH, line,
                    f"docs/16 {kind} catalog entry {entry!r} has no "
                    f"emission site in code",
                    ident=f"unemitted:{kind}:{entry}"))
