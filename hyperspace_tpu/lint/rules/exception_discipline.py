"""exception-discipline: no silent failure on the commit/serving paths.

Three checks:

  - **bare except** anywhere in the engine or bench: catches
    ``KeyboardInterrupt``/``SystemExit`` — and this codebase models
    crashes as ``InjectedCrash(BaseException)`` precisely so cleanup
    code CANNOT swallow them; a bare except re-opens that hole.
  - **swallowed Exception** (``except Exception: pass`` and the
    BaseException variant) on the action-commit and serving hot paths:
    diagnostic side-writes may be fault-quiet, but an action or a
    served request that eats an error commits lies.  Elsewhere (e.g.
    the perf ledger, trace sinks) swallowing is the documented
    contract, so the scope is deliberate.
  - **wire-error taxonomy**: every literal ``ERR ...`` status line and
    every ``WireError(code, ...)`` in ``interop/`` must use a code
    declared by the ``ERR_*`` constants in server.py — a typo'd code
    silently downgrades a retryable shed to a permanent failure in
    every client.
"""

from __future__ import annotations

import ast
from typing import List

from hyperspace_tpu.lint import catalog
from hyperspace_tpu.lint.engine import (
    Finding,
    LintContext,
    const_str,
    enclosing_function_name,
)

_SCAN_INCLUDE = ("hyperspace_tpu/", "bench.py", "run-tests.py")
_SCAN_EXCLUDE = ("hyperspace_tpu/lint/",)

# Where `except Exception: pass` is a correctness bug, not a policy call.
_HOT_PATHS = (
    "hyperspace_tpu/actions/",
    "hyperspace_tpu/interop/",
    "hyperspace_tpu/index/",
    "hyperspace_tpu/dataset.py",
    "hyperspace_tpu/io/log_store.py",
)

_WIRE_SCAN = ("hyperspace_tpu/interop/",)


def _handler_names(handler: ast.ExceptHandler) -> List[str]:
    t = handler.type
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, ast.Tuple):
        return [e.id for e in t.elts if isinstance(e, ast.Name)]
    return []


def _in(path: str, prefixes) -> bool:
    return any(path == p or (p.endswith("/") and path.startswith(p))
               for p in prefixes)


class Rule:
    name = "exception-discipline"
    description = ("no bare except anywhere; no swallowed Exception on "
                   "commit/serving hot paths; ERR lines use the taxonomy")

    def run(self, ctx: LintContext) -> List[Finding]:
        findings: List[Finding] = []
        codes = catalog.wire_codes(ctx)
        for src in ctx.py_files(include=_SCAN_INCLUDE,
                                exclude=_SCAN_EXCLUDE):
            if src.tree is None:
                continue
            hot = _in(src.relpath, _HOT_PATHS)
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ExceptHandler):
                    self._check_handler(src, node, hot, findings)
                elif isinstance(node, ast.Call) and codes:
                    self._check_wire(src, node, codes, findings)
            if codes and _in(src.relpath, _WIRE_SCAN):
                self._check_err_literals(src, codes, findings)
        return findings

    def _check_handler(self, src, node: ast.ExceptHandler, hot: bool,
                       findings: List[Finding]) -> None:
        fn = enclosing_function_name(src.tree, node.lineno)
        if node.type is None:
            findings.append(Finding(
                self.name, src.relpath, node.lineno,
                f"bare `except:` in {fn}() — catches SystemExit/"
                f"KeyboardInterrupt and the injector's InjectedCrash; "
                f"name the exception types",
                ident=f"bare-except:{fn}"))
            return
        if not hot:
            return
        names = _handler_names(node)
        swallows = ("Exception" in names or "BaseException" in names) \
            and len(node.body) == 1 and isinstance(node.body[0], ast.Pass)
        if swallows:
            findings.append(Finding(
                self.name, src.relpath, node.lineno,
                f"`except {'/'.join(names)}: pass` in {fn}() on a "
                f"commit/serving hot path swallows errors the caller "
                f"must see — handle, log via telemetry, or narrow the type",
                ident=f"swallow:{fn}"))

    def _check_wire(self, src, node: ast.Call, codes,
                    findings: List[Finding]) -> None:
        func = node.func
        ctor = func.id if isinstance(func, ast.Name) else (
            func.attr if isinstance(func, ast.Attribute) else None)
        if ctor != "WireError" or not node.args:
            return
        arg = node.args[0]
        lit = const_str(arg)
        if lit is not None and lit not in codes:
            findings.append(Finding(
                self.name, src.relpath, node.lineno,
                f"WireError code {lit!r} is not in the ERR_* taxonomy "
                f"({', '.join(sorted(codes))})",
                ident=f"wire-code:{lit}"))
        if isinstance(arg, ast.Name) and not arg.id.startswith("ERR_") \
                and arg.id not in ("code",):
            findings.append(Finding(
                self.name, src.relpath, node.lineno,
                f"WireError code should be an ERR_* constant, not "
                f"{arg.id!r}",
                ident=f"wire-code-var:{arg.id}"))

    def _check_err_literals(self, src, codes,
                            findings: List[Finding]) -> None:
        """Literal ``"ERR <word> ..."`` strings (plain or f-string heads)
        must lead with a taxonomy code or an interpolated expression."""
        if src.tree is None:
            return
        for node in ast.walk(src.tree):
            head = None
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                head = node.value
            elif isinstance(node, ast.JoinedStr) and node.values and \
                    isinstance(node.values[0], ast.Constant) and \
                    isinstance(node.values[0].value, str):
                head = node.values[0].value
            if head is None or not head.startswith("ERR "):
                continue
            rest = head[4:]
            if not rest:
                continue  # code comes from an interpolated expression
            word = rest.split()[0] if rest.split() else ""
            if word and word.isupper() and word not in codes:
                findings.append(Finding(
                    self.name, src.relpath, node.lineno,
                    f"wire status literal starts 'ERR {word}', which is "
                    f"not a taxonomy code ({', '.join(sorted(codes))})",
                    ident=f"err-literal:{word}"))
