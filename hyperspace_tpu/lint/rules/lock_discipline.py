"""lock-discipline: shared mutable state in thread-spawning modules is
mutated only under its lock; the ``with lock:`` nesting graph is acyclic.

Two analyses:

  1. **Guarded-state consistency** (per configured module).  Locks are
     discovered structurally (``threading.Lock/RLock/Condition``
     assigned to ``self._x`` or a module global).  Any state a function
     mutates inside a ``with <lock>:`` block becomes *lock-associated*;
     a mutation of the same state OUTSIDE any lock (and outside
     ``__init__``) is a violation.  Additionally, any read-modify-write
     (``+=`` and friends) of shared state in a lock-owning class that
     happens outside every lock is flagged even if the attribute was
     never seen under a lock — the lost-update shape needs no
     associative evidence.  A helper whose caller holds the lock
     carries ``# hslint: allow[lock-discipline] caller holds <lock>``
     on its ``def`` line.

  2. **Lock-ordering** (package-wide).  Every lexically nested
     ``with A: ... with B:`` contributes an A→B edge keyed by
     file-qualified lock identity; a cycle in that graph is the
     deadlock-by-design shape and is reported on one participating
     site.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from hyperspace_tpu.lint.engine import Finding, LintContext

# The thread-spawning modules whose state the guarded-state analysis
# covers (ISSUE 8; extend as new concurrent modules appear).
GUARDED_MODULES = (
    "hyperspace_tpu/interop/server.py",
    "hyperspace_tpu/telemetry/metrics.py",
    "hyperspace_tpu/execution/plan_cache.py",
    "hyperspace_tpu/execution/device_cache.py",
    "hyperspace_tpu/io/integrity.py",
)

_ORDER_SCAN = ("hyperspace_tpu/",)
_ORDER_EXCLUDE = ("hyperspace_tpu/lint/",)

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_MUTATOR_METHODS = {"append", "extend", "insert", "add", "remove",
                    "discard", "pop", "popitem", "clear", "update",
                    "setdefault", "move_to_end", "appendleft"}
_INIT_NAMES = {"__init__", "__post_init__", "__new__"}


def _is_lock_ctor(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else "")
    return name in _LOCK_CTORS


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _state_of_target(node: ast.AST,
                     global_names: Set[str]) -> Optional[str]:
    """The state identity mutated by an assignment target: ``self.x``
    (including ``self.x[...]``) or a declared-global module name."""
    if isinstance(node, ast.Subscript):
        return _state_of_target(node.value, global_names)
    attr = _self_attr(node)
    if attr is not None:
        return f"self.{attr}"
    if isinstance(node, ast.Name) and node.id in global_names:
        return node.id
    return None


class _FuncScanner(ast.NodeVisitor):
    """Walk one function, tracking the with-lock stack; record mutation
    events and lock-nesting edges."""

    def __init__(self, lock_names: Set[str], lock_prefix: str) -> None:
        self.lock_names = lock_names  # "self.X" / module-global names
        self.lock_prefix = lock_prefix  # file:Class qualifier for edges
        self.stack: List[str] = []
        self.global_names: Set[str] = set()
        # (state, guarded, lineno, is_rmw)
        self.events: List[Tuple[str, bool, int, bool]] = []
        self.edges: List[Tuple[str, str, int]] = []

    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and f"self.{attr}" in self.lock_names:
            return f"self.{attr}"
        if isinstance(expr, ast.Name) and expr.id in self.lock_names:
            return expr.id
        return None

    # Nested defs start their own lexical lock context.
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Global(self, node: ast.Global) -> None:
        self.global_names.update(node.names)

    def visit_With(self, node: ast.With) -> None:
        held = []
        for item in node.items:
            lock = self._lock_id(item.context_expr)
            if lock is not None:
                if self.stack:
                    self.edges.append((self.stack[-1], lock, node.lineno))
                self.stack.append(lock)
                held.append(lock)
        for stmt in node.body:
            self.visit(stmt)
        for _ in held:
            self.stack.pop()

    visit_AsyncWith = visit_With

    def _record(self, target: ast.AST, lineno: int, rmw: bool) -> None:
        state = _state_of_target(target, self.global_names)
        if state is None or state in self.lock_names:
            return
        self.events.append((state, bool(self.stack), lineno, rmw))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record(t, node.lineno, rmw=False)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record(node.target, node.lineno, rmw=True)
        self.visit(node.value)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATOR_METHODS:
            self._record(node.func.value, node.lineno, rmw=False)
        self.generic_visit(node)


def _module_locks(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _class_locks(cls: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    out.add(f"self.{attr}")
    return out


def _functions(body) -> List[ast.FunctionDef]:
    return [n for n in body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


class Rule:
    name = "lock-discipline"
    description = ("lock-associated state mutated only under its lock; "
                   "with-lock nesting graph is acyclic")

    def run(self, ctx: LintContext) -> List[Finding]:
        findings: List[Finding] = []
        edges: Dict[Tuple[str, str], int] = {}
        edge_site: Dict[Tuple[str, str], Tuple[str, int]] = {}

        for src in ctx.py_files(include=_ORDER_SCAN,
                                exclude=_ORDER_EXCLUDE):
            if src.tree is None:
                continue
            guarded_module = src.relpath in GUARDED_MODULES
            mod_locks = _module_locks(src.tree)

            # Module-level functions mutate module globals.
            self._scan_scope(
                src, _functions(src.tree.body), mod_locks,
                lock_prefix=f"{src.relpath}:<module>",
                guarded=guarded_module, findings=findings,
                edges=edges, edge_site=edge_site)

            for cls in [n for n in src.tree.body
                        if isinstance(n, ast.ClassDef)]:
                locks = mod_locks | _class_locks(cls)
                self._scan_scope(
                    src, _functions(cls.body), locks,
                    lock_prefix=f"{src.relpath}:{cls.name}",
                    guarded=guarded_module, findings=findings,
                    edges=edges, edge_site=edge_site)

        findings.extend(self._cycles(edges, edge_site))
        return findings

    def _scan_scope(self, src, funcs, locks, lock_prefix, guarded,
                    findings, edges, edge_site) -> None:
        owns_lock = bool(locks)
        events = []  # (state, guarded, lineno, rmw, fname)
        for fn in funcs:
            scanner = _FuncScanner(locks, lock_prefix)
            for stmt in fn.body:
                scanner.visit(stmt)
            for outer, inner, line in scanner.edges:
                key = (f"{lock_prefix}.{outer}", f"{lock_prefix}.{inner}")
                edges.setdefault(key, 0)
                edges[key] += 1
                edge_site.setdefault(key, (src.relpath, line))
            if fn.name in _INIT_NAMES:
                continue
            for state, under, line, rmw in scanner.events:
                events.append((state, under, line, rmw, fn.name))
        if not guarded or not owns_lock:
            return
        associated = {s for s, under, _l, _r, _f in events if under}
        for state, under, line, rmw, fname in events:
            if under:
                continue
            if state in associated:
                findings.append(Finding(
                    self.name, src.relpath, line,
                    f"{state} is mutated under a lock elsewhere but "
                    f"written without one in {fname}()",
                    ident=f"unlocked:{lock_prefix.split(':')[1]}."
                          f"{state}:{fname}"))
            elif rmw:
                findings.append(Finding(
                    self.name, src.relpath, line,
                    f"read-modify-write of shared {state} in {fname}() "
                    f"outside any lock (lost-update race in a "
                    f"lock-owning scope)",
                    ident=f"rmw:{lock_prefix.split(':')[1]}."
                          f"{state}:{fname}"))

    def _cycles(self, edges, edge_site) -> List[Finding]:
        graph: Dict[str, Set[str]] = {}
        for a, b in edges:
            if a != b:
                graph.setdefault(a, set()).add(b)
        findings: List[Finding] = []
        # Iterative DFS cycle detection with path recovery.
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in
                 set(graph) | {b for bs in graph.values() for b in bs}}
        reported: Set[frozenset] = set()

        def dfs(start: str) -> None:
            stack = [(start, iter(sorted(graph.get(start, ()))))]
            path = [start]
            color[start] = GRAY
            while stack:
                node, it = stack[-1]
                advanced = False
                for nxt in it:
                    if color[nxt] == GRAY:
                        cyc = path[path.index(nxt):] + [nxt]
                        key = frozenset(cyc)
                        if key not in reported:
                            reported.add(key)
                            site = edge_site.get((node, nxt)) or \
                                edge_site.get((cyc[0], cyc[1]))
                            path_s = " -> ".join(
                                c.split(":")[-1] for c in cyc)
                            findings.append(Finding(
                                self.name, site[0] if site else "",
                                site[1] if site else 1,
                                f"lock-ordering cycle: {path_s} — two "
                                f"threads taking these locks in opposite "
                                f"orders deadlock",
                                ident=f"cycle:{'|'.join(sorted(key))}"))
                    elif color[nxt] == WHITE:
                        color[nxt] = GRAY
                        path.append(nxt)
                        stack.append(
                            (nxt, iter(sorted(graph.get(nxt, ())))))
                        advanced = True
                        break
                if not advanced:
                    color[node] = BLACK
                    stack.pop()
                    if path and path[-1] == node:
                        path.pop()

        for n in sorted(color):
            if color[n] == WHITE:
                dfs(n)
        return findings
