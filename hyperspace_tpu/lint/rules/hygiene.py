"""hygiene: the ``_bvf``-class drift ADVICE.md keeps finding — duplicate
and redundant imports, dead module-level imports, import shadowing, and
mutable default arguments.

Checks (all scope-aware; the lazy function-level import idiom this
codebase uses to break cycles is NOT flagged unless the same binding
already exists at module level — then the lazy copy is pure noise):

  - duplicate import of the same binding twice in one scope;
  - function-level import that re-creates an identical module-level
    binding;
  - module-level import never referenced anywhere in the file
    (``__init__.py`` re-export surfaces are exempt);
  - module-level assignment that rebinds an imported name;
  - mutable default argument (``def f(x=[])``).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from hyperspace_tpu.lint.engine import Finding, LintContext

_SCAN_INCLUDE = ("hyperspace_tpu/", "bench.py", "run-tests.py", "tools/")
_SCAN_EXCLUDE = ()

Binding = Tuple[Optional[str], str, Optional[str]]  # (module, name, asname)


def _bindings(node) -> List[Binding]:
    if isinstance(node, ast.Import):
        return [(None, a.name, a.asname) for a in node.names]
    if isinstance(node, ast.ImportFrom):
        return [(node.module, a.name, a.asname) for a in node.names]
    return []


def _bound_name(b: Binding) -> str:
    module, name, asname = b
    if asname:
        return asname
    return name.split(".")[0] if module is None else name


class Rule:
    name = "hygiene"
    description = ("duplicate/dead imports, import shadowing, mutable "
                   "default args")

    def run(self, ctx: LintContext) -> List[Finding]:
        findings: List[Finding] = []
        for src in ctx.py_files(include=_SCAN_INCLUDE,
                                exclude=_SCAN_EXCLUDE):
            if src.tree is None:
                continue
            self._scan_file(src, findings)
        return findings

    def _scan_file(self, src, findings: List[Finding]) -> None:
        tree = src.tree
        module_bindings: Dict[Binding, int] = {}
        module_names: Dict[str, int] = {}

        # --- module scope: duplicates + shadowing ---------------------------
        self._scan_scope(src, tree.body, "<module>", module_bindings,
                         findings)
        for b, line in module_bindings.items():
            module_names.setdefault(_bound_name(b), line)

        for node in tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id in module_names \
                            and node.lineno > module_names[t.id]:
                        findings.append(Finding(
                            self.name, src.relpath, node.lineno,
                            f"module-level assignment to {t.id!r} rebinds "
                            f"the import of the same name (line "
                            f"{module_names[t.id]})",
                            ident=f"shadow-import:{t.id}"))

        # --- function scopes ------------------------------------------------
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope_bindings: Dict[Binding, int] = {}
                self._scan_scope(src, node.body, node.name, scope_bindings,
                                 findings)
                for b, line in scope_bindings.items():
                    if b in module_bindings:
                        findings.append(Finding(
                            self.name, src.relpath, line,
                            f"{node.name}() re-imports "
                            f"{_bound_name(b)!r}, already imported at "
                            f"module level (line {module_bindings[b]})",
                            ident=f"redundant-import:{node.name}:"
                                  f"{_bound_name(b)}"))
                self._check_defaults(src, node, findings)

        # --- dead module-level imports --------------------------------------
        if not src.relpath.endswith("__init__.py"):
            self._check_dead(src, tree, module_bindings, findings)

    def _scan_scope(self, src, body, scope_name: str,
                    bindings: Dict[Binding, int],
                    findings: List[Finding]) -> None:
        """Collect import bindings of one scope (module body or one
        function body, nested defs excluded).  Duplicates are flagged
        only within one statement BLOCK — two lazy imports in mutually
        exclusive branches are fine; two in the same suite (the
        ``_bvf`` shape from ADVICE.md) are not."""

        def scan_block(block) -> None:
            block_bindings: Dict[Binding, int] = {}
            for node in block:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if isinstance(node, (ast.Import, ast.ImportFrom)):
                    for b in _bindings(node):
                        if b[0] == "__future__":
                            continue
                        if b in block_bindings:
                            findings.append(Finding(
                                self.name, src.relpath, node.lineno,
                                f"duplicate import of {_bound_name(b)!r} "
                                f"in {scope_name} (first at line "
                                f"{block_bindings[b]})",
                                ident=f"dup-import:{scope_name}:"
                                      f"{_bound_name(b)}"))
                        else:
                            block_bindings[b] = node.lineno
                        if b not in bindings:
                            bindings[b] = node.lineno
                for attr in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(node, attr, None)
                    if isinstance(sub, list):
                        if attr == "handlers":
                            for h in sub:
                                scan_block(h.body)
                        else:
                            scan_block(sub)

        scan_block(list(body))

    def _check_defaults(self, src, node, findings: List[Finding]) -> None:
        defaults = list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None]
        for d in defaults:
            mutable = isinstance(d, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp))
            if isinstance(d, ast.Call) and isinstance(d.func, ast.Name) \
                    and d.func.id in ("list", "dict", "set") \
                    and not d.args and not d.keywords:
                mutable = True
            if mutable:
                findings.append(Finding(
                    self.name, src.relpath, d.lineno,
                    f"mutable default argument in {node.name}() — shared "
                    f"across calls; default to None and create inside",
                    ident=f"mutable-default:{node.name}"))

    def _check_dead(self, src, tree, module_bindings: Dict[Binding, int],
                    findings: List[Finding]) -> None:
        used: Set[str] = set()

        def use_annotation_string(value: str) -> None:
            # Quoted annotations ('-> "Tuple[np.ndarray, ...]"') hide
            # their names from the Name walk; parse them.
            try:
                expr = ast.parse(value, mode="eval")
            except SyntaxError:
                return
            for n in ast.walk(expr):
                if isinstance(n, ast.Name):
                    used.add(n.id)

        for node in ast.walk(tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            ann = None
            if isinstance(node, (ast.arg, ast.AnnAssign)):
                ann = node.annotation
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ann = node.returns
            if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                use_annotation_string(ann.value)
        # __all__ strings export names without a Name node.
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in node.targets):
                if isinstance(node.value, (ast.List, ast.Tuple)):
                    for e in node.value.elts:
                        if isinstance(e, ast.Constant) and \
                                isinstance(e.value, str):
                            used.add(e.value)
        for b, line in module_bindings.items():
            name = _bound_name(b)
            if name in used:
                continue
            # Deliberate side-effect imports carry `# noqa: F401` (the
            # flake8 convention already used here) or an hslint pragma.
            src_line = src.lines[line - 1] if line <= len(src.lines) else ""
            if "noqa" in src_line and \
                    ("F401" in src_line or "noqa:" not in src_line):
                continue
            findings.append(Finding(
                self.name, src.relpath, line,
                f"module-level import {name!r} is never used in this "
                f"file",
                ident=f"dead-import:{name}"))
