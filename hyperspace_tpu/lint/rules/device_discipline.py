"""device-discipline: the device hot path (``ops/``, ``execution/``,
``parallel/``) never syncs to host unannounced.

ROADMAP items 1-2 restructure exactly these modules; this rule makes the
invariants the PR 11 timeline profiler can only *measure* into statically
*enforced* contracts.  Checks:

  - **implicit-sync** — ``float()/int()/bool()``, ``.item()/.tolist()``,
    ``np.asarray()/np.array()``, or an ``if``/``while`` test on a value
    the taint analysis proves device-resident.  Each is a blocking
    device→host transfer the profiler cannot attribute.  The sanctioned
    forms are ``sync_guard.pull(x, site)`` / ``sync_guard.scalar(x,
    site)`` (execution/sync_guard.py — attributed, guard-audited, and
    ``exec.transfer.d2h``-counted) or an ALLOW entry below.
  - **device-loop** — a Python ``for`` loop iterating a device array:
    every element access is its own transfer.
  - **untimed-sync** — a raw ``block_until_ready`` outside the
    ``timeline.kernel_begin/kernel_end`` seams: it stalls the host with
    no ``exec.kernel.*.device_ms`` attribution.
  - **float64-literal** — an explicit float64 dtype outside a
    ``with _enable_x64():`` region: under the 32-bit default the
    x64 shim exists to scope, it silently downcasts (the grouped-
    aggregate 1e-6 relative error from PR 1).
  - **jit-unsafe** — inside a ``jax.jit``-decorated function: conf/env/
    clock reads (traced once, then baked stale into the compiled
    program) and mutable default arguments (unhashable static args
    poison the jit cache); at call sites of jitted functions, a literal
    list/dict/set passed in a ``static_argnames`` position (cache-
    busting unhashable static).

Device taint is interprocedural: a function whose return value is
device-resident (directly, through a jit-decorated callee, or through
another device-returning function — fixpoint over the lint/callgraph.py
edges) taints its callers' locals.  Calls whose result is bound inside a
``with _enable_x64():`` block are also treated as device values — in
this codebase the scoped-x64 shim brackets exactly the device compute
regions.

Legitimate boundary sites (the ONE dynamic-shape sync a kernel needs,
a host mirror that accepts either residency) are registered in ALLOW
below with a reason, or carry an inline
``# hslint: allow[device-discipline] <reason>`` pragma.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from hyperspace_tpu.lint import callgraph
from hyperspace_tpu.lint.engine import Finding, LintContext, call_name

_SCAN_INCLUDE = (
    "hyperspace_tpu/ops/",
    "hyperspace_tpu/execution/",
    "hyperspace_tpu/parallel/",
)
_SCAN_EXCLUDE = (
    # The attributed-conversion seam itself: its pulls are the product.
    "hyperspace_tpu/execution/sync_guard.py",
)

# (path, function qualname, check) -> reason.  The registry is the
# reviewable list of every sanctioned raw sync left in the hot path;
# prefer sync_guard.pull/scalar at new sites (docs/18).
ALLOW: Dict[Tuple[str, str, str], str] = {
    ("hyperspace_tpu/ops/aggregate.py", "_segment_reduce",
     "float64-literal"):
        "mean accumulates in f64 by design; the kernel is only ever "
        "traced under grouped_aggregate's scoped-x64 region, so the "
        "dtype survives",
}

# jax/jnp callables that do NOT produce device arrays.
_JAX_HOST_CALLS = {
    "jax.device_get", "jax.jit", "jax.local_devices", "jax.devices",
    "jax.default_backend", "jax.tree_util.tree_leaves",
    "jax.tree_util.tree_map", "jax.process_index",
    "jax.transfer_guard_device_to_host",
    "jnp.issubdtype", "jnp.iinfo", "jnp.finfo", "jnp.dtype",
}

# Builtins whose result is never a device array even in an x64 region.
_HOST_BUILTINS = {
    "int", "float", "bool", "len", "tuple", "list", "dict", "set",
    "min", "max", "sum", "abs", "range", "zip", "enumerate", "sorted",
    "isinstance", "getattr", "hasattr", "str", "repr", "print", "round",
    "id", "type", "iter", "next", "divmod",
}

_CONVERT_BUILTINS = {"float", "int", "bool"}
_CONVERT_METHODS = {"item", "tolist"}
_NP_CONVERTERS = {"np.asarray", "np.array", "numpy.asarray",
                  "numpy.array"}
_SANCTIONED_SUFFIXES = ("sync_guard.pull", "sync_guard.scalar")
# Methods on a device array that stay on device.
_DEVICE_METHODS_KEEP = {"astype", "reshape", "sum", "min", "max", "any",
                        "all", "at", "set", "add", "block_until_ready",
                        "copy", "squeeze", "ravel", "flatten"}
_JIT_BANNED_CALLS = {"os.getenv", "time.time", "time.monotonic",
                     "time.monotonic_ns", "time.perf_counter", "open",
                     "use_pallas"}


def _x64_spans(tree: ast.Module) -> List[Tuple[int, int]]:
    """(start, end) line spans of ``with _enable_x64():`` blocks."""
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    name = call_name(item.context_expr)
                    if name.endswith("enable_x64"):
                        spans.append((node.lineno,
                                      getattr(node, "end_lineno",
                                              node.lineno)))
    return spans


def _in_spans(line: int, spans: List[Tuple[int, int]]) -> bool:
    return any(a <= line <= b for a, b in spans)


class _Taint(ast.NodeVisitor):
    """Per-function forward taint pass: which local names are provably
    device arrays, given ``device_fids`` (the interprocedural
    fixpoint's current device-returning function set)."""

    def __init__(self, rule: "Rule", graph, index_path: str,
                 info, device_fids: Set[str],
                 x64_spans: List[Tuple[int, int]],
                 collect=None) -> None:
        self.rule = rule
        self.graph = graph
        self.path = index_path
        self.info = info
        self.device_fids = device_fids
        self.x64_spans = x64_spans
        self.tainted: Set[str] = set()
        self.returns_device = False
        self.collect = collect  # List[Finding] when checking; None on
        # the fixpoint pre-passes

    # -- expression taint ---------------------------------------------------
    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.Compare):
            return self.is_tainted(node.left) or \
                any(self.is_tainted(c) for c in node.comparators)
        if isinstance(node, ast.Call):
            return self._call_is_device(node)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or \
                self.is_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        return False

    def _call_is_device(self, node: ast.Call) -> bool:
        raw = call_name(node)
        if not raw:
            # method call on a non-name chain — device iff receiver is
            if isinstance(node.func, ast.Attribute):
                return self.is_tainted(node.func.value) and \
                    node.func.attr in _DEVICE_METHODS_KEEP
            return False
        if raw in _JAX_HOST_CALLS or any(
                raw.endswith(s) for s in _SANCTIONED_SUFFIXES):
            return False
        if raw.startswith("jnp.") or raw.startswith("jax."):
            return True
        # Method chain on a tainted receiver (rk.astype(...), w[:, 1]).
        if "." in raw:
            head = raw.split(".")[0]
            attr = raw.rsplit(".", 1)[1]
            if head in self.tainted and attr in _DEVICE_METHODS_KEEP:
                return True
        targets = self.graph._resolve(
            self.graph._indexes[self.path], self.info, raw)
        if targets:
            # Trust in-package resolution: device iff the callee is in
            # the fixpoint's device-returning set.
            return any(t in self.device_fids for t in targets)
        # Unresolved PLAIN-NAME call bound inside a scoped-x64 region
        # (a compiled-predicate callable, a shard-mapped program): the
        # shim brackets device compute, so treat the result as device.
        # Method calls on known-host locals stay host.
        if _in_spans(node.lineno, self.x64_spans) and \
                isinstance(node.func, ast.Name) and \
                raw not in _HOST_BUILTINS:
            return True
        return False

    # -- statements ---------------------------------------------------------
    def _bind(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, tainted)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tainted)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check_expr(node.value)
        t = self.is_tainted(node.value)
        for target in node.targets:
            self._bind(target, t)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_expr(node.value)
            self._bind(node.target, self.is_tainted(node.value))

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_expr(node.value)
        if self.is_tainted(node.value):
            self._bind(node.target, True)

    def visit_Return(self, node: ast.Return) -> None:
        if node.value is not None:
            self._check_expr(node.value)
            if self.is_tainted(node.value):
                self.returns_device = True

    def visit_For(self, node: ast.For) -> None:
        self._check_expr(node.iter)
        if self.collect is not None and self.is_tainted(node.iter) and \
                not isinstance(node.iter, ast.Call):
            self.rule._emit(
                self.collect, self.path, node.lineno, "device-loop",
                self.info.qualname,
                "Python-level loop iterates a device array — every "
                "element access is its own host transfer; pull once with "
                "sync_guard.pull() or keep the loop on device")
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_If(self, node: ast.If) -> None:
        self._flag_test(node.test)
        self._check_expr(node.test)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_While(self, node: ast.While) -> None:
        self._flag_test(node.test)
        self._check_expr(node.test)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_Expr(self, node: ast.Expr) -> None:
        self._check_expr(node.value)

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            self._check_expr(item.context_expr)
            if item.optional_vars is not None:
                self._bind(item.optional_vars,
                           self.is_tainted(item.context_expr))
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncWith = visit_With

    def visit_FunctionDef(self, node) -> None:  # nested defs: own pass
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- conversion checks --------------------------------------------------
    def _flag_test(self, test: ast.AST) -> None:
        if self.collect is not None and self.is_tainted(test):
            self.rule._emit(
                self.collect, self.path, test.lineno, "implicit-sync",
                self.info.qualname,
                "branching on a device value forces an implicit "
                "device→host bool() sync — pull it once with "
                "sync_guard.scalar(x, site) and branch on the host value")

    def _check_expr(self, expr: ast.AST) -> None:
        if self.collect is None:
            return
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            raw = call_name(node)
            if raw in _CONVERT_BUILTINS and len(node.args) == 1 and \
                    self.is_tainted(node.args[0]):
                self.rule._emit(
                    self.collect, self.path, node.lineno, "implicit-sync",
                    self.info.qualname,
                    f"{raw}() on a device value is an implicit, "
                    f"unattributed device→host sync — use "
                    f"sync_guard.scalar(x, site)")
            elif raw in _NP_CONVERTERS and node.args and \
                    self.is_tainted(node.args[0]):
                self.rule._emit(
                    self.collect, self.path, node.lineno, "implicit-sync",
                    self.info.qualname,
                    f"{raw}() pulls a device array to host outside the "
                    f"attributed seams — use sync_guard.pull(x, site)")
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _CONVERT_METHODS and \
                    self.is_tainted(node.func.value):
                self.rule._emit(
                    self.collect, self.path, node.lineno, "implicit-sync",
                    self.info.qualname,
                    f".{node.func.attr}() on a device value is an "
                    f"implicit, unattributed device→host sync — use "
                    f"sync_guard.scalar(x, site)")


class Rule:
    name = "device-discipline"
    description = ("no unattributed host syncs, float64 drift, device "
                   "loops, or jit-cache-busting patterns in the device "
                   "hot path")

    def run(self, ctx: LintContext) -> List[Finding]:
        graph = callgraph.for_context(ctx)
        findings: List[Finding] = []
        files = [f for f in ctx.py_files(include=_SCAN_INCLUDE,
                                         exclude=_SCAN_EXCLUDE)
                 if f.tree is not None]

        # Interprocedural device-taint fixpoint over the scanned files:
        # jit-decorated functions return device arrays by construction;
        # a function returning another device function's result joins
        # the set on the next sweep (cycles converge — membership only
        # grows and is bounded by the function count).
        device_fids: Set[str] = set()
        infos = []
        for src in files:
            for info in graph.functions_in(src.relpath):
                infos.append((src, info))
                if callgraph.is_jit_decorated(info):
                    device_fids.add(info.fid)
        spans_by_path = {src.relpath: _x64_spans(src.tree) for src in files}
        for _ in range(4):
            grew = False
            for src, info in infos:
                if info.fid in device_fids:
                    continue
                t = _Taint(self, graph, src.relpath, info, device_fids,
                           spans_by_path[src.relpath])
                for stmt in info.node.body:
                    t.visit(stmt)
                if t.returns_device:
                    device_fids.add(info.fid)
                    grew = True
            if not grew:
                break

        # Checking pass: conversions, loops, branch tests.  Jitted
        # function BODIES are exempt — a traced value cannot silently
        # sync inside a trace (it raises loudly at trace time instead).
        for src, info in infos:
            if callgraph.is_jit_decorated(info):
                continue
            t = _Taint(self, graph, src.relpath, info, device_fids,
                       spans_by_path[src.relpath], collect=findings)
            for stmt in info.node.body:
                t.visit(stmt)

        for src in files:
            self._check_untimed_sync(src, graph, findings)
            self._check_float64(src, spans_by_path[src.relpath], findings)
            self._check_jit_unsafe(src, graph, findings)
        return [f for f in findings if not self._allowed(f)]

    # -- helpers -------------------------------------------------------------
    def _emit(self, findings: List[Finding], path: str, line: int,
              check: str, qualname: str, message: str) -> None:
        findings.append(Finding(
            self.name, path, line, f"[{check}] {message}",
            ident=f"{check}:{qualname}:{line_key(findings, check, qualname)}"))

    def _allowed(self, f: Finding) -> bool:
        check = f.ident.split(":", 1)[0]
        qual = f.ident.split(":")[1] if f.ident.count(":") >= 1 else ""
        return (f.path, qual, check) in ALLOW

    def _check_untimed_sync(self, src, graph, findings) -> None:
        for info in graph.functions_in(src.relpath):
            for site in graph.sites_of(info.fid):
                if site.name.endswith("block_until_ready"):
                    self._emit(
                        findings, src.relpath, site.line, "untimed-sync",
                        info.qualname,
                        "raw block_until_ready stalls the host with no "
                        "exec.kernel.*.device_ms attribution — wrap the "
                        "dispatch in timeline.kernel_begin/kernel_end")

    def _check_float64(self, src, x64_spans, findings) -> None:
        # DEVICE dtypes only: host numpy is 64-bit regardless of the jax
        # x64 mode, so np.float64 on host arrays is not drift.
        for node in ast.walk(src.tree):
            name = None
            if isinstance(node, ast.Attribute) and \
                    node.attr in ("float64", "complex128") and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id in ("jnp", "jax"):
                name = f"{node.value.id}.{node.attr}"
            if name is None or _in_spans(node.lineno, x64_spans):
                continue
            from hyperspace_tpu.lint.engine import enclosing_function_name
            fn = enclosing_function_name(src.tree, node.lineno)
            self._emit(
                findings, src.relpath, node.lineno, "float64-literal", fn,
                f"{name} outside a scoped `with _enable_x64():` region — "
                f"under the 32-bit default this silently downcasts "
                f"(utils/compat.py shim)")

    def _check_jit_unsafe(self, src, graph, findings) -> None:
        for info in graph.functions_in(src.relpath):
            jitted = callgraph.is_jit_decorated(info)
            if jitted:
                args = info.node.args
                defaults = list(args.defaults) + [
                    d for d in args.kw_defaults if d is not None]
                for d in defaults:
                    if isinstance(d, (ast.List, ast.Dict, ast.Set,
                                      ast.ListComp, ast.DictComp,
                                      ast.SetComp)):
                        self._emit(
                            findings, src.relpath, d.lineno, "jit-unsafe",
                            info.qualname,
                            "mutable default argument on a jitted "
                            "function — unhashable as a static arg, and "
                            "a fresh object per trace busts the jit "
                            "cache")
                for site in graph.sites_of(info.fid):
                    bad = site.name in _JIT_BANNED_CALLS \
                        or site.name.startswith("os.environ") \
                        or site.name.startswith("conf.") \
                        or ".conf." in site.name
                    if bad:
                        self._emit(
                            findings, src.relpath, site.line, "jit-unsafe",
                            info.qualname,
                            f"{site.name}() inside a jitted function is "
                            f"read ONCE at trace time and baked into the "
                            f"compiled program — hoist it to a (static) "
                            f"argument")
            # Call sites passing literal containers in static positions.
            statics = _static_argnames(info.node)
            if not statics:
                continue
            params = [a.arg for a in info.node.args.args]
            positions = {params.index(s) for s in statics if s in params}
            for caller_site in graph.callers_of(info.fid):
                call_node = _find_call(graph, caller_site, info.name)
                if call_node is None:
                    continue
                for i, arg in enumerate(call_node.args):
                    if i in positions and isinstance(
                            arg, (ast.List, ast.Dict, ast.Set,
                                  ast.ListComp)):
                        caller = graph.functions[caller_site.caller]
                        self._emit(
                            findings, caller.path, arg.lineno,
                            "jit-unsafe", caller.qualname,
                            f"literal list/dict passed in static arg "
                            f"position {i} of jitted {info.name}() — "
                            f"unhashable static args raise (or retrace "
                            f"per call); pass a tuple")
                for kw in call_node.keywords:
                    if kw.arg in statics and isinstance(
                            kw.value, (ast.List, ast.Dict, ast.Set,
                                       ast.ListComp)):
                        caller = graph.functions[caller_site.caller]
                        self._emit(
                            findings, caller.path, kw.value.lineno,
                            "jit-unsafe", caller.qualname,
                            f"literal list/dict passed as static arg "
                            f"{kw.arg!r} of jitted {info.name}() — "
                            f"unhashable static args bust the jit cache; "
                            f"pass a tuple")


def _static_argnames(node) -> Set[str]:
    """``static_argnames`` of a ``partial(jax.jit, ...)`` decorator."""
    for dec in node.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        inner = call_name(dec)
        if not (inner == "partial" or inner.endswith(".partial")):
            continue
        for kw in dec.keywords:
            if kw.arg == "static_argnames" and \
                    isinstance(kw.value, (ast.Tuple, ast.List)):
                return {e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)}
    return set()


def _find_call(graph, site, name: str) -> Optional[ast.Call]:
    """The ast.Call node behind a CallSite (re-walked by line)."""
    caller = graph.functions.get(site.caller)
    if caller is None:
        return None
    for node in ast.walk(caller.node):
        if isinstance(node, ast.Call) and node.lineno == site.line and \
                call_name(node).endswith(name):
            return node
    return None


def line_key(findings: List[Finding], check: str, qualname: str) -> int:
    """Disambiguating suffix for multiple same-check findings in one
    function: the ordinal among those already collected (line numbers
    would churn the baseline on unrelated edits above)."""
    prefix = f"{check}:{qualname}:"
    return sum(1 for f in findings if f.ident.startswith(prefix))
