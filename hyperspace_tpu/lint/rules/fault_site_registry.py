"""fault-site-registry: fault-injection site strings must match the
declared ``SITES`` registry in ``io/faults.py`` — both directions.

A ``faults.check("stoer.put")`` typo is the worst kind of bug: the test
that armed the injector still passes (nothing fires), and the crash
matrix silently stops covering the site it thinks it covers.  The same
goes for ``FaultPlan(site=...)`` in tests.  Conversely, a registry site
no checkpoint ever calls is coverage theater.
"""

from __future__ import annotations

import ast
import difflib
from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.lint import catalog
from hyperspace_tpu.lint.engine import Finding, LintContext, const_str

_SCAN_INCLUDE = ("hyperspace_tpu/", "tests/", "bench.py")
# tests/test_lint.py carries deliberately-typo'd fixture sites.
_SCAN_EXCLUDE = ("hyperspace_tpu/lint/", "tests/test_lint.py")

# faults.<fn>(...) -> positional index of the site argument.
_SITE_ARG = {"check": 0, "fire": 0, "net": 0, "corrupt_file": 0,
             "write_payload": 2, "atomic_replace": 2}


def _site_from_call(node: ast.Call) -> Optional[Tuple[str, str]]:
    """(site, how) for a fault-checkpoint call or FaultPlan(...), else
    None.  Non-literal site args (conf-driven) are skipped — the conf
    path is covered by the registry validation inside faults.py itself."""
    func = node.func
    attr = None
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name) \
            and func.value.id == "faults":
        attr = func.attr
    elif isinstance(func, ast.Name) and func.id in _SITE_ARG:
        attr = func.id
    if attr in _SITE_ARG:
        idx = _SITE_ARG[attr]
        arg = node.args[idx] if len(node.args) > idx else None
        for kw in node.keywords:
            if kw.arg == "site":
                arg = kw.value
        s = const_str(arg) if arg is not None else None
        return (s, attr) if s is not None else None
    ctor = func.id if isinstance(func, ast.Name) else (
        func.attr if isinstance(func, ast.Attribute) else None)
    if ctor == "FaultPlan":
        arg = node.args[0] if node.args else None
        for kw in node.keywords:
            if kw.arg == "site":
                arg = kw.value
        s = const_str(arg) if arg is not None else None
        return (s, "FaultPlan") if s is not None else None
    return None


class Rule:
    name = "fault-site-registry"
    description = ("faults.check/fire site strings match the declared "
                   "SITES registry in io/faults.py")

    def run(self, ctx: LintContext) -> List[Finding]:
        sites, reg_line = catalog.fault_sites(ctx)
        findings: List[Finding] = []
        if not sites:
            return [Finding(self.name, catalog.FAULTS_PATH, 1,
                            "io/faults.py declares no SITES registry",
                            ident="no-registry")]
        used: Dict[str, List[Tuple[str, int, str]]] = {}
        for src in ctx.py_files(include=_SCAN_INCLUDE,
                                exclude=_SCAN_EXCLUDE):
            if src.tree is None:
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                hit = _site_from_call(node)
                if hit is None:
                    continue
                site, how = hit
                used.setdefault(site, []).append(
                    (src.relpath, node.lineno, how))

        for site, hits in sorted(used.items()):
            if site in sites:
                continue
            close = difflib.get_close_matches(site, sites, n=1, cutoff=0.7)
            hint = f" (did you mean {close[0]!r}?)" if close else ""
            for path, line, how in hits:
                findings.append(Finding(
                    self.name, path, line,
                    f"fault site {site!r} ({how}) is not in the io/faults.py "
                    f"SITES registry — it will silently never fire{hint}",
                    ident=f"unknown-site:{site}"))

        # Checkpoint coverage only counts sites wired into the ENGINE
        # (tests arming a site don't make it real).
        engine_used = {s for s, hits in used.items()
                       if any(p.startswith("hyperspace_tpu/")
                              and how != "FaultPlan"
                              for p, _l, how in hits)}
        for site in sorted(sites - engine_used):
            findings.append(Finding(
                self.name, catalog.FAULTS_PATH, reg_line,
                f"registry site {site!r} has no faults checkpoint in the "
                f"engine — dead registry entry or missing instrumentation",
                ident=f"unused-site:{site}"))
        return findings
