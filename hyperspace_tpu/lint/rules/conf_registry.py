"""conf-registry: every ``hyperspace.*`` key literal must be declared in
config.py, wired into ``_FIELD_BY_KEY``, documented in docs/02, and
actually used — in both directions, so the three surfaces cannot drift:

  - a literal used anywhere (engine, bench, tests, examples) that
    config.py does not declare is a typo'd or unregistered key — with
    near-miss suggestions, since ``conf.set`` raising ``KeyError`` at
    runtime is a far worse place to learn about it;
  - a declared key missing its docs/02 row is invisible to operators;
  - a docs/02 row for an undeclared key documents vapor;
  - a declared key no literal outside config.py ever mentions is dead
    weight (delete it, or baseline it with a reason if it is a
    compatibility placeholder).
"""

from __future__ import annotations

import ast
import difflib
from typing import Dict, List, Set, Tuple

from hyperspace_tpu.lint import catalog
from hyperspace_tpu.lint.engine import Finding, LintContext

# tests/test_lint.py is excluded: its fixture snippets deliberately
# contain typo'd keys (that's what they test).
_SCAN_EXCLUDE = (catalog.CONFIG_PATH, "hyperspace_tpu/lint/",
                 "tests/test_lint.py")


def _near_miss(key: str, declared) -> str:
    close = difflib.get_close_matches(key, declared, n=1, cutoff=0.8)
    return f" (did you mean {close[0]!r}?)" if close else ""


class Rule:
    name = "conf-registry"
    description = ("hyperspace.* conf keys agree across code, config.py, "
                   "and docs/02-configuration.md")

    def run(self, ctx: LintContext) -> List[Finding]:
        declared, wired, line_of, field_of = catalog.conf_registry(ctx)
        documented = catalog.documented_conf_keys(ctx)
        findings: List[Finding] = []
        if not declared:
            return [Finding(self.name, catalog.CONFIG_PATH, 1,
                            "could not parse the conf-key registry",
                            ident="unparseable")]

        # Three ways a key is "used" outside config.py: its string
        # literal, its constant name (NUM_BUCKETS), or its dataclass
        # field (conf.num_buckets / getattr(conf, "num_buckets", ...)).
        used: Dict[str, List[Tuple[str, int]]] = {}
        names_used: Set[str] = set()  # Name ids, Attribute attrs, strings
        for src in ctx.py_files(exclude=_SCAN_EXCLUDE):
            if src.tree is None:
                continue
            seen_here: Set[str] = set()
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str):
                    if catalog._CONF_KEY_RE.match(node.value):
                        if node.value not in seen_here:
                            seen_here.add(node.value)
                            used.setdefault(node.value, []).append(
                                (src.relpath, node.lineno))
                    else:
                        names_used.add(node.value)
                elif isinstance(node, ast.Name):
                    names_used.add(node.id)
                elif isinstance(node, ast.Attribute):
                    names_used.add(node.attr)

        for key, sites in sorted(used.items()):
            if key in declared:
                continue
            for path, line in sites:
                findings.append(Finding(
                    self.name, path, line,
                    f"conf key {key!r} is not declared in config.py"
                    f"{_near_miss(key, declared)}",
                    ident=f"undeclared:{key}"))

        for key, const in sorted(declared.items()):
            if key not in wired:
                findings.append(Finding(
                    self.name, catalog.CONFIG_PATH, line_of[key],
                    f"conf key {key!r} ({const}) is declared but not wired "
                    f"into _FIELD_BY_KEY (set()/get() raise KeyError on it)",
                    ident=f"unwired:{key}"))
            if key not in documented:
                findings.append(Finding(
                    self.name, catalog.CONFIG_PATH, line_of[key],
                    f"conf key {key!r} ({const}) has no row in "
                    f"docs/02-configuration.md",
                    ident=f"undocumented:{key}"))
            alive = key in used or const in names_used \
                or field_of.get(key) in names_used
            if not alive:
                findings.append(Finding(
                    self.name, catalog.CONFIG_PATH, line_of[key],
                    f"conf key {key!r} ({const}) is declared but neither "
                    f"its literal, its constant, nor its field "
                    f"({field_of.get(key, '?')}) is referenced outside "
                    f"config.py — dead key?",
                    ident=f"unused:{key}"))

        for key, line in sorted(documented.items()):
            if key not in declared:
                findings.append(Finding(
                    self.name, catalog.CONF_DOC_PATH, line,
                    f"docs/02 documents {key!r}, which config.py does not "
                    f"declare{_near_miss(key, declared)}",
                    ident=f"doc-undeclared:{key}"))
        return findings
