"""Rule registry.  A rule is an object with ``name``, ``description``,
and ``run(ctx) -> list[Finding]``; adding one = writing the module and
listing it here (docs/18-static-analysis.md, "Writing a new rule")."""

from __future__ import annotations

from typing import List


def all_rules() -> List[object]:
    from hyperspace_tpu.lint.rules import (
        conf_registry,
        exception_discipline,
        fault_site_registry,
        hygiene,
        io_seam,
        lock_discipline,
        telemetry_catalog,
    )

    return [
        conf_registry.Rule(),
        telemetry_catalog.Rule(),
        io_seam.Rule(),
        fault_site_registry.Rule(),
        exception_discipline.Rule(),
        lock_discipline.Rule(),
        hygiene.Rule(),
    ]
