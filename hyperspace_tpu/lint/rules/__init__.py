"""Rule registry.  A rule is an object with ``name``, ``description``,
and ``run(ctx) -> list[Finding]``; adding one = writing the module,
listing it here, and bumping ``CATALOG_VERSION`` (docs/18, "Writing a
new rule")."""

from __future__ import annotations

from typing import List

# Bumped whenever the rule set (or a rule's checks) changes shape: the
# baseline file records the version it was written against, and
# Hyperspace.doctor()'s lint check grades a mismatch as stale — old
# grandfathered fingerprints may hide findings the new rules would raise.
CATALOG_VERSION = 2


def all_rules() -> List[object]:
    from hyperspace_tpu.lint.rules import (
        blocking_discipline,
        conf_registry,
        device_discipline,
        exception_discipline,
        fault_site_registry,
        hygiene,
        io_seam,
        lock_discipline,
        telemetry_catalog,
    )

    return [
        conf_registry.Rule(),
        telemetry_catalog.Rule(),
        io_seam.Rule(),
        fault_site_registry.Rule(),
        exception_discipline.Rule(),
        lock_discipline.Rule(),
        device_discipline.Rule(),
        blocking_discipline.Rule(),
        hygiene.Rule(),
    ]
