"""``hslint --fix``: mechanical autofixes for the hygiene findings.

Scope is deliberately the MECHANICAL subset — edits whose correctness is
decidable from the AST alone:

  - ``dup-import`` / ``redundant-import`` / ``dead-import`` — remove the
    binding (the whole statement when it binds nothing else, just the
    alias otherwise);
  - ``mutable-default`` — rewrite ``def f(x=[])`` to ``x=None`` and
    insert the ``if x is None: x = []`` guard after the docstring.

Everything else (a lock-held store put, an unattributed device sync) is
a DESIGN decision and stays a human's job — the fixer refuses by
construction because it only consumes hygiene fingerprints.

``--fix --dry-run`` prints the unified diff and writes nothing; ``--fix``
applies and reports per-file edit counts.  Fix → relint is clean by
contract (tested in tests/test_lint.py): every fixed finding stops
firing and no new finding appears.
"""

from __future__ import annotations

import ast
import difflib
from typing import Dict, List, Optional, Sequence, Tuple

from hyperspace_tpu.lint.engine import Finding, LintContext

FIXABLE_PREFIXES = ("dup-import:", "redundant-import:", "dead-import:",
                    "mutable-default:")


def fixable(findings: Sequence[Finding]) -> List[Finding]:
    return [f for f in findings
            if f.rule == "hygiene" and not f.baselined
            and any(f.ident.startswith(p) for p in FIXABLE_PREFIXES)]


class FileFix:
    def __init__(self, relpath: str, before: str, after: str,
                 applied: List[Finding]) -> None:
        self.relpath = relpath
        self.before = before
        self.after = after
        self.applied = applied

    def diff(self) -> str:
        return "".join(difflib.unified_diff(
            self.before.splitlines(keepends=True),
            self.after.splitlines(keepends=True),
            fromfile=f"a/{self.relpath}", tofile=f"b/{self.relpath}"))


def plan_fixes(ctx: LintContext,
               findings: Sequence[Finding]) -> List[FileFix]:
    """Compute the edits for every fixable finding, one FileFix per
    touched file.  Pure: nothing is written."""
    by_path: Dict[str, List[Finding]] = {}
    for f in fixable(findings):
        by_path.setdefault(f.path, []).append(f)
    fixes: List[FileFix] = []
    for path, fs in sorted(by_path.items()):
        src = ctx.file(path)
        if src is None or src.tree is None:
            continue
        after, applied = _fix_file(src, fs)
        if applied and after != src.text:
            fixes.append(FileFix(path, src.text, after, applied))
    return fixes


def apply_fixes(root: str, fixes: Sequence[FileFix]) -> None:
    import os

    for fix in fixes:
        # The fixer rewrites SOURCE files in the working tree, not index
        # data — the LogStore seam has no business here.
        # hslint: allow[io-seam] source autofix, not index data
        with open(os.path.join(root, fix.relpath), "w",
                  encoding="utf-8") as f:
            f.write(fix.after)


# ---------------------------------------------------------------------------
# Per-file editing
# ---------------------------------------------------------------------------
def _fix_file(src, findings: List[Finding]) -> Tuple[str, List[Finding]]:
    lines = src.text.splitlines(keepends=True)
    # Line edits: lineno -> None (delete) | str (replace).  Applied
    # bottom-up so earlier linenos stay valid.
    edits: Dict[int, Optional[str]] = {}
    inserts: List[Tuple[int, str]] = []  # (after-lineno, text)
    applied: List[Finding] = []
    for f in findings:
        ok = False
        if f.ident.startswith(("dup-import:", "redundant-import:",
                               "dead-import:")):
            ok = _drop_import_binding(src, f, edits)
        elif f.ident.startswith("mutable-default:"):
            ok = _fix_mutable_default(src, f, edits, inserts)
        if ok:
            applied.append(f)
    if not applied:
        return src.text, []
    for lineno, ins in sorted(inserts, reverse=True):
        lines.insert(lineno, ins)
    for lineno in sorted(edits, reverse=True):
        repl = edits[lineno]
        if lineno - 1 >= len(lines):
            continue
        if repl is None:
            del lines[lineno - 1]
        else:
            lines[lineno - 1] = repl
    return "".join(lines), applied


def _drop_import_binding(src, f: Finding, edits) -> bool:
    """Remove the named alias from the import statement at the finding's
    line — the whole line when it binds nothing else."""
    name = f.ident.rsplit(":", 1)[-1]
    node = _import_at(src.tree, f.line)
    if node is None:
        return False
    keep = []
    for a in node.names:
        bound = a.asname or (a.name.split(".")[0]
                             if isinstance(node, ast.Import) else a.name)
        if bound != name:
            keep.append(a)
    if len(keep) == len(node.names):
        return False
    if not keep:
        # Multi-line imports (parenthesized from-imports) delete every
        # line of the statement.
        end = getattr(node, "end_lineno", node.lineno)
        for ln in range(node.lineno, end + 1):
            edits[ln] = None
        return True
    if getattr(node, "end_lineno", node.lineno) != node.lineno:
        # Parenthesized multi-name import: drop just the alias's line
        # when it sits alone on one (the repo style); otherwise skip.
        for ln in range(node.lineno,
                        getattr(node, "end_lineno", node.lineno) + 1):
            stripped = src.lines[ln - 1].strip().rstrip(",")
            cand = {name}
            for a in node.names:
                if (a.asname or a.name) == name and a.asname:
                    cand.add(f"{a.name} as {a.asname}")
            if stripped in cand:
                edits[ln] = None
                return True
        return False
    indent = src.lines[node.lineno - 1][
        :len(src.lines[node.lineno - 1])
        - len(src.lines[node.lineno - 1].lstrip())]
    rendered = ", ".join(
        a.name + (f" as {a.asname}" if a.asname else "") for a in keep)
    if isinstance(node, ast.Import):
        edits[node.lineno] = f"{indent}import {rendered}\n"
    else:
        dots = "." * node.level
        edits[node.lineno] = \
            f"{indent}from {dots}{node.module or ''} import {rendered}\n"
    return True


def _import_at(tree, lineno: int):
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)) and \
                node.lineno <= lineno <= getattr(node, "end_lineno",
                                                 node.lineno):
            return node
    return None


def _fix_mutable_default(src, f: Finding, edits, inserts) -> bool:
    """``def g(x=[])`` -> ``x=None`` + ``if x is None: x = []`` after the
    docstring.  Only single-line defaults whose source text is exactly
    reproducible are rewritten; anything fancier is left to a human."""
    fn = None
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in list(node.args.defaults) + \
                    [d for d in node.args.kw_defaults if d is not None]:
                if d.lineno == f.line and \
                        f.ident == f"mutable-default:{node.name}":
                    fn = (node, d)
                    break
        if fn:
            break
    if fn is None:
        return False
    node, d = fn
    if d.lineno != getattr(d, "end_lineno", d.lineno):
        return False
    line = src.lines[d.lineno - 1]
    default_src = line[d.col_offset:d.end_col_offset]
    # The parameter name owning this default.
    arg_name = None
    pos = node.args.args[len(node.args.args) - len(node.args.defaults):]
    for a, dd in zip(pos, node.args.defaults):
        if dd is d:
            arg_name = a.arg
    for a, dd in zip(node.args.kwonlyargs, node.args.kw_defaults):
        if dd is not None and dd is d:
            arg_name = a.arg
    if arg_name is None:
        return False
    edits[d.lineno] = line[:d.col_offset] + "None" + \
        line[d.end_col_offset:] + ("" if line.endswith("\n") else "\n")
    # Insert the guard after a leading docstring (if any).
    body_start = node.body[0]
    insert_after = node.body[0].lineno - 1  # line BEFORE first stmt
    if isinstance(body_start, ast.Expr) and \
            isinstance(body_start.value, ast.Constant) and \
            isinstance(body_start.value.value, str):
        insert_after = getattr(body_start, "end_lineno",
                               body_start.lineno)
        if len(node.body) > 1:
            pass  # guard goes between docstring and next stmt
    first_code = node.body[1] if (len(node.body) > 1 and
                                  insert_after >= node.body[0].lineno) \
        else node.body[0]
    indent = " " * first_code.col_offset
    inserts.append((
        insert_after,
        f"{indent}if {arg_name} is None:\n"
        f"{indent}    {arg_name} = {default_src}\n"))
    return True
