"""Module-level interprocedural call graph for hslint rules.

PR 8's rules are all single-module pattern checks; the device-discipline
and blocking-discipline families (docs/18-static-analysis.md) need to
reason PAST function boundaries: "is a blocking store put reachable from
this with-lock block?", "does every executor dispatch path reach a
deadline check?", "does this helper return a device array?".  This
module builds, once per lint run, the package call graph those queries
run over:

  - **function table** — every ``def`` in the package, keyed by a stable
    function id ``<relpath>::<qualname>`` (``Class.method`` qualnames,
    nested defs as ``outer.<locals>.inner``);
  - **import-aware call edges** — each :class:`CallSite` records the raw
    dotted callee name plus the in-package function ids it resolves to.
    Resolution understands ``import a.b as c``, ``from a.b import f``
    (including relative forms), same-file calls, ``self.method()``
    against the enclosing class and same-file bases, and
    ``ClassName(...)`` as ``ClassName.__init__``;
  - **lock-held context** — every call site carries the set of lock ids
    (``<relpath>:<scope>.<attr>``, discovered structurally like the
    lock-discipline rule) lexically held at the call, so rules can
    propagate "holding lock L" across call edges;
  - **cycle-tolerant reachability** — :meth:`CallGraph.find_path` does a
    BFS with a visited set, returning a witness chain of call sites so a
    finding can show the whole ``a -> b -> c`` path.

Pure stdlib, AST-only, never imports the checked package — the same
constraints as the rest of ``lint/`` (engine.py docstring).
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from hyperspace_tpu.lint.engine import LintContext, call_name

PACKAGE = "hyperspace_tpu"

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}


def module_of(relpath: str) -> str:
    """Dotted module name of a repo-relative path
    (``hyperspace_tpu/io/faults.py`` -> ``hyperspace_tpu.io.faults``)."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


class CallSite:
    """One call expression inside one function."""

    __slots__ = ("caller", "line", "name", "targets", "locks")

    def __init__(self, caller: str, line: int, name: str,
                 targets: Tuple[str, ...], locks: Tuple[str, ...]) -> None:
        self.caller = caller      # function id of the enclosing def
        self.line = line
        self.name = name          # raw dotted callee ("store.put", "f")
        self.targets = targets    # resolved in-package function ids
        self.locks = locks        # lock ids lexically held at the call

    def __repr__(self) -> str:  # debugging aid only
        return f"<CallSite {self.caller}:{self.line} {self.name}>"


class FunctionInfo:
    __slots__ = ("fid", "path", "qualname", "name", "lineno", "end_lineno",
                 "node", "class_name", "decorators")

    def __init__(self, fid: str, path: str, qualname: str, node) -> None:
        self.fid = fid
        self.path = path
        self.qualname = qualname
        self.name = node.name
        self.lineno = node.lineno
        self.end_lineno = getattr(node, "end_lineno", node.lineno)
        self.node = node
        parts = qualname.split(".")
        self.class_name = parts[-2] \
            if len(parts) >= 2 and parts[-2] != "<locals>" else ""
        self.decorators = [_decorator_name(d) for d in node.decorator_list]


def _decorator_name(dec: ast.AST) -> str:
    """``@jax.jit`` -> "jax.jit"; ``@partial(jax.jit, ...)`` ->
    "partial(jax.jit)"; anything else best-effort dotted text."""
    if isinstance(dec, ast.Call):
        inner = call_name(dec)
        if inner == "partial" or inner.endswith(".partial"):
            if dec.args:
                arg = dec.args[0]
                parts: List[str] = []
                cur = arg
                while isinstance(cur, ast.Attribute):
                    parts.append(cur.attr)
                    cur = cur.value
                if isinstance(cur, ast.Name):
                    parts.append(cur.id)
                    return f"partial({'.'.join(reversed(parts))})"
            return "partial(?)"
        return inner
    parts = []
    cur = dec
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


def is_jit_decorated(info: FunctionInfo) -> bool:
    """Is the function wrapped by ``jax.jit`` (directly or via
    ``partial(jax.jit, ...)``)?"""
    for d in info.decorators:
        if d in ("jax.jit", "jit", "partial(jax.jit)", "partial(jit)"):
            return True
    return False


class _FileIndex:
    """Per-file name environment: imports, module-level functions,
    classes (methods + same-file bases), module-level locks."""

    def __init__(self, src, modules: Dict[str, str]) -> None:
        self.src = src
        self.mod_alias: Dict[str, str] = {}     # local name -> module
        self.from_names: Dict[str, Tuple[str, str]] = {}  # name -> (mod, attr)
        self.classes: Dict[str, ast.ClassDef] = {}
        self.class_bases: Dict[str, List[str]] = {}
        self.module_funcs: Set[str] = set()
        self.module_locks: Set[str] = set()
        self._collect(src, modules)

    def _collect(self, src, modules: Dict[str, str]) -> None:
        pkg_parts = module_of(src.relpath).split(".")
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    top = a.name.split(".")[0]
                    if a.asname:
                        if a.name in modules:
                            self.mod_alias[a.asname] = a.name
                    elif top == PACKAGE:
                        # ``import hyperspace_tpu.io.faults`` binds the
                        # root; dotted call names are resolved directly.
                        self.mod_alias.setdefault(PACKAGE, PACKAGE)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    # Relative import: resolve against this file's package.
                    anchor = pkg_parts[:-1]  # drop the module's own name
                    if src.relpath.endswith("__init__.py"):
                        anchor = pkg_parts
                    if node.level > 1:
                        anchor = anchor[: -(node.level - 1)] \
                            if node.level - 1 <= len(anchor) else []
                    base = ".".join(anchor + ([base] if base else []))
                if not base.startswith(PACKAGE):
                    continue
                for a in node.names:
                    local = a.asname or a.name
                    sub = f"{base}.{a.name}"
                    if sub in modules:
                        self.mod_alias[local] = sub
                    elif base in modules:
                        self.from_names[local] = (base, a.name)
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_funcs.add(node.name)
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                self.class_bases[node.name] = [
                    b.id for b in node.bases if isinstance(b, ast.Name)]
            elif isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks.add(t.id)


def _is_lock_ctor(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else "")
    return name in _LOCK_CTORS


def _class_lock_attrs(cls: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    out.add(t.attr)
    return out


class _SiteCollector(ast.NodeVisitor):
    """Walk ONE function body (nested defs excluded — they are their own
    graph nodes), tracking the lexical with-lock stack and recording
    every call expression."""

    def __init__(self, graph: "CallGraph", index: _FileIndex,
                 info: FunctionInfo, lock_names: Set[str],
                 lock_scope: str) -> None:
        self.graph = graph
        self.index = index
        self.info = info
        self.lock_names = lock_names  # "self.X" / module-global names
        self.lock_scope = lock_scope  # "<relpath>:<Class|<module>>"
        self.stack: List[str] = []
        self.sites: List[CallSite] = []

    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and f"self.{expr.attr}" \
                in self.lock_names:
            return f"{self.lock_scope}.{expr.attr}"
        if isinstance(expr, ast.Name) and expr.id in self.lock_names:
            return f"{self.lock_scope}.{expr.id}"
        return None

    def visit_FunctionDef(self, node) -> None:  # nested def: own node
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node: ast.With) -> None:
        held = []
        for item in node.items:
            if isinstance(item.context_expr, ast.Call):
                self.visit(item.context_expr)
            lock = self._lock_id(item.context_expr)
            if lock is not None:
                self.stack.append(lock)
                held.append(lock)
        for stmt in node.body:
            self.visit(stmt)
        for _ in held:
            self.stack.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call) -> None:
        raw = call_name(node)
        if raw:
            targets = self.graph._resolve(self.index, self.info, raw)
            self.sites.append(CallSite(
                self.info.fid, node.lineno, raw, tuple(targets),
                tuple(self.stack)))
        self.generic_visit(node)


class CallGraph:
    """The package call graph.  Build once per run with
    :meth:`CallGraph.build`; rules share the instance through
    :func:`for_context` (keyed on the :class:`LintContext` identity)."""

    def __init__(self, ctx: LintContext) -> None:
        self.ctx = ctx
        self.modules: Dict[str, str] = {}        # module name -> relpath
        self.functions: Dict[str, FunctionInfo] = {}
        self.sites: Dict[str, List[CallSite]] = {}  # caller fid -> sites
        self._by_file: Dict[str, List[str]] = {}    # relpath -> fids
        self._indexes: Dict[str, _FileIndex] = {}
        self._build()

    # -- construction --------------------------------------------------------
    def _build(self) -> None:
        files = [f for f in self.ctx.py_files(include=(f"{PACKAGE}/",))
                 if f.tree is not None]
        for src in files:
            self.modules[module_of(src.relpath)] = src.relpath
        for src in files:
            self._indexes[src.relpath] = _FileIndex(src, self.modules)
            self._collect_functions(src)
        for src in files:
            self._collect_sites(src)

    def _collect_functions(self, src) -> None:
        fids = self._by_file.setdefault(src.relpath, [])

        def walk(body, prefix: str) -> None:
            for node in body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    qual = f"{prefix}{node.name}"
                    fid = f"{src.relpath}::{qual}"
                    info = FunctionInfo(fid, src.relpath, qual, node)
                    self.functions[fid] = info
                    fids.append(fid)
                    walk(node.body, f"{qual}.<locals>.")
                elif isinstance(node, ast.ClassDef):
                    walk(node.body, f"{prefix}{node.name}.")

        walk(src.tree.body, "")

    def _collect_sites(self, src) -> None:
        index = self._indexes[src.relpath]
        for fid in self._by_file.get(src.relpath, ()):
            info = self.functions[fid]
            cls = self._enclosing_class(info)
            lock_names: Set[str] = set(index.module_locks)
            scope = f"{src.relpath}:<module>"
            if cls is not None:
                scope = f"{src.relpath}:{cls.name}"
                for attr in self._all_lock_attrs(index, cls.name):
                    lock_names.add(f"self.{attr}")
            coll = _SiteCollector(self, index, info, lock_names, scope)
            for stmt in info.node.body:
                coll.visit(stmt)
            self.sites[fid] = coll.sites

    def _enclosing_class(self, info: FunctionInfo) -> Optional[ast.ClassDef]:
        parts = info.qualname.split(".")
        if len(parts) >= 2 and parts[-2] != "<locals>":
            return self._indexes[info.path].classes.get(parts[0]) \
                if len(parts) == 2 else \
                self._indexes[info.path].classes.get(parts[-2])
        return None

    def _all_lock_attrs(self, index: _FileIndex, cls_name: str,
                        _seen: Optional[Set[str]] = None) -> Set[str]:
        """Lock attrs of a class plus its same-file bases."""
        seen = _seen or set()
        if cls_name in seen or cls_name not in index.classes:
            return set()
        seen.add(cls_name)
        out = _class_lock_attrs(index.classes[cls_name])
        for base in index.class_bases.get(cls_name, ()):
            out |= self._all_lock_attrs(index, base, seen)
        return out

    # -- resolution ----------------------------------------------------------
    def _module_func(self, module: str, attr: str) -> List[str]:
        relpath = self.modules.get(module)
        if relpath is None:
            return []
        out = []
        fid = f"{relpath}::{attr}"
        if fid in self.functions:
            out.append(fid)
        init = f"{relpath}::{attr}.__init__"
        if init in self.functions:
            out.append(init)
        return out

    def _class_method(self, path: str, cls_name: str, method: str,
                      _seen: Optional[Set[str]] = None) -> List[str]:
        """Resolve ``self.method`` against a class and its same-file
        bases (nearest definition wins)."""
        seen = _seen or set()
        if cls_name in seen:
            return []
        seen.add(cls_name)
        index = self._indexes.get(path)
        if index is None or cls_name not in index.classes:
            return []
        fid = f"{path}::{cls_name}.{method}"
        if fid in self.functions:
            return [fid]
        for base in index.class_bases.get(cls_name, ()):
            found = self._class_method(path, base, method, seen)
            if found:
                return found
        return []

    def _resolve(self, index: _FileIndex, info: FunctionInfo,
                 raw: str) -> List[str]:
        parts = raw.split(".")
        path = info.path
        if len(parts) == 1:
            name = parts[0]
            if name in index.module_funcs:
                return [f"{path}::{name}"]
            if name in index.classes:
                return self._class_method(path, name, "__init__")
            if name in index.from_names:
                mod, attr = index.from_names[name]
                return self._module_func(mod, attr)
            if name in index.mod_alias:  # callable module alias — not a call
                return []
            # A nested def of this function, or a sibling nested def of
            # the same enclosing function.
            own = f"{path}::{info.qualname}.<locals>.{name}"
            if own in self.functions:
                return [own]
            if "." in info.qualname:
                outer = info.qualname.rsplit(".", 1)[0]
                fid = f"{path}::{outer}.{name}" \
                    if outer.endswith("<locals>") else \
                    f"{path}::{outer}.<locals>.{name}"
                if fid in self.functions:
                    return [fid]
            return []
        if parts[0] == "self" and len(parts) == 2:
            qparts = info.qualname.split(".")
            if len(qparts) >= 2 and qparts[-2] != "<locals>":
                cls_name = qparts[-2]
                return self._class_method(path, cls_name, parts[1])
            return []
        if parts[0] == "cls" and len(parts) == 2:
            qparts = info.qualname.split(".")
            if len(qparts) >= 2 and qparts[-2] != "<locals>":
                return self._class_method(path, qparts[-2], parts[1])
            return []
        # ClassName.method within the same file.
        if parts[0] in index.classes and len(parts) == 2:
            return self._class_method(path, parts[0], parts[1])
        # module alias chains: faults.check / np.asarray / a.b.f
        head = parts[0]
        if head in index.mod_alias:
            base = index.mod_alias[head]
            mod = ".".join([base] + parts[1:-1])
            return self._module_func(mod, parts[-1])
        if head == PACKAGE:
            mod = ".".join(parts[:-1])
            return self._module_func(mod, parts[-1])
        # imported-class method: ``from x import C`` then C.build(...)
        if head in index.from_names and len(parts) == 2:
            mod, attr = index.from_names[head]
            relpath = self.modules.get(mod)
            if relpath is not None:
                return self._class_method(relpath, attr, parts[1])
        return []

    # -- queries -------------------------------------------------------------
    def function(self, path: str, qualname: str) -> Optional[FunctionInfo]:
        return self.functions.get(f"{path}::{qualname}")

    def functions_in(self, path: str) -> List[FunctionInfo]:
        return [self.functions[f] for f in self._by_file.get(path, ())]

    def sites_of(self, fid: str) -> List[CallSite]:
        return self.sites.get(fid, [])

    def callers_of(self, fid: str) -> List[CallSite]:
        out = []
        for sites in self.sites.values():
            for s in sites:
                if fid in s.targets:
                    out.append(s)
        return out

    def find_path(
        self,
        start: str,
        site_pred: Callable[[CallSite], bool],
        max_nodes: int = 4000,
    ) -> Optional[Tuple[List[str], CallSite]]:
        """Cycle-tolerant BFS from function ``start``: the first call
        site (in BFS order) matching ``site_pred``, plus the chain of
        function ids walked to reach it (``[start, ..., site.caller]``).
        Returns None when nothing matches within ``max_nodes``."""
        if start not in self.functions:
            return None
        seen: Set[str] = {start}
        queue: List[Tuple[str, List[str]]] = [(start, [start])]
        while queue and len(seen) <= max_nodes:
            fid, chain = queue.pop(0)
            for site in self.sites.get(fid, ()):
                if site_pred(site):
                    return chain, site
                for target in site.targets:
                    if target not in seen:
                        seen.add(target)
                        queue.append((target, chain + [target]))
        return None

    def reaches(self, start: str,
                site_pred: Callable[[CallSite], bool]) -> bool:
        return self.find_path(start, site_pred) is not None


# ---------------------------------------------------------------------------
# One graph per lint run, shared by every rule
# ---------------------------------------------------------------------------
_CACHE: List[Tuple[int, CallGraph]] = []


def for_context(ctx: LintContext) -> CallGraph:
    for key, graph in _CACHE:
        if key == id(ctx):
            return graph
    graph = CallGraph(ctx)
    del _CACHE[:]
    _CACHE.append((id(ctx), graph))
    return graph


def describe_chain(graph: CallGraph, chain: Sequence[str],
                   site: CallSite) -> str:
    """Human-readable ``a -> b -> c -> prim() (file:line)`` witness."""
    names = [graph.functions[f].qualname for f in chain
             if f in graph.functions]
    hop = " -> ".join(names + [f"{site.name}()"])
    return f"{hop} ({site.caller.split('::')[0]}:{site.line})"
