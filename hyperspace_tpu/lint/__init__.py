"""hslint: AST-based invariant checker for this repository's contracts.

Seven PRs of growth left the system's load-bearing contracts encoded as
string conventions — conf-key literals that must agree with ``config.py``
and docs/02, a metric/span catalog in docs/16, fault-injection site names
that silently no-op when typo'd, a LogStore/fault-injection IO seam any
stray ``open()`` bypasses, and a serving layer whose thread safety rests
on lock discipline.  This package makes those invariants machine-checked:

    python -m hyperspace_tpu.lint            # human output, exit 1 on new
    python -m hyperspace_tpu.lint --json     # machine output
    python -m hyperspace_tpu.lint --check-catalog --trace t.jsonl

Pure stdlib (``ast`` + text parsing) — the linter never imports the
package it checks, so it runs in any environment, including CI images
without jax.  See docs/18-static-analysis.md for the rule catalog, the
baseline workflow, the allowlist pragma syntax, and how to add a rule.
"""

from hyperspace_tpu.lint.engine import (  # noqa: F401 — public surface
    Finding,
    LintContext,
    load_baseline,
    run_lint,
    write_baseline,
)
