"""SARIF 2.1.0 rendering for hslint findings.

SARIF is the interchange format CI code-scanning UIs ingest (GitHub's
``upload-sarif`` action annotates PR diffs with per-line findings from
it).  One run object, one driver (``hslint``), one rule entry per lint
rule, one result per NEW finding — baselined findings are suppressed
(`suppressions`, kind "external") so the annotations match the CLI's
exit-code contract exactly.
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence

from hyperspace_tpu.lint.engine import Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def render_sarif(findings: Sequence[Finding], rules,
                 root: str) -> str:
    rule_index: Dict[str, int] = {}
    rule_objs: List[dict] = []
    for r in rules:
        rule_index[r.name] = len(rule_objs)
        rule_objs.append({
            "id": r.name,
            "shortDescription": {"text": r.description},
            "helpUri": "docs/18-static-analysis.md",
        })
    results = []
    for f in findings:
        if f.rule not in rule_index:  # parse errors et al.
            rule_index[f.rule] = len(rule_objs)
            rule_objs.append({"id": f.rule,
                              "shortDescription": {"text": f.rule}})
        res = {
            "ruleId": f.rule,
            "ruleIndex": rule_index[f.rule],
            "level": "error",
            "message": {"text": f.message},
            "partialFingerprints": {"hslint/v1": f.fingerprint},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
        }
        if f.baselined:
            res["suppressions"] = [{"kind": "external",
                                    "justification": "hslint baseline"}]
        results.append(res)
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "hslint",
                "informationUri": "docs/18-static-analysis.md",
                "rules": rule_objs,
            }},
            "originalUriBaseIds": {"SRCROOT": {"uri": f"file://{root}/"}},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)
