"""Configuration registry: every tunable in one place, with typed accessors.

Mirrors the reference's key/default registry (index/IndexConstants.scala:21-114)
and typed accessor layer (util/HyperspaceConf.scala:26-118), collapsed into a
single dataclass because we own the session object instead of riding Spark's
string-keyed SQLConf.  String-keyed get/set is still supported (``set``/``get``)
so tests and the Python API can flip flags the way Spark conf users do.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional

# Canonical string keys (kept spark-compatible in spirit so reference users
# can map their configs 1:1; see docs/_docs/02-ug-configuration.md:9-23).
SYSTEM_PATH = "hyperspace.system.path"
NUM_BUCKETS = "hyperspace.index.numBuckets"
NUM_BUCKETS_LEGACY = "hyperspace.index.num.buckets"  # HyperspaceConf.scala:109-117
LINEAGE_ENABLED = "hyperspace.index.lineage.enabled"
HYBRID_SCAN_ENABLED = "hyperspace.index.hybridscan.enabled"
HYBRID_SCAN_APPENDED_RATIO = "hyperspace.index.hybridscan.maxAppendedRatio"
HYBRID_SCAN_DELETED_RATIO = "hyperspace.index.hybridscan.maxDeletedRatio"
OPTIMIZE_FILE_SIZE_THRESHOLD = "hyperspace.index.optimize.fileSizeThreshold"
INDEX_MAX_ROWS_PER_FILE = "hyperspace.index.maxRowsPerFile"
FILTER_RULE_USE_BUCKET_SPEC = "hyperspace.index.filterRule.useBucketSpec"
CACHE_EXPIRY_SECONDS = "hyperspace.index.cache.expiryDurationInSeconds"
SOURCE_PROVIDERS = "hyperspace.index.sources.fileBasedBuilders"
SIGNATURE_PROVIDER = "hyperspace.index.signatureProvider"
LOG_MANAGER_CLASS = "hyperspace.index.logManagerClass"
LOG_STORE_CLASS = "hyperspace.index.logStoreClass"
CONCURRENCY_MAX_RETRIES = "hyperspace.index.concurrency.maxRetries"
DEGRADED_FALLBACK_TO_SOURCE = "hyperspace.system.degraded.fallbackToSource"
OBJECT_STORE_STALE_LIST_MS = "hyperspace.system.objectStore.staleListMs"
EVENT_LOGGER = "hyperspace.eventLoggerClass"
SUPPORTED_FILE_FORMATS = "hyperspace.index.supportedFileFormats"
DEVICE_BATCH_ROWS = "hyperspace.tpu.deviceBatchRows"
DEVICE_FILTER_MIN_ROWS = "hyperspace.tpu.deviceFilterMinRows"
MESH_FILTER_MIN_ROWS = "hyperspace.tpu.meshFilterMinRows"
INDEX_FILE_COMPRESSION = "hyperspace.tpu.indexFileCompression"
DEVICE_JOIN_MIN_ROWS = "hyperspace.tpu.deviceJoinMinRows"
DEVICE_BUILD_MIN_ROWS = "hyperspace.tpu.deviceBuildMinRows"
MESH_JOIN_MIN_ROWS = "hyperspace.tpu.meshJoinMinRows"
DEVICE_AGG_MIN_ROWS = "hyperspace.tpu.deviceAggMinRows"
DEVICE_RESIDENT_MIN_ROWS = "hyperspace.tpu.deviceResidentMinRows"
DEVICE_CACHE_BYTES = "hyperspace.tpu.deviceCacheBytes"
DEVICE_CACHE_POLICY = "hyperspace.tpu.deviceCachePolicy"
PARALLEL_BUILD = "hyperspace.tpu.parallelBuild"
SHUFFLE_CAPACITY_SLACK = "hyperspace.tpu.shuffleCapacitySlack"
MESH_ENABLED = "hyperspace.parallel.mesh.enabled"
MESH_MAX_DEVICES = "hyperspace.parallel.mesh.maxDevices"
MESH_AGG_MIN_ROWS = "hyperspace.tpu.meshAggMinRows"
BUILD_PIPELINE_ENABLED = "hyperspace.index.build.pipeline.enabled"
BUILD_PREFETCH_DEPTH = "hyperspace.index.build.prefetchDepth"
BUILD_FINALIZE_WORKERS = "hyperspace.index.build.finalizeWorkers"
MULTIHOST_BUILD_HOSTS = "hyperspace.index.build.multihost.hosts"
MULTIHOST_BUILD_CLAIM_TTL_S = "hyperspace.index.build.multihost.claimTtlS"
MULTIHOST_BUILD_POLL_S = "hyperspace.index.build.multihost.pollS"
MULTIHOST_BUILD_DEADLINE_S = "hyperspace.index.build.multihost.deadlineS"
GLOBBING_PATTERN = "hyperspace.source.globbingPattern"
DISPLAY_MODE = "hyperspace.explain.displayMode"
HIGHLIGHT_BEGIN_TAG = "hyperspace.explain.displayMode.highlight.beginTag"
HIGHLIGHT_END_TAG = "hyperspace.explain.displayMode.highlight.endTag"
AUTO_RECOVERY_ENABLED = "hyperspace.index.autoRecovery.enabled"
AUTO_REPAIR_ENABLED = "hyperspace.index.autoRepair.enabled"
INTEGRITY_DIGEST_ON_WRITE = "hyperspace.system.integrity.digestOnWrite"
INTEGRITY_QUARANTINE_ON_FAILURE = \
    "hyperspace.system.integrity.quarantineOnReadFailure"
IO_RETRY_MAX_ATTEMPTS = "hyperspace.system.io.retry.maxAttempts"
IO_RETRY_INITIAL_BACKOFF_MS = "hyperspace.system.io.retry.initialBackoffMs"
IO_RETRY_MAX_BACKOFF_MS = "hyperspace.system.io.retry.maxBackoffMs"
TELEMETRY_TRACING_ENABLED = "hyperspace.system.telemetry.tracing.enabled"
TELEMETRY_TRACE_SINK = "hyperspace.system.telemetry.trace.sink"
TELEMETRY_TRACE_MAX_BYTES = "hyperspace.system.telemetry.trace.maxBytes"
DEVICE_GUARD_ENABLED = "hyperspace.system.deviceGuard.enabled"
TIMELINE_ENABLED = "hyperspace.system.timeline.enabled"
TIMELINE_MAX_INTERVALS = "hyperspace.system.timeline.maxIntervals"
TIMELINE_MEMORY_SAMPLE_MS = "hyperspace.system.timeline.memorySampleMs"
DOCTOR_LATENCY_SLO_MS = "hyperspace.doctor.latencySloMs"
DOCTOR_SHED_WARN_RATIO = "hyperspace.doctor.shedWarnRatio"
DOCTOR_DEVICE_SKEW_WARN = "hyperspace.doctor.deviceSkewWarn"
FLEET_TELEMETRY_ENABLED = "hyperspace.fleet.telemetry.enabled"
FLEET_PUBLISH_INTERVAL_S = "hyperspace.fleet.telemetry.publishIntervalS"
FLEET_STALE_AFTER_S = "hyperspace.fleet.telemetry.staleAfterS"
FLEET_PRUNE_AFTER_S = "hyperspace.fleet.telemetry.pruneAfterS"
BUILD_PROFILING_ENABLED = "hyperspace.system.buildProfiling.enabled"
PERF_LEDGER_ENABLED = "hyperspace.system.perf.ledger.enabled"
PERF_LEDGER_MAX_ENTRIES = "hyperspace.system.perf.ledger.maxEntries"
ADVISOR_CAPTURE_ENABLED = "hyperspace.advisor.capture.enabled"
ADVISOR_CAPTURE_MAX_ENTRIES = "hyperspace.advisor.capture.maxEntries"
ADVISOR_MAX_CANDIDATES = "hyperspace.advisor.maxCandidates"
SERVING_WORKERS = "hyperspace.serving.workers"
SERVING_QUEUE_DEPTH = "hyperspace.serving.queueDepth"
SERVING_MAX_CONNECTIONS = "hyperspace.serving.maxConnections"
SERVING_DEFAULT_DEADLINE_MS = "hyperspace.serving.defaultDeadlineMs"
SERVING_REQUEST_TIMEOUT_S = "hyperspace.serving.requestTimeoutS"
SERVING_SEND_TIMEOUT_S = "hyperspace.serving.sendTimeoutS"
SERVING_DRAIN_GRACE_S = "hyperspace.serving.drainGraceS"
SERVING_SHED_RSS_MB = "hyperspace.serving.shed.rssWatermarkMb"
SERVING_SHED_QUEUE_WAIT_MS = "hyperspace.serving.shed.queueWaitWatermarkMs"
SERVING_PLAN_CACHE_ENABLED = "hyperspace.serving.planCache.enabled"
SERVING_PLAN_CACHE_BYTES = "hyperspace.serving.planCacheBytes"
SERVING_IO_MODE = "hyperspace.serving.ioMode"
SERVING_TENANT_MAX_QUEUED = "hyperspace.serving.tenant.maxQueued"
FLIGHT_RECORDER_ENABLED = "hyperspace.serving.flightRecorder.enabled"
FLIGHT_RECORDER_MAX_RECORDS = "hyperspace.serving.flightRecorder.maxRecords"
FLIGHT_RECORDER_SLOW_MS = "hyperspace.serving.flightRecorder.slowMs"
FLIGHT_RECORDER_HEALTHY_SAMPLE_N = \
    "hyperspace.serving.flightRecorder.healthySampleN"
FLIGHT_RECORDER_MAX_BUNDLES = "hyperspace.serving.flightRecorder.maxBundles"
LIFECYCLE_ENABLED = "hyperspace.lifecycle.enabled"
LIFECYCLE_INTERVAL_S = "hyperspace.lifecycle.intervalS"
LIFECYCLE_BYTE_BUDGET = "hyperspace.lifecycle.byteBudget"
LIFECYCLE_QUICK_APPEND_RATIO = "hyperspace.lifecycle.quickAppendRatio"
LIFECYCLE_FULL_CHURN_RATIO = "hyperspace.lifecycle.fullChurnRatio"
LIFECYCLE_JOURNAL_MAX_ENTRIES = "hyperspace.lifecycle.journal.maxEntries"
LIFECYCLE_BACKOFF_INITIAL_S = "hyperspace.lifecycle.backoff.initialS"
LIFECYCLE_BACKOFF_MAX_S = "hyperspace.lifecycle.backoff.maxS"
LIFECYCLE_LEASE_ENABLED = "hyperspace.lifecycle.lease.enabled"
LIFECYCLE_LEASE_TTL_S = "hyperspace.lifecycle.lease.ttlS"
LIFECYCLE_CDC_ENABLED = "hyperspace.lifecycle.cdc.enabled"
LIFECYCLE_CDC_MERGE_DEBT_RATIO = "hyperspace.lifecycle.cdc.mergeDebtRatio"
LIFECYCLE_COMPACTION_ENABLED = "hyperspace.lifecycle.compaction.enabled"
LIFECYCLE_COMPACTION_MIN_SMALL_FILES = \
    "hyperspace.lifecycle.compaction.minSmallFiles"
LIFECYCLE_COMPACTION_MODE = "hyperspace.lifecycle.compaction.mode"
WATCH_ENABLED = "hyperspace.system.watch.enabled"
WATCH_MODE = "hyperspace.system.watch.mode"
WATCH_POLL_INTERVAL_S = "hyperspace.system.watch.pollIntervalS"
WATCH_DEBOUNCE_MS = "hyperspace.system.watch.debounceMs"
FAULT_INJECTION_ENABLED = "hyperspace.system.faultInjection.enabled"
FAULT_INJECTION_SITE = "hyperspace.system.faultInjection.site"
FAULT_INJECTION_KIND = "hyperspace.system.faultInjection.kind"
FAULT_INJECTION_AT = "hyperspace.system.faultInjection.at"
FAULT_INJECTION_COUNT = "hyperspace.system.faultInjection.count"
FAULT_INJECTION_LATENCY_MS = "hyperspace.system.faultInjection.latencyMs"
FAULT_INJECTION_HANG_S = "hyperspace.system.faultInjection.hangS"
CLIENT_HEDGE_ENABLED = "hyperspace.client.hedge.enabled"
CLIENT_HEDGE_DELAY_MS = "hyperspace.client.hedge.delayMs"
CLIENT_BREAKER_ENABLED = "hyperspace.client.breaker.enabled"
CLIENT_BREAKER_FAILURES = "hyperspace.client.breaker.failures"
CLIENT_BREAKER_COOLDOWN_MS = "hyperspace.client.breaker.cooldownMs"
ALERTS_ENABLED = "hyperspace.alerts.enabled"
ALERTS_INTERVAL_S = "hyperspace.alerts.intervalS"
ALERTS_AVAILABILITY_TARGET = "hyperspace.alerts.availabilityTarget"
ALERTS_LATENCY_TARGET = "hyperspace.alerts.latencyTarget"
ALERTS_FAST_SHORT_S = "hyperspace.alerts.fastShortS"
ALERTS_FAST_LONG_S = "hyperspace.alerts.fastLongS"
ALERTS_FAST_FACTOR = "hyperspace.alerts.fastFactor"
ALERTS_SLOW_SHORT_S = "hyperspace.alerts.slowShortS"
ALERTS_SLOW_LONG_S = "hyperspace.alerts.slowLongS"
ALERTS_SLOW_FACTOR = "hyperspace.alerts.slowFactor"
ALERTS_PENDING_EVALS = "hyperspace.alerts.pendingEvals"
ALERTS_RESOLVE_EVALS = "hyperspace.alerts.resolveEvals"
ALERTS_STALENESS_WARN_S = "hyperspace.alerts.stalenessWarnS"
ALERTS_MAX_ENTRIES = "hyperspace.alerts.maxEntries"
ALERTS_NOTIFY_COMMAND = "hyperspace.alerts.notify.command"

_DEFAULT_NUM_BUCKETS = 200  # IndexConstants.scala:31-32 (spark.sql.shuffle.partitions default)


def _index_compression_default() -> str:
    from hyperspace_tpu.io.parquet import INDEX_COMPRESSION_DEFAULT

    return INDEX_COMPRESSION_DEFAULT


@dataclasses.dataclass
class HyperspaceConf:
    """Session-scoped configuration.

    Defaults follow index/IndexConstants.scala:
      - num_buckets=200            (:31-32)
      - hybrid scan off, appended<=0.3 / deleted<=0.2 byte ratios (:40-48)
      - filter-rule bucket spec off (:52-53)
      - cache TTL 300 s            (:61-63)
      - optimize threshold 256 MB  (:91-92)
      - lineage off                (:97-99)
    """

    system_path: Optional[str] = None
    num_buckets: int = _DEFAULT_NUM_BUCKETS
    lineage_enabled: bool = False
    hybrid_scan_enabled: bool = False
    hybrid_scan_max_appended_ratio: float = 0.3
    hybrid_scan_max_deleted_ratio: float = 0.2
    optimize_file_size_threshold: int = 256 * 1024 * 1024
    # Split each bucket's sorted run into files of at most this many rows
    # (0 = one file per bucket).  Smaller files = finer per-file min/max
    # pruning granularity (and bounded Parquet sizes at scale).
    index_max_rows_per_file: int = 0
    filter_rule_use_bucket_spec: bool = False
    cache_expiry_seconds: int = 300
    source_providers: str = "default,delta,iceberg"
    signature_provider: str = "IndexSignatureProvider"
    # Operation-log backend, a dotted class path.  The default uses POSIX
    # create-if-absent + atomic rename; object stores without atomic
    # rename (e.g. GCS/S3 generation-/etag-conditional puts) plug in a
    # subclass of IndexLogManager here — the seam SURVEY.md §7 flags as a
    # hard part of the reference's HDFS-rename assumption.
    log_manager_class: str = (
        "hyperspace_tpu.index.log_manager.IndexLogManager")
    # Storage backend for ObjectStoreLogManager (a LogStore subclass,
    # io/log_store.py): conditional-put primitives the rename-less log
    # protocol is built on.  Ignored by the default POSIX manager.
    log_store_class: str = "hyperspace_tpu.io.log_store.EmulatedObjectStore"
    # Optimistic transaction loop (actions/base.py): on a concurrent-write
    # conflict the action re-validates against the new latest log id and
    # retries with jittered backoff, up to this many extra attempts
    # (0 = the reference's abort-on-conflict behavior).
    concurrency_max_retries: int = 3
    # Degraded-mode querying: an index whose log is unreadable, torn past
    # recovery, or whose store is erroring is SKIPPED by the rewrite rules
    # — the query answers from the source scan and telemetry records an
    # IndexDegradedEvent.  Off = such an index raises instead (strict).
    degraded_fallback_to_source: bool = True
    # EmulatedObjectStore listing-visibility window (ms): keys committed
    # within the window are hidden from list operations (point reads stay
    # strong) — the eventual-consistency shape object-store listings have.
    object_store_stale_list_ms: float = 0.0
    event_logger: str = ""
    # Reference default allow-list (HyperspaceConf.scala:97).
    supported_file_formats: str = "avro,csv,json,orc,parquet,text"
    # TPU data-plane tunable: kernel row dimensions are padded up to the
    # next multiple of this, so builds of different datasets share one
    # compiled program per capacity instead of paying a fresh XLA compile
    # per distinct row count.  Env HS_DEVICE_BATCH_ROWS overrides the
    # default (the test suite shrinks it so tiny CPU builds stay tiny).
    device_batch_rows: int = dataclasses.field(
        default_factory=lambda: int(
            os.environ.get("HS_DEVICE_BATCH_ROWS", 1 << 20)))
    # Below this row count a filter evaluates host-side (arrow compute): a
    # device round trip costs fixed transfer latency plus per-row upload,
    # which a vectorized host pass may never repay (measured at 6M rows
    # over a remote tunnel, the upload alone exceeds the whole host pass
    # by >100x).  None (the default) derives the threshold from MEASURED
    # attachment physics at first use (utils/calibrate.py): a remote
    # tunnel calibrates to "never organically", a locally attached chip
    # to a realistic batch size.  Set an int to pin it (always wins).
    device_filter_min_rows: Optional[int] = None
    # At or above this row count a device-eligible filter shards its
    # columns over ALL visible devices (1-D mesh) instead of evaluating on
    # one chip: the predicate is elementwise, so XLA partitions it with
    # zero collectives and each device scans 1/N of the rows.
    mesh_filter_min_rows: int = 1 << 24
    # Parquet codec for INDEX data files.  An index is a derived,
    # query-latency-oriented copy: lz4 decodes ~25% faster than snappy at
    # the same size on typical numeric index columns ("none" is fastest
    # still, +16% size).  Source data is never rewritten.  The default
    # literal lives with the writers (io/parquet.INDEX_COMPRESSION_DEFAULT)
    # so a compression-kwarg-less writer call can never drift from it.
    index_file_compression: str = dataclasses.field(
        default_factory=lambda: _index_compression_default())
    # Same cost model for joins: below this (max-side) row count the
    # sorted-merge join runs in numpy on host.  (Round-3 tunnel
    # measurement, 6M x 1.5M int64 keys: host 7.5 s, device 14.9 s warm —
    # transfer dominates.)  None = calibrate from measured physics.
    device_join_min_rows: Optional[int] = None
    # Same cost model for the BUILD's fused hash+lexsort kernel: below
    # this row count the bit-identical host mirror runs instead (the
    # round-2 bench regression was this kernel's transfer + compile
    # latency over the tunnel dominating an 800k-row build).  The layouts
    # are identical either way — only where the permutation is computed
    # changes.  None = calibrate from measured physics.
    device_build_min_rows: Optional[int] = None
    # With >1 visible device, a bucket-aligned INNER join at or above this
    # total row count dispatches its per-bucket joins over the mesh
    # (parallel/join.copartitioned_join_ragged: buckets range-partitioned
    # over the shard axis, zero-collective by co-partitioning); below it,
    # the host thread pool runs the buckets (the single-chip path).
    mesh_join_min_rows: int = 1 << 24
    # Same cost model for GROUP BY: at or above this row count an eligible
    # aggregation (integer/bool keys, null-free numeric inputs,
    # sum/min/max/mean/count) runs as the device segment-reduction kernel
    # (ops/aggregate.py); below it, host arrow hash aggregation.
    # Aggregation ships EVERY input column to the device (measured ~20 MB
    # -> ~5 s over the remote tunnel vs ~26 ms host arrow at 400k rows),
    # so only resident-data / locally-attached deployments route here
    # organically.  None = calibrate from measured physics.
    device_agg_min_rows: Optional[int] = None
    # HBM-resident index-column cache (execution/device_cache.py): byte
    # budget for post-decode device arrays kept across queries, keyed by
    # file identity.  0 disables.
    device_cache_bytes: int = 1 << 30
    # "auto": populate when the device path runs anyway; "eager": ship
    # eligible scan columns on first use (pay a slow attachment once,
    # serve repeats from HBM); "off": never cache.
    device_cache_policy: str = "auto"
    # Row threshold when inputs are ALREADY resident (latency-only
    # break-even); applies to every op kind.  None = calibrate.
    device_resident_min_rows: Optional[int] = None
    # Distributed build over the device mesh: "auto" uses it when more than
    # one accelerator is visible; "on"/"off" force it.  The shuffle uses
    # capacity-padded all_to_all; slack is the initial headroom factor over
    # the perfectly-balanced per-destination row count (doubled on overflow).
    parallel_build: str = "auto"
    shuffle_capacity_slack: float = 1.5
    # The engine-wide device mesh (parallel/mesh.py): "auto" activates the
    # mesh-sharded kernel paths (sharded build route, bucket-owned join/
    # aggregate dispatch) whenever more than one local device is visible;
    # "on" insists (still a no-op below 2 devices — there is nothing to
    # shard); "off" pins every kernel to the single-device path, which is
    # bit-equal by construction (the mesh changes WHERE work runs, never
    # the layout or the answer).  maxDevices caps how many local devices
    # the mesh spans (0 = all) — useful to leave a device free for
    # serving while builds shard over the rest.
    mesh_enabled: str = "auto"
    mesh_max_devices: int = 0
    # Same cost model as meshJoinMinRows for GROUP BY: at or above this
    # row count an eligible device aggregation shards its rows over the
    # mesh by group-key bucket ownership (parallel/aggregate.py — each
    # group lives wholly on one device, so no partial-merge pass exists);
    # below it, the single-device segment kernel.
    mesh_agg_min_rows: int = 1 << 24
    # Overlapped build pipeline (actions/create.py; docs/13, docs/16):
    #   - pipeline.enabled: the external (spill) build runs as overlapped
    #     stages — async prefetch of source decode, concurrent chunk
    #     routing, and streaming per-bucket-group finalize — instead of
    #     the forced-serial read → route → finalize loop.  Off is the
    #     bit-equal serial reference (layout NEVER depends on this flag;
    #     tests/test_build_pipeline.py proves it) and the sane setting
    #     for debugging or strictly single-threaded environments.
    #   - prefetchDepth: decoded-but-unconsumed source chunks the
    #     prefetcher may hold (its ONE reader thread decodes file N+1
    #     while file N routes; the bound is the backpressure that keeps
    #     peak RSS at ~depth device batches, not the dataset).
    #   - finalizeWorkers: worker threads merging + parquet-encoding
    #     closed bucket groups, concurrent with routing of remaining
    #     input.  Each in-flight group pins one bucket's rows in memory.
    build_pipeline_enabled: bool = True
    build_prefetch_depth: int = 2
    build_finalize_workers: int = 4
    # Fault-tolerant multi-host build (parallel/multihost_build.py;
    # docs/21):
    #   - multihost.hosts >= 2 runs createIndex as N subprocess hosts
    #     cooperating through crash-recoverable work claims over the
    #     LogStore CAS seam — each host routes claimed chunks, then
    #     finalizes claimed bucket GROUPS into its own staging dir; the
    #     coordinating action CAS-commits the union or nothing.  1 runs
    #     one subprocess host through the same claim pipeline (the bench
    #     baseline for the scaling ratio; also handy for debugging the
    #     protocol without host interleaving).  0 = the ordinary
    #     single-process build (zero multihost code runs).
    #   - claimTtlS: a work claim expires this long after its last
    #     renew; a SIGKILLed host's claims are reclaimed by survivors
    #     after at most one TTL (epoch fencing keeps the zombie out).
    #   - pollS: claim-table poll interval for hosts waiting on the
    #     route phase to drain and for the coordinator.
    #   - deadlineS: coordinator wall-clock budget; if claims stop
    #     progressing (every host dead) the build fails loudly instead
    #     of hanging.
    multihost_build_hosts: int = 0
    multihost_build_claim_ttl_s: float = 10.0
    multihost_build_poll_s: float = 0.05
    multihost_build_deadline_s: float = 600.0
    # Comma-separated glob pattern(s); when set, createIndex records the
    # pattern as the indexed root paths so later-appearing directories that
    # match are picked up by refresh (IndexConstants.scala:108-114).
    globbing_pattern: str = ""
    # Explain output rendering (IndexConstants.scala:69-80): "plaintext",
    # "html", or "console"; custom highlight tags override the mode default.
    display_mode: str = "plaintext"
    highlight_begin_tag: str = ""
    highlight_end_tag: str = ""
    # When the latest log entry of an index is a TRANSIENT state (a prior
    # action died mid-flight), lifecycle calls through the collection
    # manager first roll it back to the last stable state — an implicit
    # cancel() (actions/CancelAction.scala:25-58).  Off by default: the
    # reference's contract is explicit user recovery, and an in-flight
    # concurrent action looks identical to a crashed one (the rollback is
    # still SAFE either way — the optimistic log write arbitrates — but
    # it would make the racer that started LATER win).
    auto_recovery_enabled: bool = False
    # Integrity subsystem (io/integrity.py, actions/verify.py,
    # index/quarantine.py; docs/15-integrity.md):
    #   - digestOnWrite: hash every index data file as it lands and record
    #     the content digest in its FileInfo (xxh64; ~memory-speed, paid
    #     once per file at build time).  Off = files commit digest-less
    #     and full scrub reports them status="unknown".
    #   - quarantineOnReadFailure: when an index scan dies at execution,
    #     probe that index's files, QUARANTINE the unreadable/mismatched
    #     ones and re-plan with only the damaged buckets read from source
    #     — before PR 2's whole-index fallback (which stays the last
    #     resort).
    #   - autoRepair: after such a containment re-plan answers the query,
    #     rebuild the quarantined buckets in the background of the call
    #     (refresh mode="repair") so the NEXT query runs clean.  Off by
    #     default: repair re-reads source data, which is an operator
    #     decision on metered storage.
    integrity_digest_on_write: bool = True
    integrity_quarantine_on_failure: bool = True
    auto_repair_enabled: bool = False
    # Transient-IO retry for the op-log's file primitives (EIO/ENOSPC/
    # EAGAIN/EINTR): total attempts and exponential-backoff bounds, with
    # uniform jitter so racing writers don't re-collide in lockstep.
    io_retry_max_attempts: int = 3
    io_retry_initial_backoff_ms: float = 10.0
    io_retry_max_backoff_ms: float = 1000.0
    # Observability (telemetry/trace.py; docs/16-observability.md):
    # tracing.enabled turns on per-query span trees (disabled cost: one
    # module-global bool check per instrumented site); trace.sink is a
    # JSONL file path every finished root span is appended to — the
    # machine-readable artifact bench.py and production runs leave.
    # Run reports and the metrics registry are always on (their cost is
    # a contextvar read / a dict increment at file/action granularity).
    telemetry_tracing_enabled: bool = False
    telemetry_trace_sink: str = ""
    # Size bound for the JSONL trace sink: past it the sink file rotates
    # to <path>.1 (replacing the previous rotation), so a long-lived
    # traced server keeps at most ~2x this on disk.  0 = unbounded.
    telemetry_trace_max_bytes: int = 256 << 20
    # Pipeline timeline profiler (telemetry/timeline.py;
    # docs/16-observability.md): interval-level recording — every
    # BuildReport phase (incl. spill worker threads), executor operator,
    # and block_until_ready-timed device kernel lands as a
    # (lane, kind, start, end) interval in a bounded process ring, plus
    # a background memory sampler during profiled actions.  Off by
    # default; the disabled cost is one module-global bool check (the
    # device-kernel seams never force a sync while off).  maxIntervals
    # bounds the ring (oldest dropped, counted in timeline.dropped);
    # memorySampleMs is the sampler cadence (0 disables the sampler).
    timeline_enabled: bool = False
    # Strict-mode runtime sync guard (execution/sync_guard.py): armed per
    # collect; a device→host conversion outside the attributed
    # sync_guard.pull/scalar seams raises DeviceSyncError and counts
    # guard.sync.violations.  Off (the default) leaves jax untouched.
    device_guard_enabled: bool = False
    timeline_max_intervals: int = 8192
    timeline_memory_sample_ms: float = 25.0
    # Hyperspace.doctor() thresholds (telemetry/doctor.py): the serving
    # check warns past shed/requests >= shedWarnRatio (crit at 5x) and
    # grades latency-SLO burn as the fraction of serve.latency_ms
    # observations above latencySloMs.
    doctor_latency_slo_ms: float = 1000.0
    doctor_shed_warn_ratio: float = 0.05
    # doctor() device-skew grading (single-process ``device_skew`` check
    # and the fleet-level ``fleet.skew`` check): warn when the
    # max/median ratio over attributed per-device (or per-process)
    # kernel milliseconds reaches this; 0 disables the grading.
    doctor_device_skew_warn: float = 4.0
    # Fleet telemetry federation (telemetry/fleet.py;
    # docs/16-observability.md):
    #   - enabled: each process publishes a bounded heartbeat snapshot
    #     (identity/role, typed metrics, health grade, per-device
    #     kernel ms, interesting flight-recorder tail) under
    #     <systemPath>/_hyperspace_fleet through the LogStore seam —
    #     the substrate of fleet_status()/fleet_metrics()/
    #     doctor(fleet=True).  Off by default: publishing writes small
    #     files on a cadence, an operator decision on metered storage.
    #   - publishIntervalS: heartbeat cadence.
    #   - staleAfterS: age past which a heartbeat counts as a
    #     dead/hung process (fleet doctor crit); 0 derives 2x the
    #     publish interval.
    #   - pruneAfterS: age past which publishers garbage-collect a
    #     dead process's heartbeat entirely.
    fleet_telemetry_enabled: bool = False
    fleet_publish_interval_s: float = 5.0
    fleet_stale_after_s: float = 0.0
    fleet_prune_after_s: float = 600.0
    # Build-pipeline profiler (telemetry/build_report.py): every action
    # run records per-phase wall time, bytes moved, spill counts, and
    # memory gauges into a BuildReport (Hyperspace.last_build_report()),
    # exported through the metrics registry (build.phase.*.seconds,
    # build.spill.bytes, ...).  Disabling keeps the pre-existing
    # build_stats_log phase seconds but skips the memory sampling,
    # metric/span export, and the ledger append — the bench
    # ``build_profile`` section gates the on-vs-off delta < 3%.
    build_profiling_enabled: bool = True
    # Persistent perf ledger (telemetry/perf_ledger.py): every completed
    # action (and bench section) appends a compact structured record —
    # phases, bytes, outcome, host/jax/conf fingerprint — through the
    # LogStore seam under <systemPath>/_hyperspace_perf, readable via
    # Hyperspace.perf_history() and the interop ``perf_history`` verb.
    # Appends are fault-quiet and never fail the action; the ledger is
    # bounded (oldest records pruned past maxEntries).
    perf_ledger_enabled: bool = True
    perf_ledger_max_entries: int = 2048
    # Index advisor (hyperspace_tpu/advisor/; docs/17-advisor.md):
    #   - capture.enabled: persist a bounded, deduplicated log of query
    #     FINGERPRINTS (filter/join/group columns + measured bytes
    #     scanned, never data values) under
    #     ``<systemPath>/_hyperspace_workload`` through the LogStore seam.
    #     Off by default: capture writes small files per *distinct* query
    #     shape (repeats fold into a hit counter, flushed at
    #     power-of-two hit counts so the steady-state cost is one dict
    #     update) — bench gates the overhead < 3% on the filter workload.
    #   - capture.maxEntries: cap on distinct fingerprints; new shapes
    #     beyond it are dropped (counted in advisor.capture.dropped).
    #   - maxCandidates: how many candidate indexes
    #     ``recommend_indexes`` enumerates/scores from the workload.
    advisor_capture_enabled: bool = False
    advisor_capture_max_entries: int = 512
    advisor_max_candidates: int = 20
    # Serving layer (interop/server.py; docs/07-interop.md):
    #   - workers: executor threads per QueryServer — the hard bound on
    #     concurrent query EXECUTION (socket IO threads are separate and
    #     bounded by maxConnections).
    #   - queueDepth: admitted-but-not-yet-running requests; a full queue
    #     sheds new requests with a retryable ERR BUSY.
    #   - maxConnections: concurrent client connections; beyond it the
    #     ACCEPT loop answers ERR BUSY without spawning a handler thread,
    #     so a connection storm cannot grow the thread count.
    #   - defaultDeadlineMs: per-request deadline when the request spec
    #     carries no deadline_ms of its own (0 = none).  The deadline
    #     propagates into dataset.collect via utils/deadline.py and
    #     aborts cleanly at executor phase boundaries (ERR DEADLINE).
    #   - requestTimeoutS / sendTimeoutS: socket read / WRITE timeouts —
    #     a dead client that stops reading mid-Arrow-stream frees its
    #     worker after sendTimeoutS instead of pinning it forever.
    #   - drainGraceS: on drain (SIGTERM), how long in-flight requests
    #     get to finish before the server closes anyway.
    #   - shed.rssWatermarkMb / shed.queueWaitWatermarkMs: overload
    #     watermarks (0 = off) — past either, new requests shed BUSY.
    #   - planCache.*: the optimize-result cache keyed by the advisor's
    #     structural plan fingerprint (execution/plan_cache.py), byte-
    #     budget LRU shared mechanism with the device column cache.
    #   - ioMode: "threaded" (default — one handler thread per
    #     connection) or "async" (one selector thread watches every
    #     socket; workers still execute queries).  Bit-equal wire
    #     behavior either way; async keeps the thread count flat under
    #     thousands of mostly-idle connections.
    #   - tenant.maxQueued: per-tenant cap on queued-or-running requests
    #     (0 = off).  A hot tenant past its cap sheds retryable BUSY
    #     (serve.shed.tenant) without consuming global queue depth, so
    #     it degrades itself, not the fleet.
    serving_workers: int = 4
    serving_queue_depth: int = 16
    serving_max_connections: int = 64
    serving_default_deadline_ms: float = 0.0
    serving_request_timeout_s: float = 30.0
    serving_send_timeout_s: float = 30.0
    serving_drain_grace_s: float = 10.0
    serving_shed_rss_watermark_mb: float = 0.0
    serving_shed_queue_wait_watermark_ms: float = 0.0
    serving_plan_cache_enabled: bool = True
    serving_plan_cache_bytes: int = 64 << 20
    serving_io_mode: str = "threaded"
    serving_tenant_max_queued: int = 0
    # Request flight recorder (telemetry/flight_recorder.py;
    # docs/16-observability.md): a bounded ring of completed request
    # records with tail-based retention — slow (>= slowMs), error,
    # deadline-expired, and shed requests always kept, healthy ones
    # sampled 1-in-healthySampleN (0 = none).  Read by
    # Hyperspace.slow_queries()/diagnostics() and the slow_queries /
    # trace interop verbs; drain()/dump_diagnostics() persist the ring
    # (+ metrics snapshot + perf-ledger tail) as a diagnostics bundle
    # through the LogStore seam, bounded by maxBundles.
    flight_recorder_enabled: bool = True
    flight_recorder_max_records: int = 256
    flight_recorder_slow_ms: float = 1000.0
    flight_recorder_healthy_sample_n: int = 16
    flight_recorder_max_bundles: int = 8
    # Autonomous index lifecycle (hyperspace_tpu/lifecycle/;
    # docs/19-lifecycle.md):
    #   - enabled: the opt-in maintenance daemon thread — detect source
    #     change, pick the cheapest refresh mode, close the advisor loop
    #     under the byte budget, journal every decision.  Off by
    #     default: autonomous builds re-read source data, an operator
    #     decision on metered storage.  ``maintenance_cycle()`` drives
    #     one step at a time regardless of this flag.
    #   - intervalS: seconds between daemon cycles.
    #   - byteBudget: total on-disk index bytes the advisor pass may
    #     grow the fleet to; 0 disables autonomous create/delete
    #     entirely (refresh/repair decisions are unaffected).
    #   - quickAppendRatio: appended-bytes fraction (new + pending
    #     hybrid-scan debt, over recorded source bytes) below which an
    #     append-only change takes the metadata-only quick refresh
    #     (hybrid scan must be on); above it, incremental.
    #   - fullChurnRatio: changed-file fraction of the recorded set at
    #     or past which a full rebuild beats an incremental pass.
    #   - journal.maxEntries: decision-journal bound under
    #     ``<systemPath>/_hyperspace_lifecycle`` (oldest pruned).
    #   - backoff.initialS/.maxS: per-index exponential backoff after a
    #     failed maintenance action (doubles per consecutive failure).
    #   - lease.enabled/.ttlS: cross-process maintenance lease
    #     (lifecycle/lease.py) through the LogStore CAS seam — exactly
    #     one daemon per index tree executes maintenance; losers
    #     idle-poll, a dead holder's lease expires after ttlS and is
    #     taken over with an epoch bump that fences the zombie.
    lifecycle_enabled: bool = False
    lifecycle_interval_s: float = 30.0
    lifecycle_byte_budget: int = 0
    lifecycle_quick_append_ratio: float = 0.1
    lifecycle_full_churn_ratio: float = 0.5
    lifecycle_journal_max_entries: int = 1024
    lifecycle_backoff_initial_s: float = 1.0
    lifecycle_backoff_max_s: float = 300.0
    lifecycle_lease_enabled: bool = False
    lifecycle_lease_ttl_s: float = 30.0
    # Row-level CDC ingest (lifecycle/cdc.py, docs/19-lifecycle.md):
    #   - cdc.enabled: merge-on-read — deletes/mutations with lineage take
    #     the metadata-only quick refresh (the hybrid rule applies the
    #     delete overlay at scan time, bit-equal to a rebuild) while the
    #     accumulated merge debt stays under cdc.mergeDebtRatio of the
    #     recorded source bytes; past it, the real incremental refresh.
    #   - compaction.enabled/.minSmallFiles/.mode: optimizeIndex joins
    #     the policy ladder — when an otherwise-idle index carries at
    #     least minSmallFiles mergeable small files (below
    #     hyperspace.index.optimizeFileSizeThreshold, sharing a bucket),
    #     the daemon schedules an optimize in ``mode`` and journals it
    #     like every other decision.
    lifecycle_cdc_enabled: bool = False
    lifecycle_cdc_merge_debt_ratio: float = 0.2
    lifecycle_compaction_enabled: bool = False
    lifecycle_compaction_min_small_files: int = 8
    lifecycle_compaction_mode: str = "quick"
    # Push-based source change detection (io/watch.py): the maintenance
    # daemon wakes on source events instead of sleeping the full
    # lifecycle interval, so measured staleness is bounded by event
    # latency.  mode: "auto" picks inotify on Linux, else the store
    # notification bus, else stat polling; "inotify"/"store"/"poll"
    # force a backend.  pollIntervalS paces the poll/store watchers;
    # debounceMs coalesces event bursts into one wake.
    watch_enabled: bool = False
    watch_mode: str = "auto"
    watch_poll_interval_s: float = 0.5
    watch_debounce_ms: float = 50.0
    # Deterministic fault injection (io/faults.py): fire ``kind`` at the
    # ``at``-th call of ``site``, ``count`` times.  Test-only machinery;
    # disabled costs one None check per file-level IO op.
    fault_injection_enabled: bool = False
    fault_injection_site: str = ""
    fault_injection_kind: str = ""
    fault_injection_at: int = 1
    fault_injection_count: int = 1
    # Wire-fault shaping (io/faults.py net kinds): added delay for
    # ``slow``, hang duration for ``black-hole``.
    fault_injection_latency_ms: float = 25.0
    fault_injection_hang_s: float = 0.25
    # Front-door resilience features (interop/server.FleetQueryClient).
    # Both default OFF: the plain request path stays byte-for-byte the
    # PR 16 behavior with zero added work beyond a bool check.
    #   - hedge.enabled/.delayMs: fire a second attempt on a different
    #     survivor when the first is slower than the hedge delay
    #     (delayMs 0 = derive from the client's latency EWMA); first
    #     response wins, the loser is discarded by request_id.
    #   - breaker.enabled/.failures/.cooldownMs: per-endpoint circuit
    #     breaker — ``failures`` consecutive errors open it (routing
    #     avoids it), after ``cooldownMs`` one half-open probe may
    #     close it again.
    client_hedge_enabled: bool = False
    client_hedge_delay_ms: float = 0.0
    client_breaker_enabled: bool = False
    client_breaker_failures: int = 5
    client_breaker_cooldown_ms: float = 2000.0
    # The SLO alert engine (telemetry/alerts.py + telemetry/slo.py).
    # Default OFF; when on, an evaluator thread samples the metrics
    # registry every intervalS (0 = ride the fleet-heartbeat cadence)
    # and evaluates multi-window multi-burn-rate rules: the fast pair
    # (fastShortS+fastLongS at fastFactor budgets/window) pages, the
    # slow pair warns.  availabilityTarget/latencyTarget set the error
    # budgets (latency splits serve.latency_ms at
    # hyperspace.doctor.latencySloMs); stalenessWarnS thresholds the
    # staleness objective; pendingEvals/resolveEvals flap-damp the
    # pending -> firing -> resolved machine; maxEntries bounds the
    # persisted transition log; notify.command runs off-thread on
    # firing/resolved with the record as JSON on stdin.
    alerts_enabled: bool = False
    alerts_interval_s: float = 0.0
    alerts_availability_target: float = 0.999
    alerts_latency_target: float = 0.99
    alerts_fast_short_s: float = 300.0
    alerts_fast_long_s: float = 3600.0
    alerts_fast_factor: float = 14.4
    alerts_slow_short_s: float = 21600.0
    alerts_slow_long_s: float = 259200.0
    alerts_slow_factor: float = 1.0
    alerts_pending_evals: int = 2
    alerts_resolve_evals: int = 2
    alerts_staleness_warn_s: float = 600.0
    alerts_max_entries: int = 512
    alerts_notify_command: str = ""
    # Keys explicitly applied through set(); drives canonical-vs-legacy key
    # precedence.
    _set_keys: set = dataclasses.field(default_factory=set, repr=False,
                                       compare=False)

    _FIELD_BY_KEY = {
        SYSTEM_PATH: "system_path",
        NUM_BUCKETS: "num_buckets",
        NUM_BUCKETS_LEGACY: "num_buckets",
        GLOBBING_PATTERN: "globbing_pattern",
        LINEAGE_ENABLED: "lineage_enabled",
        HYBRID_SCAN_ENABLED: "hybrid_scan_enabled",
        HYBRID_SCAN_APPENDED_RATIO: "hybrid_scan_max_appended_ratio",
        HYBRID_SCAN_DELETED_RATIO: "hybrid_scan_max_deleted_ratio",
        OPTIMIZE_FILE_SIZE_THRESHOLD: "optimize_file_size_threshold",
        INDEX_MAX_ROWS_PER_FILE: "index_max_rows_per_file",
        FILTER_RULE_USE_BUCKET_SPEC: "filter_rule_use_bucket_spec",
        CACHE_EXPIRY_SECONDS: "cache_expiry_seconds",
        SOURCE_PROVIDERS: "source_providers",
        SIGNATURE_PROVIDER: "signature_provider",
        LOG_MANAGER_CLASS: "log_manager_class",
        LOG_STORE_CLASS: "log_store_class",
        CONCURRENCY_MAX_RETRIES: "concurrency_max_retries",
        DEGRADED_FALLBACK_TO_SOURCE: "degraded_fallback_to_source",
        OBJECT_STORE_STALE_LIST_MS: "object_store_stale_list_ms",
        EVENT_LOGGER: "event_logger",
        SUPPORTED_FILE_FORMATS: "supported_file_formats",
        DEVICE_BATCH_ROWS: "device_batch_rows",
        DEVICE_FILTER_MIN_ROWS: "device_filter_min_rows",
        MESH_FILTER_MIN_ROWS: "mesh_filter_min_rows",
        INDEX_FILE_COMPRESSION: "index_file_compression",
        DEVICE_JOIN_MIN_ROWS: "device_join_min_rows",
        DEVICE_BUILD_MIN_ROWS: "device_build_min_rows",
        MESH_JOIN_MIN_ROWS: "mesh_join_min_rows",
        DEVICE_AGG_MIN_ROWS: "device_agg_min_rows",
        DEVICE_RESIDENT_MIN_ROWS: "device_resident_min_rows",
        DEVICE_CACHE_BYTES: "device_cache_bytes",
        DEVICE_CACHE_POLICY: "device_cache_policy",
        PARALLEL_BUILD: "parallel_build",
        SHUFFLE_CAPACITY_SLACK: "shuffle_capacity_slack",
        MESH_ENABLED: "mesh_enabled",
        MESH_MAX_DEVICES: "mesh_max_devices",
        MESH_AGG_MIN_ROWS: "mesh_agg_min_rows",
        BUILD_PIPELINE_ENABLED: "build_pipeline_enabled",
        BUILD_PREFETCH_DEPTH: "build_prefetch_depth",
        BUILD_FINALIZE_WORKERS: "build_finalize_workers",
        MULTIHOST_BUILD_HOSTS: "multihost_build_hosts",
        MULTIHOST_BUILD_CLAIM_TTL_S: "multihost_build_claim_ttl_s",
        MULTIHOST_BUILD_POLL_S: "multihost_build_poll_s",
        MULTIHOST_BUILD_DEADLINE_S: "multihost_build_deadline_s",
        DISPLAY_MODE: "display_mode",
        HIGHLIGHT_BEGIN_TAG: "highlight_begin_tag",
        HIGHLIGHT_END_TAG: "highlight_end_tag",
        AUTO_RECOVERY_ENABLED: "auto_recovery_enabled",
        AUTO_REPAIR_ENABLED: "auto_repair_enabled",
        INTEGRITY_DIGEST_ON_WRITE: "integrity_digest_on_write",
        INTEGRITY_QUARANTINE_ON_FAILURE: "integrity_quarantine_on_failure",
        IO_RETRY_MAX_ATTEMPTS: "io_retry_max_attempts",
        IO_RETRY_INITIAL_BACKOFF_MS: "io_retry_initial_backoff_ms",
        IO_RETRY_MAX_BACKOFF_MS: "io_retry_max_backoff_ms",
        TELEMETRY_TRACING_ENABLED: "telemetry_tracing_enabled",
        TELEMETRY_TRACE_SINK: "telemetry_trace_sink",
        TELEMETRY_TRACE_MAX_BYTES: "telemetry_trace_max_bytes",
        TIMELINE_ENABLED: "timeline_enabled",
        DEVICE_GUARD_ENABLED: "device_guard_enabled",
        TIMELINE_MAX_INTERVALS: "timeline_max_intervals",
        TIMELINE_MEMORY_SAMPLE_MS: "timeline_memory_sample_ms",
        DOCTOR_LATENCY_SLO_MS: "doctor_latency_slo_ms",
        DOCTOR_SHED_WARN_RATIO: "doctor_shed_warn_ratio",
        DOCTOR_DEVICE_SKEW_WARN: "doctor_device_skew_warn",
        FLEET_TELEMETRY_ENABLED: "fleet_telemetry_enabled",
        FLEET_PUBLISH_INTERVAL_S: "fleet_publish_interval_s",
        FLEET_STALE_AFTER_S: "fleet_stale_after_s",
        FLEET_PRUNE_AFTER_S: "fleet_prune_after_s",
        BUILD_PROFILING_ENABLED: "build_profiling_enabled",
        PERF_LEDGER_ENABLED: "perf_ledger_enabled",
        PERF_LEDGER_MAX_ENTRIES: "perf_ledger_max_entries",
        ADVISOR_CAPTURE_ENABLED: "advisor_capture_enabled",
        ADVISOR_CAPTURE_MAX_ENTRIES: "advisor_capture_max_entries",
        ADVISOR_MAX_CANDIDATES: "advisor_max_candidates",
        SERVING_WORKERS: "serving_workers",
        SERVING_QUEUE_DEPTH: "serving_queue_depth",
        SERVING_MAX_CONNECTIONS: "serving_max_connections",
        SERVING_DEFAULT_DEADLINE_MS: "serving_default_deadline_ms",
        SERVING_REQUEST_TIMEOUT_S: "serving_request_timeout_s",
        SERVING_SEND_TIMEOUT_S: "serving_send_timeout_s",
        SERVING_DRAIN_GRACE_S: "serving_drain_grace_s",
        SERVING_SHED_RSS_MB: "serving_shed_rss_watermark_mb",
        SERVING_SHED_QUEUE_WAIT_MS: "serving_shed_queue_wait_watermark_ms",
        SERVING_PLAN_CACHE_ENABLED: "serving_plan_cache_enabled",
        SERVING_PLAN_CACHE_BYTES: "serving_plan_cache_bytes",
        SERVING_IO_MODE: "serving_io_mode",
        SERVING_TENANT_MAX_QUEUED: "serving_tenant_max_queued",
        FLIGHT_RECORDER_ENABLED: "flight_recorder_enabled",
        FLIGHT_RECORDER_MAX_RECORDS: "flight_recorder_max_records",
        FLIGHT_RECORDER_SLOW_MS: "flight_recorder_slow_ms",
        FLIGHT_RECORDER_HEALTHY_SAMPLE_N: "flight_recorder_healthy_sample_n",
        FLIGHT_RECORDER_MAX_BUNDLES: "flight_recorder_max_bundles",
        LIFECYCLE_ENABLED: "lifecycle_enabled",
        LIFECYCLE_INTERVAL_S: "lifecycle_interval_s",
        LIFECYCLE_BYTE_BUDGET: "lifecycle_byte_budget",
        LIFECYCLE_QUICK_APPEND_RATIO: "lifecycle_quick_append_ratio",
        LIFECYCLE_FULL_CHURN_RATIO: "lifecycle_full_churn_ratio",
        LIFECYCLE_JOURNAL_MAX_ENTRIES: "lifecycle_journal_max_entries",
        LIFECYCLE_BACKOFF_INITIAL_S: "lifecycle_backoff_initial_s",
        LIFECYCLE_BACKOFF_MAX_S: "lifecycle_backoff_max_s",
        LIFECYCLE_LEASE_ENABLED: "lifecycle_lease_enabled",
        LIFECYCLE_LEASE_TTL_S: "lifecycle_lease_ttl_s",
        LIFECYCLE_CDC_ENABLED: "lifecycle_cdc_enabled",
        LIFECYCLE_CDC_MERGE_DEBT_RATIO: "lifecycle_cdc_merge_debt_ratio",
        LIFECYCLE_COMPACTION_ENABLED: "lifecycle_compaction_enabled",
        LIFECYCLE_COMPACTION_MIN_SMALL_FILES:
            "lifecycle_compaction_min_small_files",
        LIFECYCLE_COMPACTION_MODE: "lifecycle_compaction_mode",
        WATCH_ENABLED: "watch_enabled",
        WATCH_MODE: "watch_mode",
        WATCH_POLL_INTERVAL_S: "watch_poll_interval_s",
        WATCH_DEBOUNCE_MS: "watch_debounce_ms",
        FAULT_INJECTION_ENABLED: "fault_injection_enabled",
        FAULT_INJECTION_SITE: "fault_injection_site",
        FAULT_INJECTION_KIND: "fault_injection_kind",
        FAULT_INJECTION_AT: "fault_injection_at",
        FAULT_INJECTION_COUNT: "fault_injection_count",
        FAULT_INJECTION_LATENCY_MS: "fault_injection_latency_ms",
        FAULT_INJECTION_HANG_S: "fault_injection_hang_s",
        CLIENT_HEDGE_ENABLED: "client_hedge_enabled",
        CLIENT_HEDGE_DELAY_MS: "client_hedge_delay_ms",
        CLIENT_BREAKER_ENABLED: "client_breaker_enabled",
        CLIENT_BREAKER_FAILURES: "client_breaker_failures",
        CLIENT_BREAKER_COOLDOWN_MS: "client_breaker_cooldown_ms",
        ALERTS_ENABLED: "alerts_enabled",
        ALERTS_INTERVAL_S: "alerts_interval_s",
        ALERTS_AVAILABILITY_TARGET: "alerts_availability_target",
        ALERTS_LATENCY_TARGET: "alerts_latency_target",
        ALERTS_FAST_SHORT_S: "alerts_fast_short_s",
        ALERTS_FAST_LONG_S: "alerts_fast_long_s",
        ALERTS_FAST_FACTOR: "alerts_fast_factor",
        ALERTS_SLOW_SHORT_S: "alerts_slow_short_s",
        ALERTS_SLOW_LONG_S: "alerts_slow_long_s",
        ALERTS_SLOW_FACTOR: "alerts_slow_factor",
        ALERTS_PENDING_EVALS: "alerts_pending_evals",
        ALERTS_RESOLVE_EVALS: "alerts_resolve_evals",
        ALERTS_STALENESS_WARN_S: "alerts_staleness_warn_s",
        ALERTS_MAX_ENTRIES: "alerts_max_entries",
        ALERTS_NOTIFY_COMMAND: "alerts_notify_command",
    }

    # Auto-calibrated routing thresholds: None = derive from measured
    # attachment physics (utils/calibrate.py).
    _AUTO_INT_FIELDS = ("device_filter_min_rows", "device_join_min_rows",
                        "device_agg_min_rows", "device_build_min_rows",
                        "device_resident_min_rows")

    def device_min_rows(self, kind: str) -> int:
        """Effective host-vs-device threshold for ``kind`` (one of
        filter/join/agg/join_agg/build): an explicitly set conf value
        wins; otherwise the calibrated (or conservative-fallback) value.
        The fused join+aggregate has no conf field of its own — an
        explicit join threshold governs it (it IS the join's device
        decision, with the aggregation fused behind it)."""
        field = "join" if kind == "join_agg" else kind
        explicit = getattr(self, f"device_{field}_min_rows")
        if explicit is not None:
            return int(explicit)
        from hyperspace_tpu.utils.calibrate import calibrated_min_rows

        return calibrated_min_rows(kind)

    def resident_min_rows(self, kind: str) -> int:
        """Threshold when the op's inputs are already device-resident
        (only round-trip latency must be repaid)."""
        if self.device_resident_min_rows is not None:
            return int(self.device_resident_min_rows)
        from hyperspace_tpu.utils.calibrate import (
            calibrated_resident_min_rows,
        )

        return calibrated_resident_min_rows(kind)

    def set(self, key: str, value: Any) -> None:
        field = self._FIELD_BY_KEY.get(key)
        if field is None:
            raise KeyError(f"Unknown hyperspace conf key: {key}")
        # Canonical-key precedence (HyperspaceConf.scala:109-117): a value
        # set via the canonical numBuckets key is never overwritten by the
        # legacy key, regardless of apply order.
        if key == NUM_BUCKETS_LEGACY and NUM_BUCKETS in self._set_keys:
            return
        self._set_keys.add(key)
        current = getattr(self, field)
        if field in self._AUTO_INT_FIELDS:
            value = None if value is None or str(value).lower() in (
                "none", "auto") else int(value)
        elif isinstance(current, bool):
            value = value if isinstance(value, bool) else str(value).lower() == "true"
        elif isinstance(current, int):
            value = int(value)
        elif isinstance(current, float):
            value = float(value)
        # Bypass __setattr__: a LEGACY set must not mark the CANONICAL key
        # as explicitly set (later legacy writes still apply).
        object.__setattr__(self, field, value)

    def get(self, key: str) -> Any:
        field = self._FIELD_BY_KEY.get(key)
        if field is None:
            raise KeyError(f"Unknown hyperspace conf key: {key}")
        return getattr(self, field)

    def __setattr__(self, name: str, value: Any) -> None:
        # Direct attribute assignment of num_buckets counts as setting the
        # canonical key for legacy-key precedence.  During __init__ the
        # tracking set doesn't exist yet — defaults are not "explicitly set".
        object.__setattr__(self, name, value)
        if name == "num_buckets":
            tracked = getattr(self, "_set_keys", None)
            if tracked is not None:
                tracked.add(NUM_BUCKETS)

    def copy(self) -> "HyperspaceConf":
        c = dataclasses.replace(self)
        # replace() aliases mutable fields; precedence state must not leak
        # between the copy and the original.
        object.__setattr__(c, "_set_keys", set(self._set_keys))
        return c
