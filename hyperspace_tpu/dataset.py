"""User-facing Dataset: a logical plan + session, with DataFrame-style verbs.

The DataFrame analog the reference operates on.  ``collect()`` runs the
optimizer (rules apply only when hyperspace is enabled on the session,
package.scala:47-79) and then the executor.
"""

from __future__ import annotations

from typing import List, Sequence

import pyarrow as pa

from hyperspace_tpu.plan.expr import Expr
from hyperspace_tpu.plan.nodes import Filter, Join, LogicalPlan, Project


class Dataset:
    def __init__(self, plan: LogicalPlan, session) -> None:
        self.plan = plan
        self.session = session

    # -- verbs --------------------------------------------------------------
    def filter(self, condition: Expr) -> "Dataset":
        return Dataset(Filter(condition, self.plan), self.session)

    def select(self, *columns: str) -> "Dataset":
        return Dataset(Project(list(columns), self.plan), self.session)

    def join(self, other: "Dataset", condition: Expr, how: str = "inner") -> "Dataset":
        return Dataset(Join(self.plan, other.plan, condition, how), self.session)

    # -- execution ----------------------------------------------------------
    def optimized_plan(self) -> LogicalPlan:
        return self.session.optimize(self.plan)

    def collect(self) -> pa.Table:
        from hyperspace_tpu.execution.executor import Executor

        return Executor(self.session).execute(self.optimized_plan())

    def to_pandas(self):
        return self.collect().to_pandas()

    def count(self) -> int:
        return self.collect().num_rows

    @property
    def columns(self) -> List[str]:
        return self.plan.output_columns(self.session.schema_of)

    def explain_string(self) -> str:
        return self.plan.tree_string()
