"""User-facing Dataset: a logical plan + session, with DataFrame-style verbs.

The DataFrame analog the reference operates on.  ``collect()`` runs the
optimizer (rules apply only when hyperspace is enabled on the session,
package.scala:47-79) and then the executor.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np
import pyarrow as pa

from hyperspace_tpu.plan.expr import Col, Expr, Lit
from hyperspace_tpu.plan.nodes import (
    Aggregate,
    Compute,
    Distinct,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Project,
    Scan,
    SetOp,
    Sort,
    Union,
    Window,
    WithColumns,
)


def _index_scans_of(plan: LogicalPlan) -> List[str]:
    """Names of indexes the optimized plan reads (Scan relations carrying
    the index marker) — the guard for degraded re-execution: only a plan
    that actually touches index data qualifies for the source fallback."""
    out: List[str] = []
    if isinstance(plan, Scan) and plan.relation.index_scan_of is not None:
        out.append(plan.relation.index_scan_of)
    for child in plan.children:
        out.extend(_index_scans_of(child))
    return sorted(set(out))


def _index_scan_files(plan: LogicalPlan) -> List:
    """(index name, file paths) per index scan in the plan."""
    out: List = []
    if isinstance(plan, Scan) and plan.relation.index_scan_of is not None:
        out.append((plan.relation.index_scan_of,
                    list(plan.relation.file_paths or ())))
    for child in plan.children:
        out.extend(_index_scan_files(child))
    return out


def _quarantine_damaged_index_files(session, plan: LogicalPlan) -> List[str]:
    """Containment probe after an execution failure on a plan that reads
    index data: stat + parquet-footer-check every index file the plan
    touches, then (for files that pass) re-hash against the digest the
    entry records.  Unreadable/mismatched files are QUARANTINED
    (index/quarantine.py) so the re-plan serves their buckets from
    source.  Returns the newly quarantined paths — empty means the
    failure was not attributable to index data and the caller falls
    through to the whole-plan source fallback."""
    import os

    import pyarrow.parquet as pq

    from hyperspace_tpu.io import integrity

    mgr = session.index_collection_manager
    newly: List[str] = []
    for name, paths in _index_scan_files(plan):
        quarantine = mgr.quarantine_manager(name)
        entry = mgr.get_index(name)
        digest_of = {} if entry is None else \
            {f.name: f.digest for f in entry.content.file_infos()}
        for path in paths:
            reason = None
            try:
                os.stat(path)
            except OSError as err:
                reason = f"stat failed: {err}"
            else:
                try:
                    pq.read_metadata(path)
                except Exception as err:  # noqa: BLE001 — any footer
                    # parse failure means the file cannot serve reads
                    reason = f"unreadable: {err}"
                else:
                    digest = digest_of.get(path)
                    if digest is not None and \
                            integrity.verify_file(path, digest) is False:
                        reason = f"content digest mismatch ({digest})"
            if reason is not None and \
                    quarantine.add(path, f"execution-failure probe: {reason}"):
                newly.append(path)
    return newly


class GroupedDataset:
    """``df.group_by(...)`` intermediate; ``agg`` specs are pandas-style
    keyword pairs: ``agg(total=("l_quantity", "sum"))``."""

    def __init__(self, dataset: "Dataset", group_by: Sequence[str]) -> None:
        self._dataset = dataset
        self._group_by = list(group_by)

    def agg(self, **named_specs) -> "Dataset":
        """Specs are ``out=(input, func)`` where ``input`` is a column name
        or an expression: ``agg(revenue=(col("p") * (1 - col("d")), "sum"))``
        — the TPC-H Q3/Q10 shape."""
        aggs = [(func, agg_in, out)
                for out, (agg_in, func) in named_specs.items()]
        return Dataset(Aggregate(self._group_by, aggs, self._dataset.plan),
                       self._dataset.session)

    def count(self, name: str = "count") -> "Dataset":
        """ROW count per group (count(*): null group keys count too)."""
        if not self._group_by:
            raise ValueError(
                "group_by().count() needs group columns; use "
                "Dataset.count() for the total row count")
        return Dataset(Aggregate(self._group_by, [("count_all", "", name)],
                                 self._dataset.plan), self._dataset.session)


class Dataset:
    def __init__(self, plan: LogicalPlan, session) -> None:
        self.plan = plan
        self.session = session

    # -- verbs --------------------------------------------------------------
    def filter(self, condition: Expr) -> "Dataset":
        return Dataset(Filter(condition, self.plan), self.session)

    def select(self, *columns: str, **computed: Expr) -> "Dataset":
        """Project columns, optionally with computed expressions:
        ``select("o_orderkey", revenue=col("p") * (1 - col("d")))``.
        Plain-string-only selects stay a Project (the shape the rewrite
        rules pattern-match); any computed output builds a Compute node."""
        bad = [c for c in columns if not isinstance(c, str)]
        if bad:
            raise ValueError(
                f"select() positional arguments are column names; pass "
                f"expressions as keywords (alias=expr), got {bad[0]!r}")
        if not computed:
            return Dataset(Project(list(columns), self.plan), self.session)
        exprs = [(c, Col(c)) for c in columns]
        for name, e in computed.items():
            if isinstance(e, str):
                # Ambiguous: a rename (col) or a constant (lit)?  Make the
                # caller say which.
                raise ValueError(
                    f"select({name}={e!r}): pass col({e!r}) to project a "
                    f"column under a new name, or lit({e!r}) for a string "
                    f"constant")
            exprs.append((name, e if isinstance(e, Expr) else Lit(e)))
        return Dataset(Compute(exprs, self.plan), self.session)

    def with_column(self, name: str, expr: Expr) -> "Dataset":
        """Append (or replace) one computed column, keeping all others."""
        return Dataset(WithColumns([(name, expr)], self.plan), self.session)

    def with_window(self, name: str, func: str,
                    partition_by: Sequence[str] = (),
                    order_by: Sequence = (),
                    value: str = None, offset: int = 1,
                    frame=None) -> "Dataset":
        """Append one analytic column: ``func(value) OVER (PARTITION BY
        partition_by ORDER BY order_by [ROWS frame])`` — Spark's window
        surface (rank/row_number/dense_rank/ntile/sum/min/max/mean/
        count/lag/lead/first_value/last_value).

            df.with_window("rk", "rank", partition_by=["grp"],
                           order_by=[("revenue", False)])

        ``order_by`` entries are column names or (column, ascending)
        pairs, like ``sort``.  Aggregates with an ORDER BY are running
        (Spark's default RANGE frame: rows tied on the order key share
        one value); without one they reduce the whole partition.
        ``lag``/``lead`` shift ``value`` by ``offset`` rows within the
        partition's order (out-of-partition positions yield null);
        ``ntile`` reads its tile count from ``offset``.  ``frame`` is an
        explicit ROWS frame as an (lo, hi) pair of row offsets relative
        to the current row (negative = preceding, None = unbounded):
        ``frame=(None, 0)`` is ROWS BETWEEN UNBOUNDED PRECEDING AND
        CURRENT ROW, ``frame=(-2, 2)`` a centered 5-row frame."""
        normalized = []
        for k in order_by:
            if isinstance(k, str):
                normalized.append((k, True))
            elif (isinstance(k, (tuple, list)) and len(k) == 2
                    and isinstance(k[0], str)):
                normalized.append((k[0], bool(k[1])))
            else:
                raise ValueError(
                    f"Window order key must be a column name or a "
                    f"(column, ascending) pair, got {k!r}")
        if frame is not None:
            if (not isinstance(frame, (tuple, list)) or len(frame) != 2):
                raise ValueError(
                    f"frame must be an (lo, hi) pair of row offsets "
                    f"(None = unbounded), got {frame!r}")
            frame = (frame[0], frame[1])
        return Dataset(Window(name, func, value, list(partition_by),
                              normalized, self.plan, offset=offset,
                              frame=frame),
                       self.session)

    def join(self, other: "Dataset", condition: Expr, how: str = "inner") -> "Dataset":
        return Dataset(Join(self.plan, other.plan, condition, how), self.session)

    def sort(self, *keys, ascending: bool = True) -> "Dataset":
        """Order by ``keys`` — column names, or (column, ascending)
        pairs; a bare name takes the ``ascending`` default."""
        normalized = []
        for k in keys:
            if isinstance(k, str):
                normalized.append((k, ascending))
            elif (isinstance(k, (tuple, list)) and len(k) == 2
                    and isinstance(k[0], str)
                    and isinstance(k[1], (bool, int, np.bool_,
                                          np.integer))):
                # Bool-like flags only (incl. ints / numpy bools); a string
                # is the ('a', 'b') two-column confusion and None/nested
                # junk means the caller didn't intend a direction — reject.
                normalized.append((k[0], bool(k[1])))
            else:
                raise ValueError(
                    f"Sort key must be a column name or a "
                    f"(column, ascending) pair, got {k!r}")
        return Dataset(Sort(normalized, self.plan), self.session)

    def limit(self, n: int) -> "Dataset":
        return Dataset(Limit(n, self.plan), self.session)

    def distinct(self) -> "Dataset":
        """Unique rows over the full output (SQL DISTINCT)."""
        return Dataset(Distinct(self.plan), self.session)

    def union(self, other: "Dataset") -> "Dataset":
        """UNION ALL with bag semantics, columns resolved BY NAME and
        missing columns null-filled — Spark's
        ``unionByName(allowMissingColumns=True)``, not positional
        ``union``.  Numeric widths widen (int32 ∪ int64 → int64); truly
        incompatible same-named types fail at execution.  Chain
        ``.distinct()`` for SQL UNION."""
        return Dataset(Union([self.plan, other.plan]), self.session)

    def intersect(self, other: "Dataset") -> "Dataset":
        """SQL INTERSECT: distinct rows present in BOTH datasets, rows
        compared positionally and null-safely (Spark's intersect)."""
        return Dataset(SetOp("intersect", self.plan, other.plan),
                       self.session)

    def subtract(self, other: "Dataset") -> "Dataset":
        """SQL EXCEPT: distinct rows of this dataset absent from
        ``other`` (Spark's subtract/except), null-safe comparison."""
        return Dataset(SetOp("except", self.plan, other.plan),
                       self.session)

    def cache(self) -> "Dataset":
        """Materialize this dataset's CURRENT result and return a Dataset
        over the in-memory table (Spark's ``df.cache()`` role, eagerly).
        Later queries over it skip IO and re-optimization of the subtree;
        underlying file changes no longer affect it (like a cached RDD).
        Device-side residency is separate: the HBM column cache
        (execution/device_cache.py) keeps hot INDEX columns on-chip
        keyed by file identity."""
        from hyperspace_tpu.plan.nodes import InMemory

        return Dataset(InMemory(self.collect()), self.session)

    def group_by(self, *columns: str) -> "GroupedDataset":
        return GroupedDataset(self, columns)

    def agg(self, **named_specs) -> "Dataset":
        """Global aggregation (no grouping): ``df.agg(n=("k", "count"))``."""
        return GroupedDataset(self, ()).agg(**named_specs)

    # -- execution ----------------------------------------------------------
    def optimized_plan(self, use_indexes: bool = True) -> LogicalPlan:
        return self.session.optimize(self.plan, use_indexes=use_indexes)

    def collect(self, plan_cache=None) -> pa.Table:
        """Optimize + execute, wrapped in the query-lifecycle trace and a
        :class:`~hyperspace_tpu.telemetry.report.QueryRunReport`: every
        branch this method can take (re-plan, quarantine containment,
        source fallback) is recorded so ``last_run_report()`` can explain
        the query afterwards — docs/16-observability.md.

        ``plan_cache`` is the serving layer's optimize-result cache
        (:class:`~hyperspace_tpu.execution.plan_cache.PlanCache`): on a
        fresh hit the optimizer pass is skipped entirely and the cached
        plan goes straight to the executor; an entry whose plan fails at
        execution is dropped before the degraded/containment machinery
        runs.  Local callers leave it None — caching pays off for the
        repeat-heavy served workload, not one-shot notebook queries."""
        from hyperspace_tpu.telemetry import report as run_report
        from hyperspace_tpu.telemetry import trace

        # Conf set after session construction still wins (same contract as
        # the fault injector / integrity conf re-application).
        trace.configure_from_conf(self.session.conf)
        from hyperspace_tpu.telemetry import timeline

        timeline.configure_from_conf(self.session.conf)
        from hyperspace_tpu.execution import sync_guard

        sync_guard.arm(self.session.conf)
        token = run_report.start()
        query_span = None
        try:
            with trace.span("query.collect") as sp:
                query_span = sp  # the real Span when tracing is enabled
                out = self._collect_traced(plan_cache)
        except Exception:
            rep = run_report.active()
            if rep is not None:
                rep.outcome = "error"
            raise
        finally:
            rep = run_report.finish(token)
            if isinstance(query_span, trace.Span):
                rep.root_span = query_span
            self.session.last_run_report_value = rep
            if trace.current_request_context() is None:
                # A LOCAL query: feed the flight recorder here so
                # slow_queries() works without a server.  Served queries
                # are recorded by their worker/handler (with wire trace
                # context and queue timings), which sets the request
                # scope this checks.  record_local never raises.
                from hyperspace_tpu.telemetry import flight_recorder

                flight_recorder.record_local(self.session.conf, rep)
        if self.session.conf.advisor_capture_enabled:
            # Workload capture (advisor/workload.py): the run report just
            # finished is the feed — fingerprint + measured bytes, folded
            # into the deduplicated workload log.  capture() never raises.
            from hyperspace_tpu.advisor import workload as _workload

            _workload.capture(self.session, self.plan, rep,
                              result_rows=out.num_rows)
        return out

    def explain(self, verbose: bool = False, whatif=None) -> str:
        """The with/without-indexes plan comparison
        (``Hyperspace.explain`` without needing the Hyperspace object).

        ``whatif`` switches to advisor mode: a list of
        :class:`~hyperspace_tpu.index.index_config.IndexConfig` specs (or
        pre-built hypothetical entries) to plan AGAINST AS IF BUILT —
        returns the rendered plan diff plus the estimated bytes-scanned
        delta, touching no data and never executing
        (docs/17-advisor.md)."""
        if whatif is not None:
            from hyperspace_tpu.advisor.hypothetical import whatif as _whatif

            return _whatif(self.session, self, whatif).render()
        from hyperspace_tpu.plananalysis.explain import explain_string

        return explain_string(self, self.session, verbose=verbose)

    def last_run_report(self):
        """The run report of this session's most recent ``collect()`` on
        the calling thread (None before any query), explaining which
        indexes were considered/used/skipped, every degraded/quarantine
        decision, and — when tracing was enabled — where time went."""
        return self.session.last_run_report_value

    def _collect_traced(self, plan_cache=None) -> pa.Table:
        from hyperspace_tpu.exceptions import (
            DeadlineExceededError,
            DeviceSyncError,
        )
        from hyperspace_tpu.execution.executor import Executor
        from hyperspace_tpu.telemetry import report as run_report
        from hyperspace_tpu.telemetry import metrics
        from hyperspace_tpu.telemetry.trace import span
        from hyperspace_tpu.utils import deadline as _deadline

        # Deadline phase boundary: a served request that spent its budget
        # queueing aborts here before paying for planning at all.
        _deadline.check("planning")
        executor = Executor(self.session)
        plan = None
        cache_key = None
        if plan_cache is not None:
            cache_key = plan_cache.key_for(self.session, self.plan)
            if cache_key is not None:
                plan = plan_cache.get(cache_key)
                if plan is not None:
                    # The optimizer pass (whose rules feed indexes_used)
                    # is skipped on a hit: attribute the cached plan's
                    # index scans so "which index answered this query"
                    # survives caching.  The fingerprint rides the
                    # report so the flight record can name the plan.
                    run_report.record("plan_cache", hit=True,
                                      fingerprint=cache_key)
                    for name in _index_scans_of(plan):
                        run_report.record("index.used", index=name,
                                          message="served from plan cache")
        if plan is not None:
            pass  # optimize skipped: the serving layer's repeat fast path
        else:
            try:
                plan = self.optimized_plan()
                if cache_key is not None:
                    plan_cache.put(cache_key, plan)
                    run_report.record("plan_cache", hit=False,
                                      fingerprint=cache_key)
            except Exception as e:  # noqa: BLE001 — InjectedCrash propagates.
                # PLANNING died with index rewrites on (e.g. every file of
                # an index unreadable, so even its schema cannot be
                # fetched).  Degraded mode owns this stage too: re-plan
                # without indexes; a failure of THAT plan is a genuine
                # query error and propagates from a planning pass indexes
                # never touched.  A deadline expiry is NOT a degraded
                # condition: re-planning would spend more time past a
                # deadline that already passed — propagate it.  A strict-
                # mode sync-guard violation likewise: re-planning would
                # just repeat the unattributed sync.
                if isinstance(e, (DeadlineExceededError, DeviceSyncError)):
                    raise
                if not self.session.is_hyperspace_enabled() or \
                        not self.session.conf.degraded_fallback_to_source:
                    raise
                from hyperspace_tpu.telemetry.events import (
                    IndexDegradedEvent,
                    emit_event,
                )

                emit_event(IndexDegradedEvent(
                    reason=f"index-aware planning failed: {e!r}",
                    message="re-planned without index rewrites"))
                run_report.record("replan", mode="source-fallback",
                                  stage="planning")
                with span("optimize.replan", mode="source-fallback"):
                    plan = self.optimized_plan(use_indexes=False)
        try:
            with span("execute"):
                out = executor.execute(plan)
        except Exception as e:  # noqa: BLE001 — InjectedCrash is a
            # BaseException and still dies like a real crash.
            if isinstance(e, (DeadlineExceededError, DeviceSyncError)):
                # Past-deadline work is the one thing the fallback
                # machinery must NOT do more of — propagate immediately.
                # Same for a strict-mode sync-guard violation: the
                # fallback would re-execute the unattributed sync.
                raise
            if cache_key is not None:
                # The cached plan (or the plan just cached) failed at
                # execution: drop it so the containment/fallback outcome
                # below is what the NEXT request re-derives from scratch,
                # not a replay of this failure.
                plan_cache.invalidate(cache_key)
            index_names = _index_scans_of(plan)
            if not index_names or \
                    not self.session.conf.degraded_fallback_to_source:
                raise
            from hyperspace_tpu.telemetry.events import (
                IndexDegradedEvent,
                emit_event,
            )

            # CONTAINMENT first (the integrity loop, docs/15-integrity.md):
            # probe the index files the dead plan read, quarantine the
            # damaged ones, and re-plan WITH indexes — the rewrite rules
            # now serve only the damaged buckets from source.  One rotten
            # bucket costs one bucket's source IO, not the whole index.
            out = None
            if self.session.conf.integrity_quarantine_on_failure:
                with span("containment.probe") as sp:
                    damaged = _quarantine_damaged_index_files(
                        self.session, plan)
                    sp.set(quarantined=len(damaged))
                if damaged:
                    metrics.inc("quarantine.files", len(damaged))
                    run_report.record(
                        "quarantine", index=",".join(index_names),
                        files=damaged)
                    emit_event(IndexDegradedEvent(
                        index_name=",".join(index_names),
                        reason=f"index scan failed at execution: {e!r}; "
                               f"quarantined {len(damaged)} damaged "
                               f"file(s)",
                        message="re-planned with damaged buckets read "
                                "from source"))
                    run_report.record("replan", mode="containment",
                                      stage="execution")
                    try:
                        executor = Executor(self.session)
                        with span("execute.replan", mode="containment"):
                            out = executor.execute(self.optimized_plan())
                    except Exception:  # noqa: BLE001 — containment is
                        # best-effort; the full fallback below still owns
                        # the answer (InjectedCrash stays fatal).
                        out = None
                    if out is not None and \
                            self.session.conf.auto_repair_enabled:
                        # Opt-in self-heal: rebuild the quarantined
                        # buckets now so the NEXT query runs clean.  A
                        # repair failure must never cost this query its
                        # (already computed) answer.
                        for name in index_names:
                            try:
                                self.session.index_collection_manager \
                                    .refresh(name, "repair")
                            except Exception as repair_exc:  # noqa: BLE001
                                # Best-effort self-heal; the failure must
                                # still be visible in the run report.
                                run_report.record(
                                    "replan", mode="auto-repair-failed",
                                    stage="execution",
                                    error=repr(repair_exc))
            if out is None:
                # Degraded mode, execution stage — the LAST resort: re-plan
                # WITHOUT index rewrites and run the source scan; a failure
                # of that plan is a genuine source problem and propagates.
                emit_event(IndexDegradedEvent(
                    index_name=",".join(index_names),
                    reason=f"index scan failed at execution: {e!r}",
                    message="re-executed against the source scan"))
                run_report.record("replan", mode="source-fallback",
                                  stage="execution")
                executor = Executor(self.session)
                with span("execute.replan", mode="source-fallback"):
                    out = executor.execute(
                        self.optimized_plan(use_indexes=False))
        # Physical stats of the most recent execution (join strategies,
        # scan file counts) — read by verbose explain and tests.
        executor.finalize_stats()
        self.session.last_execution_stats = executor.stats
        return out

    def to_pandas(self):
        return self.collect().to_pandas()

    def count(self) -> int:
        return self.collect().num_rows

    @property
    def columns(self) -> List[str]:
        return self.plan.output_columns(self.session.schema_of)

    def explain_string(self) -> str:
        return self.plan.tree_string()

    def show(self, n: int = 20) -> None:
        """Print the first ``n`` rows (df.show analog; the reference shims
        Spark's showString, org/apache/spark/sql/hyperspace/utils).

        Materializes the full result like ``collect()`` does (there is no
        limit pushdown); use a selective filter for large datasets."""
        table = self.collect()
        head = table.slice(0, n)
        names = head.column_names
        rows = [[str(v) for v in row.values()] for row in head.to_pylist()]
        widths = [max(len(name), *(len(r[i]) for r in rows), 1) if rows
                  else len(name) for i, name in enumerate(names)]
        print(" ".join(name.rjust(w) for name, w in zip(names, widths)))
        for r in rows:
            print(" ".join(v.rjust(w) for v, w in zip(r, widths)))
        if table.num_rows > n:
            print(f"... ({table.num_rows - n} more rows)")
