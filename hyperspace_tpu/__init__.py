"""hyperspace_tpu: a TPU-native indexing subsystem for lake-resident data.

Users build covering indexes — bucket-hashed, sorted, column-pruned copies
of source datasets — and optimizer rules transparently rewrite filter/join
queries to scan the index instead of the raw data.  The data plane (hash,
sort, predicate, join) runs on TPU via JAX/XLA; the metadata/control plane
(operation log, action state machine, signatures, hybrid scan) is host-side.

Public API mirrors the reference surface (Hyperspace.scala:26-166,
package.scala:47-79, python/hyperspace/hyperspace.py:9).
"""

from hyperspace_tpu.actions.optimize import OptimizeSummary
from hyperspace_tpu.actions.refresh import RefreshSummary
from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.dataset import Dataset
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.hyperspace import Hyperspace
from hyperspace_tpu.index.index_config import DataSkippingIndexConfig, IndexConfig
from hyperspace_tpu.plan.expr import (
    col,
    concat,
    dayofmonth,
    exists,
    in_subquery,
    length,
    lit,
    lower,
    month,
    outer_ref,
    quarter,
    scalar,
    substring,
    trim,
    upper,
    when,
    year,
)
from hyperspace_tpu.session import HyperspaceSession

__version__ = "0.4.0"

__all__ = [
    "Hyperspace",
    "HyperspaceSession",
    "HyperspaceConf",
    "HyperspaceError",
    "IndexConfig",
    "DataSkippingIndexConfig",
    "Dataset",
    "RefreshSummary",
    "OptimizeSummary",
    "col",
    "lit",
    "when",
    "year",
    "month",
    "dayofmonth",
    "quarter",
    "scalar",
    "in_subquery",
    "outer_ref",
    "exists",
    "upper",
    "lower",
    "length",
    "trim",
    "substring",
    "concat",
]
