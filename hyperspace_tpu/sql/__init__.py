"""SQL front end: SELECT text -> the engine's Dataset DSL.

The reference's users and its golden harness feed ``.sql`` files
(goldstandard/PlanStabilitySuite.scala:81-283); this package parses a
practical SELECT dialect and lowers it onto the existing plan verbs, so
corpus queries run near-verbatim.  ``plan/pushdown.py`` makes the
canonical WHERE-above-joins lowering optimize into the same plans as
hand-placed DSL filters.
"""

from hyperspace_tpu.sql.parser import SqlError, sql

__all__ = ["sql", "SqlError"]
