"""A recursive-descent SELECT parser lowering to the Dataset DSL.

Supported surface (the shapes the reference's TPC corpus uses):

    SELECT [DISTINCT] items | *
    FROM table [alias] | (subquery) [alias]
    [ [INNER|LEFT [OUTER]|RIGHT [OUTER]|FULL [OUTER]|[LEFT] SEMI|
       [LEFT] ANTI] JOIN source ON cond ]...
    [WHERE cond] [GROUP BY keys] [HAVING cond]
    [ORDER BY out [ASC|DESC], ...] [LIMIT n]

Expressions: literals (numbers, 'strings', DATE 'yyyy-mm-dd', TRUE/
FALSE/NULL), [alias.]column, + - * /, comparisons (= <> != < <= > >=),
AND/OR/NOT, BETWEEN, [NOT] IN (list | subquery), [NOT] LIKE, IS [NOT]
NULL, CASE WHEN, CAST(x AS type), EXTRACT(field FROM x) and
year/month/day/quarter(x), aggregate calls (sum/min/max/avg/count/
count(DISTINCT x)/stddev/variance), window calls ``func(...) OVER
(PARTITION BY ... ORDER BY ...)`` as top-level select items, scalar
subqueries ``(SELECT ...)``.  A column qualified by an alias not in the
current scope becomes ``outer_ref`` — SQL's correlated subquery form.

[NOT] EXISTS (SELECT ... WHERE inner = alias.outer) lowers to the
SEMI/ANTI join rewrite (plan/subquery.py); the subquery's own select
list is existence-only, so ``SELECT 1`` works.  In NON-aggregate select
lists, unaliased computed items auto-name as ``_c<position>``;
aggregate select items still require AS aliases (their names become the
aggregate outputs).
"""

from __future__ import annotations

import datetime
import re
from typing import Any, Dict, List, Optional, Tuple

from hyperspace_tpu.plan.expr import (
    And,
    BinOp,
    Case,
    Cast,
    Col,
    Exists,
    Expr,
    Extract,
    InSubquery,
    IsIn,
    IsNull,
    Lit,
    Neg,
    Not,
    Or,
    OuterRef,
    ScalarSubquery,
    StringFn,
    StringMatch,
)


class SqlError(ValueError):
    """Parse or lowering failure, with position context."""


# ---- markers local to lowering -----------------------------------------

class _AggCall(Expr):
    def __init__(self, func: str, arg: Optional[Expr]) -> None:
        self.func = func  # engine spelling (mean, count_all, ...)
        # Named "child" so the shared expression walkers
        # (plan/subquery._walk_exprs) descend into it.
        self.child = arg

    def __repr__(self) -> str:
        return f"_agg_{self.func}({self.child!r})"


class _WindowCall(Expr):
    def __init__(self, func, value, partition_by, order_by,
                 offset: int = 1, frame=None) -> None:
        self.func = func
        self.value = value
        self.partition_by = partition_by
        self.order_by = order_by
        self.offset = offset
        self.frame = frame

    def __repr__(self) -> str:
        # STRUCTURAL repr: ORDER BY-expression resolution matches select
        # items by repr, so two windows differing only in value/keys/
        # frame must never collide.
        return (f"_window_{self.func}({self.value!r}, "
                f"p={list(self.partition_by)!r}, "
                f"o={list(self.order_by)!r}, k={self.offset}, "
                f"f={self.frame!r})")


_AGG_FUNCS = {"sum": "sum", "min": "min", "max": "max", "avg": "mean",
              "mean": "mean", "count": "count", "stddev": "stddev",
              "variance": "variance"}
_WINDOW_FUNCS = ("row_number", "rank", "dense_rank", "ntile", "sum",
                 "min", "max", "avg", "count", "lag", "lead",
                 "first_value", "last_value")
_EXTRACT_FUNCS = {"year": "year", "month": "month", "day": "day",
                  "dayofmonth": "day", "quarter": "quarter"}

_NAME_KINDS = ("ident", "qident")

_TOKEN_RE = re.compile(r"""
    \s+
  | --[^\n]*
  | (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+|\d+(?:[eE][+-]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<bq>`[^`]*`)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><>|!=|<=|>=|=|<|>|\(|\)|,|\.|\*|\+|-|/|;)
""", re.VERBOSE)


def _tokenize(text: str) -> List[Tuple[str, str, int]]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise SqlError(f"Unexpected character {text[pos]!r} at "
                           f"position {pos}: ...{text[pos:pos+20]!r}")
        pos = m.end()
        if m.lastgroup == "num":
            out.append(("num", m.group("num"), m.start()))
        elif m.lastgroup == "str":
            out.append(("str", m.group("str")[1:-1].replace("''", "'"),
                        m.start()))
        elif m.lastgroup == "bq":
            # Backtick-quoted identifier (TPC-DS q32/q92 alias spelling):
            # its OWN token kind, so quoting a reserved word (`from`,
            # `order`) never trips the keyword matchers — only the
            # name-position readers accept it (_NAME_KINDS).
            out.append(("qident", m.group("bq")[1:-1], m.start()))
        elif m.lastgroup == "ident":
            out.append(("ident", m.group("ident"), m.start()))
        elif m.lastgroup == "op":
            out.append(("op", m.group("op"), m.start()))
    out.append(("eof", "", len(text)))
    return out


class _Parser:
    def __init__(self, text: str, session, tables: Dict[str, Any],
                 outer_aliases: Tuple[str, ...] = (),
                 outer_columns: frozenset = frozenset()) -> None:
        self.text = text
        self.tokens = _tokenize(text)
        self.i = 0
        self.session = session
        self.tables = tables
        self.outer_aliases = outer_aliases
        # Column names visible in the ENCLOSING query's scope: a bare
        # name unknown here but known there is an implicit correlation
        # (TPC-DS q32/q92 correlate through bare names).
        self.outer_columns = outer_columns
        self.aliases: List[str] = []  # this query's own scope
        # FROM-order source registry: ({names}, [columns] or None) per
        # source, for qualified-reference validation.
        self.sources: List[Tuple[set, Optional[List[str]]]] = []
        # Comma-style self-join lift: alias -> column prefix for later
        # occurrences of an already-seen table, whose columns are
        # renamed so every column has exactly one owning source.
        self.qual_rename: Dict[str, str] = {}
        self._in_join_on = False

    # -- token plumbing --------------------------------------------------
    def peek(self, offset: int = 0):
        return self.tokens[min(self.i + offset, len(self.tokens) - 1)]

    def next(self):
        t = self.tokens[self.i]
        self.i = min(self.i + 1, len(self.tokens) - 1)
        return t

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t[0] == "ident" and t[1].upper() in words

    def take_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.next()
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.take_kw(word):
            self.fail(f"expected {word}")

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t[0] == "op" and t[1] in ops

    def take_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.take_op(op):
            self.fail(f"expected {op!r}")

    def fail(self, msg: str) -> None:
        t = self.peek()
        raise SqlError(f"{msg} at position {t[2]} (near {t[1]!r}): "
                       f"...{self.text[t[2]:t[2] + 30]!r}")

    # -- query -----------------------------------------------------------
    def parse_select(self, allow_tail: bool = True):
        self.expect_kw("SELECT")
        distinct = self.take_kw("DISTINCT")
        # FROM declares the aliases the select list references, so parse
        # it FIRST: skip ahead to the depth-0 FROM, build the sources,
        # then come back for the items with the scope populated.
        items_start = self.i
        self._skip_to_from()
        self.expect_kw("FROM")
        ds = self.parse_from()
        after_from = self.i
        self.i = items_start
        items = self.parse_select_items()
        if not self.at_kw("FROM"):
            self.fail("expected FROM after the select list")
        self.i = after_from
        where = None
        if self.take_kw("WHERE"):
            where = self.parse_expr()
        if isinstance(ds, _CommaJoin):
            ds, where = _assemble_comma_join(self, ds.items, where)
        group_by: List[str] = []
        if self.take_kw("GROUP"):
            self.expect_kw("BY")
            group_by = self.parse_group_keys()
        having = None
        if self.take_kw("HAVING"):
            having = self.parse_expr()
        order_by: List[Tuple[str, bool]] = []
        limit = None
        if allow_tail:
            # Inside a UNION chain the trailing ORDER BY/LIMIT bind the
            # WHOLE union (SQL), so branch parses leave them untouched.
            if self.take_kw("ORDER"):
                self.expect_kw("BY")
                order_by = self.parse_order_keys()
            if self.take_kw("LIMIT"):
                limit = self.parse_limit_count()
        return _lower(self, ds, items, distinct, where, group_by, having,
                      order_by, limit)

    def parse_limit_count(self) -> int:
        t = self.next()
        if t[0] != "num":
            self.fail("expected a number after LIMIT")
        return int(t[1])

    def _parse_frame_bound(self):
        """One frame bound → ("unb", ±1) or ("off", signed_row_offset)."""
        if self.take_kw("UNBOUNDED"):
            if self.take_kw("PRECEDING"):
                return ("unb", -1)
            if self.take_kw("FOLLOWING"):
                return ("unb", 1)
            self.fail("expected PRECEDING or FOLLOWING after UNBOUNDED")
        if self.take_kw("CURRENT"):
            self.expect_kw("ROW")
            return ("off", 0)
        t = self.next()
        if t[0] != "num" or "." in str(t[1]):
            self.fail("expected UNBOUNDED, CURRENT ROW, or an integer "
                      "frame offset")
        k = int(t[1])
        if self.take_kw("PRECEDING"):
            return ("off", -k)
        if self.take_kw("FOLLOWING"):
            return ("off", k)
        self.fail("expected PRECEDING or FOLLOWING after the frame "
                  "offset")

    def parse_frame_clause(self):
        """Optional window frame.  ROWS frames lower to the engine's
        (lo, hi) row-offset pair (None = unbounded); RANGE accepts only
        the shapes equal to SQL's DEFAULT frame (UNBOUNDED PRECEDING ..
        CURRENT ROW, the form TPC-DS q51 spells out —
        /root/reference/src/test/resources/tpcds/queries/q51.sql:1-8)
        and returns None so peers share values."""
        is_range = False
        if self.take_kw("ROWS"):
            pass
        elif self.take_kw("RANGE"):
            is_range = True
        else:
            return None
        if self.take_kw("BETWEEN"):
            lo_b = self._parse_frame_bound()
            self.expect_kw("AND")
            hi_b = self._parse_frame_bound()
        else:  # SQL shorthand: <bound> means BETWEEN <bound> AND CURRENT
            lo_b = self._parse_frame_bound()
            hi_b = ("off", 0)
        if lo_b == ("unb", 1):
            self.fail("frame cannot start at UNBOUNDED FOLLOWING")
        if hi_b == ("unb", -1):
            self.fail("frame cannot end at UNBOUNDED PRECEDING")
        lo = None if lo_b[0] == "unb" else lo_b[1]
        hi = None if hi_b[0] == "unb" else hi_b[1]
        if is_range:
            if not (lo is None and hi == 0):
                self.fail("Only RANGE BETWEEN UNBOUNDED PRECEDING AND "
                          "CURRENT ROW is supported; use a ROWS frame "
                          "for offset frames")
            return None  # identical to the default frame
        if lo is not None and hi is not None and lo > hi:
            self.fail(f"frame lower bound {lo} is above upper bound "
                      f"{hi}")
        return (lo, hi)

    def _skip_to_from(self) -> None:
        depth = 0
        while True:
            t = self.peek()
            if t[0] == "eof":
                self.fail("expected FROM")
            if t[0] == "op" and t[1] == "(":
                depth += 1
            elif t[0] == "op" and t[1] == ")":
                depth -= 1
            elif depth == 0 and t[0] == "ident" and t[1].upper() == "FROM":
                return
            self.next()

    def parse_select_items(self):
        if self.take_op("*"):
            return [("*", None)]
        items = []
        while True:
            e = self.parse_expr()
            alias = None
            if self.take_kw("AS"):
                t = self.next()
                if t[0] not in _NAME_KINDS:
                    self.fail("expected an alias after AS")
                alias = t[1]
            elif self.peek()[0] in _NAME_KINDS and not self.at_kw(
                    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT"):
                alias = self.next()[1]
            items.append((alias, e))
            if not self.take_op(","):
                return items

    def parse_group_keys(self) -> List[str]:
        keys = []
        while True:
            e = self.parse_expr()
            keys.append(e)
            if not self.take_op(","):
                return keys

    def parse_order_keys(self):
        """ORDER BY entries: (column_name, asc) for plain references, or
        (Expr, asc) for expression keys (``ORDER BY sum(x) DESC`` — the
        TPC-DS corpus orders by unaliased aggregates); _lower resolves
        expression keys against the select outputs structurally."""
        keys = []
        while True:
            e = self.parse_expr()
            asc = True
            if self.take_kw("DESC"):
                asc = False
            else:
                self.take_kw("ASC")
            keys.append((e.name if isinstance(e, Col) else e, asc))
            if not self.take_op(","):
                return keys

    # -- FROM / JOIN -----------------------------------------------------
    def parse_from(self):
        """One FROM clause.  Comma-separated sources (the TPC-DS corpus
        idiom, ``FROM store_sales, date_dim, item WHERE ...``) return a
        _CommaJoin placeholder: the join tree is assembled AFTER the
        WHERE clause parses, from its equi-join conjuncts — explicit
        JOIN ... ON binds tighter than the comma, per SQL."""
        items = [self._parse_from_item()]
        while self.take_op(","):
            items.append(self._parse_from_item())
        if len(items) == 1:
            return items[0]
        return _CommaJoin(items)

    def _parse_from_item(self):
        ds = self.parse_source()
        while True:
            how = self.parse_join_type()
            if how is None:
                return ds
            right = self.parse_source()
            self.expect_kw("ON")
            # Join conditions resolve each side independently (the
            # engine's equi-join pairs), so same-named keys on both
            # sides are fine there — skip the ambiguity check.
            self._in_join_on = True
            try:
                cond = self.parse_expr()
            finally:
                self._in_join_on = False
            ds = ds.join(right, cond, how=how)

    def parse_join_type(self) -> Optional[str]:
        if self.take_kw("JOIN"):
            return "inner"
        if self.take_kw("INNER"):
            self.expect_kw("JOIN")
            return "inner"
        for kw, how in (("LEFT", "left"), ("RIGHT", "right"),
                        ("FULL", "full"), ("SEMI", "semi"),
                        ("ANTI", "anti")):
            if self.at_kw(kw):
                self.next()
                if kw == "LEFT" and self.at_kw("SEMI", "ANTI"):
                    how = "semi" if self.take_kw("SEMI") else "anti"
                else:
                    self.take_kw("OUTER")
                self.expect_kw("JOIN")
                return how
        return None

    def parse_source(self):
        if self.take_op("("):
            sub = self.fork()
            sub.outer_aliases = self.outer_aliases
            ds = sub.parse_select()
            self.i = sub.i
            self.expect_op(")")
            names = set()
            if self.peek()[0] in _NAME_KINDS \
                    and not self._at_clause_kw():
                alias = self.next()[1]
                self.aliases.append(alias)
                names.add(alias)
            self._register_source(names, ds)
            return ds
        t = self.next()
        if t[0] not in _NAME_KINDS:
            self.fail("expected a table name")
        name = t[1]
        src = self.tables.get(name)
        if src is None:
            raise SqlError(
                f"Unknown table {name!r}; pass it in sql(..., tables="
                f"{{{name!r}: dataset_or_parquet_path}})")
        ds = self.session.read.parquet(src) if isinstance(src, str) else src
        alias = None
        if self.peek()[0] in _NAME_KINDS \
                and not self._at_clause_kw():
            alias = self.next()[1]
        seen_before = any(name in ns for ns, _c in self.sources)
        if alias is None and seen_before:
            # Without an alias there is nothing to address the second
            # instance by: every qualified reference would bind to
            # whichever registration happened to come first.  Error
            # crisply instead of answering from an ambiguous plan.
            raise SqlError(
                f"Table {name!r} appears more than once in FROM and "
                f"the later occurrence needs an alias (e.g. "
                f"{name} a JOIN {name} b ON ...) so qualified "
                f"references are unambiguous")
        if alias is not None and seen_before:
            # Self-join lift: a LATER occurrence of an already-seen
            # table becomes an independent scan instance with its
            # columns renamed to ``<alias>__<column>`` — every column
            # then has exactly one owning source, so the comma-join
            # assembly's owner() resolution (and qualified-reference
            # validation) work unchanged.  Only the alias addresses the
            # instance; unaliased select items keep the lifted engine
            # name (``m.name`` -> output column ``m__name``) — use AS
            # for SQL-style output names.
            try:
                cols = list(ds.columns)
            except Exception:
                self.fail(f"self-joined table {name!r} needs a "
                          f"resolvable schema")
            ds = ds.select(**{f"{alias}__{c}": Col(c) for c in cols})
            self.qual_rename[alias] = f"{alias}__"
            self.aliases.append(alias)
            self._register_source({alias}, ds)
            return ds
        names = {name}
        self.aliases.append(name)
        if alias is not None:
            self.aliases.append(alias)
            names.add(alias)
        self._register_source(names, ds)
        return ds

    def fork(self) -> "_Parser":
        """A fresh per-select scope sharing this parser's token stream
        (no re-tokenization) and position."""
        child = _Parser.__new__(_Parser)
        child.text = self.text
        child.tokens = self.tokens
        child.i = self.i
        child.session = self.session
        child.tables = self.tables
        child.outer_aliases = ()
        child.outer_columns = frozenset()
        child.aliases = []
        child.sources = []
        child.qual_rename = {}
        child._in_join_on = False
        return child

    def _register_source(self, names: set, ds) -> None:
        try:
            cols = list(ds.columns)
        except Exception:
            cols = None  # unresolvable schema: skip validation
        self.sources.append((names, cols))

    def _at_clause_kw(self) -> bool:
        return self.at_kw("WHERE", "GROUP", "HAVING", "ORDER", "LIMIT",
                          "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "SEMI",
                          "ANTI", "ON", "AS", "UNION", "INTERSECT",
                          "EXCEPT", "MINUS")

    # -- expressions (precedence climbing) -------------------------------
    def parse_expr(self) -> Expr:
        return self.parse_or()

    def parse_or(self) -> Expr:
        e = self.parse_and()
        while self.take_kw("OR"):
            e = Or(e, self.parse_and())
        return e

    def parse_and(self) -> Expr:
        e = self.parse_not()
        while self.take_kw("AND"):
            e = And(e, self.parse_not())
        return e

    def parse_not(self) -> Expr:
        if self.take_kw("NOT"):
            return Not(self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> Expr:
        e = self.parse_additive()
        if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
            op = self.next()[1]
            rhs = self.parse_additive()
            if op == "=":
                return BinOp("==", e, rhs)
            if op in ("<>", "!="):
                return Not(BinOp("==", e, rhs))
            return BinOp(op, e, rhs)
        if self.at_kw("BETWEEN"):
            self.next()
            lo = self.parse_additive()
            self.expect_kw("AND")
            hi = self.parse_additive()
            return And(BinOp(">=", e, lo), BinOp("<=", e, hi))
        negated = False
        if self.at_kw("NOT") and self.peek(1)[0] == "ident" \
                and self.peek(1)[1].upper() in ("IN", "LIKE"):
            self.next()
            negated = True
        if self.take_kw("IN"):
            self.expect_op("(")
            if self.at_kw("SELECT"):
                sub = self._parse_subquery()
                out: Expr = InSubquery(e, sub.plan)
            else:
                values = [self._literal_value(self.parse_additive())]
                while self.take_op(","):
                    values.append(self._literal_value(self.parse_additive()))
                out = IsIn(e, values)
            if not isinstance(out, InSubquery):
                self.expect_op(")")
            return Not(out) if negated else out
        if self.take_kw("LIKE"):
            t = self.next()
            if t[0] != "str":
                self.fail("LIKE needs a string pattern")
            out = StringMatch("like", e, t[1])
            return Not(out) if negated else out
        if self.take_kw("IS"):
            neg = self.take_kw("NOT")
            self.expect_kw("NULL")
            out = IsNull(e)
            return Not(out) if neg else out
        return e

    def _literal_value(self, e: Expr):
        if isinstance(e, Neg) and isinstance(e.child, Lit) \
                and isinstance(e.child.value, (int, float)):
            return -e.child.value
        if not isinstance(e, Lit):
            self.fail("IN lists take literals (use an IN subquery for "
                      "computed sets)")
        return e.value

    def parse_additive(self) -> Expr:
        e = self.parse_multiplicative()
        while self.at_op("+", "-"):
            op = self.next()[1]
            if self.at_kw("INTERVAL"):
                # Constant date arithmetic — TPC-DS's
                # ``cast('1999-02-22' AS DATE) + INTERVAL 30 days``
                # (q12/q20/q37/q82/q98): folds to a date literal at
                # parse time.  Non-constant date expressions would need
                # runtime interval arithmetic — rejected loudly.
                days = self._parse_interval_days()
                base = _fold_const_date(e)
                if base is None:
                    self.fail("INTERVAL arithmetic needs a constant "
                              "date left-hand side (a DATE literal or "
                              "cast('...' AS DATE))")
                delta = datetime.timedelta(days=days)
                e = Lit(base + delta if op == "+" else base - delta)
                continue
            e = (e + self.parse_multiplicative()) if op == "+" \
                else (e - self.parse_multiplicative())
        return e

    def _parse_interval_days(self) -> int:
        self.expect_kw("INTERVAL")
        t = self.next()
        if t[0] != "num" or "." in str(t[1]):
            self.fail("INTERVAL needs an integer count")
        unit = self.next()
        if unit[0] != "ident" or unit[1].upper() not in ("DAY", "DAYS"):
            self.fail("Only INTERVAL <n> DAYS is supported")
        return int(t[1])

    def parse_multiplicative(self) -> Expr:
        e = self.parse_unary()
        while self.at_op("*", "/"):
            op = self.next()[1]
            e = (e * self.parse_unary()) if op == "*" \
                else (e / self.parse_unary())
        return e

    def parse_unary(self) -> Expr:
        if self.take_op("-"):
            return Neg(self.parse_unary())
        if self.take_op("+"):
            return self.parse_unary()
        return self.parse_primary()

    def _parse_subquery(self):
        own_cols = set()
        for _names, cols in self.sources:
            own_cols |= set(cols or ())
        # fork() shares the token stream — no re-lex of the whole text
        # per subquery — then the correlation scope attaches.
        sub = self.fork()
        sub.outer_aliases = tuple(self.aliases) + self.outer_aliases
        sub.outer_columns = frozenset(own_cols) | self.outer_columns
        ds = sub.parse_select()
        self.i = sub.i
        self.expect_op(")")
        return ds

    def parse_primary(self) -> Expr:
        t = self.peek()
        if self.take_op("("):
            if self.at_kw("SELECT"):
                return ScalarSubquery(self._parse_subquery().plan)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t[0] == "num":
            self.next()
            text = t[1]
            return Lit(float(text) if any(c in text for c in ".eE")
                       else int(text))
        if t[0] == "str":
            self.next()
            return Lit(t[1])
        if t[0] == "qident":
            self.next()
            return Col(t[1])
        if t[0] != "ident":
            self.fail("expected an expression")
        word = t[1]
        upper = word.upper()
        if upper == "DATE":
            self.next()
            s = self.next()
            if s[0] != "str":
                self.fail("DATE needs a 'yyyy-mm-dd' string")
            try:
                return Lit(datetime.date.fromisoformat(s[1]))
            except ValueError as e:
                raise SqlError(f"Bad DATE literal {s[1]!r}: {e}") from e
        if upper in ("TRUE", "FALSE"):
            self.next()
            return Lit(upper == "TRUE")
        if upper == "NULL":
            self.next()
            return Lit(None)
        if upper == "CASE":
            return self.parse_case()
        if upper == "CAST":
            self.next()
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("AS")
            type_name = self.next()[1]
            self.expect_op(")")
            return Cast(e, type_name)
        if upper == "EXTRACT":
            self.next()
            self.expect_op("(")
            field = self.next()[1].lower()
            if field not in _EXTRACT_FUNCS:
                self.fail(f"EXTRACT field must be one of "
                          f"{sorted(_EXTRACT_FUNCS)}")
            self.expect_kw("FROM")
            e = self.parse_expr()
            self.expect_op(")")
            return Extract(_EXTRACT_FUNCS[field], e)
        if upper == "EXISTS":
            self.next()
            self.expect_op("(")
            if not self.at_kw("SELECT"):
                self.fail("EXISTS needs a (SELECT ...) subquery")
            return Exists(self._parse_subquery().plan)
        if self.peek(1)[0] == "op" and self.peek(1)[1] == "(":
            return self.parse_call()
        # [alias.]column
        self.next()
        if self.take_op("."):
            c = self.next()
            if c[0] not in _NAME_KINDS:
                self.fail("expected a column after '.'")
            if word in self.aliases:
                return self._qualified_col(word, c[1])
            if word in self.outer_aliases:
                return OuterRef(c[1])
            raise SqlError(
                f"Unknown table alias {word!r} (in scope: "
                f"{self.aliases + list(self.outer_aliases)})")
        if self.outer_columns and word in self.outer_columns \
                and not any(cols is None or word in cols
                            for _n, cols in self.sources):
            # Unknown in every LOCAL source (all of which have resolved
            # schemas) but known in the enclosing scope: SQL's implicit
            # correlated reference.  Innermost scope always wins when a
            # local source could plausibly own the name.
            return OuterRef(word)
        return Col(word)

    def _qualified_col(self, alias: str, column: str) -> Expr:
        """``alias.column`` with BINDING validation: the engine's Col has
        no qualifier, and a joined table exposes the FIRST (leftmost)
        source's copy under an ambiguous name — so a reference that
        would silently bind to a different table must error instead.
        A self-join-lifted alias translates to its renamed column."""
        prefix = self.qual_rename.get(alias, "")
        column = prefix + column
        target = next((cols for names, cols in self.sources
                       if alias in names), None)
        if target is not None:
            if column not in target:
                shown = [c[len(prefix):] if prefix else c for c in target]
                raise SqlError(
                    f"Column {column[len(prefix):]!r} does not exist in "
                    f"table {alias!r} (columns: {shown})")
            first = next((names for names, cols in self.sources
                          if cols is not None and column in cols), None)
            if not self._in_join_on and first is not None \
                    and alias not in first:
                raise SqlError(
                    f"Ambiguous column {alias}.{column}: another table "
                    f"earlier in FROM also has {column!r}, and the "
                    f"joined output exposes that copy under this name — "
                    f"rename one side via a derived table "
                    f"(SELECT {column} AS ... FROM ...)")
        return Col(column)

    def parse_case(self) -> Expr:
        """Both CASE forms.  The simple form (``CASE expr WHEN v THEN r
        ...``) desugars to the searched form with ``expr = v``
        conditions, exactly as Spark's parser does — so a NULL operand
        matches no WHEN (NULL = v is NULL, never true) and falls
        through to ELSE."""
        self.expect_kw("CASE")
        operand: Optional[Expr] = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expr()
        branches = []
        while self.take_kw("WHEN"):
            cond = self.parse_expr()
            if operand is not None:
                cond = BinOp("==", operand, cond)
            self.expect_kw("THEN")
            branches.append((cond, self.parse_expr()))
        otherwise: Expr = Lit(None)
        if self.take_kw("ELSE"):
            otherwise = self.parse_expr()
        self.expect_kw("END")
        if not branches:
            self.fail("CASE needs at least one WHEN")
        return Case(branches, otherwise)

    def parse_call(self) -> Expr:
        name = self.next()[1].lower()
        self.expect_op("(")
        distinct = False
        star = False
        arg: Optional[Expr] = None
        args: List[Expr] = []
        if self.take_op("*"):
            star = True
        elif not self.at_op(")"):
            if self.take_kw("DISTINCT"):
                distinct = True
            arg = self.parse_expr()
            args.append(arg)
            while self.take_op(","):
                args.append(self.parse_expr())
        self.expect_op(")")
        if name in ("substr", "substring"):
            if distinct or star:
                self.fail("substring() takes plain expression arguments")
            if len(args) not in (2, 3):
                self.fail("substring(expr, start[, length])")
            folded = [args[0]]
            for a in args[1:]:
                if isinstance(a, Neg) and isinstance(a.child, Lit):
                    a = Lit(-a.child.value)  # unary minus parses as Neg
                if not (isinstance(a, Lit) and isinstance(a.value, int)
                        and not isinstance(a.value, bool)):
                    self.fail("substring start/length must be integer "
                              "literals")
                folded.append(a)
            try:
                return StringFn("substring", folded)
            except ValueError as e:
                self.fail(str(e))
        if name in ("upper", "lower", "length", "trim", "ltrim", "rtrim"):
            if distinct or star or len(args) != 1:
                self.fail(f"{name}() takes one argument")
            return StringFn(name, args)
        if name == "concat":
            if distinct or star or len(args) < 2:
                self.fail("concat() needs at least two plain arguments")
            return StringFn("concat", args)
        if name in ("coalesce", "ifnull", "nvl", "nullif") \
                and (distinct or star):
            self.fail(f"{name}() takes plain expression arguments")
        if name in ("coalesce", "ifnull", "nvl"):
            if len(args) < 2:
                self.fail(f"{name}() needs at least two arguments")
            # COALESCE(a, b, c) -> CASE WHEN a IS NOT NULL THEN a
            #                           WHEN b IS NOT NULL THEN b ELSE c
            branches = [(Not(IsNull(a)), a) for a in args[:-1]]
            return Case(branches, args[-1])
        if name == "nullif":
            if len(args) != 2:
                self.fail("nullif() takes exactly two arguments")
            return Case([(BinOp("==", args[0], args[1]), Lit(None))],
                        args[0])
        if len(args) > 1 and name not in ("lag", "lead"):
            self.fail(f"{name}() takes one argument")
        # OVER -> window call
        if self.at_kw("OVER"):
            self.next()
            self.expect_op("(")
            partition: List[str] = []
            order: List[Tuple[str, bool]] = []
            if self.take_kw("PARTITION"):
                self.expect_kw("BY")
                while True:
                    c = self.parse_primary()
                    if not isinstance(c, Col):
                        self.fail("PARTITION BY keys must be columns")
                    partition.append(c.name)
                    if not self.take_op(","):
                        break
            if self.take_kw("ORDER"):
                self.expect_kw("BY")
                while True:
                    c = self.parse_primary()
                    if not isinstance(c, Col):
                        self.fail("window ORDER BY keys must be columns")
                    asc = True
                    if self.take_kw("DESC"):
                        asc = False
                    else:
                        self.take_kw("ASC")
                    order.append((c.name, asc))
                    if not self.take_op(","):
                        break
            frame = self.parse_frame_clause()
            self.expect_op(")")
            if name not in _WINDOW_FUNCS:
                self.fail(f"Unsupported window function {name}")
            if distinct:
                self.fail("DISTINCT is not supported in window functions")
            func = {"avg": "mean"}.get(name, name)
            value = None
            offset = 1
            if func in ("sum", "min", "max", "mean", "count", "lag",
                        "lead", "first_value", "last_value") \
                    and arg is not None:
                if isinstance(arg, Col):
                    value = arg.name
                elif isinstance(arg, _AggCall) and func in (
                        "sum", "min", "max", "mean", "count",
                        "first_value", "last_value"):
                    # Window over an aggregate output — TPC-DS's
                    # ``sum(sum(x)) OVER (...)`` idiom (q51/q12/q20):
                    # the inner aggregate materializes as a hidden
                    # GROUP BY output and the window runs over it.
                    value = arg
                else:
                    self.fail("window function arguments must be "
                              "columns (or aggregates in a GROUP BY "
                              "query)")
            if func in ("lag", "lead"):
                if len(args) > 2:
                    self.fail(f"{func}(value[, offset]) takes at most "
                              f"two arguments")
                if len(args) == 2:
                    off = args[1]
                    if not isinstance(off, Lit) \
                            or not isinstance(off.value, int):
                        self.fail(f"{func}() offset must be an integer "
                                  f"literal")
                    offset = off.value
            if func == "ntile":
                if not args or not isinstance(args[0], Lit) \
                        or not isinstance(args[0].value, int):
                    self.fail("ntile(n) needs an integer literal "
                              "tile count")
                offset = args[0].value
                value = None
            return _WindowCall(func, value, partition, order, offset,
                               frame=frame)
        if name in _AGG_FUNCS:
            func = _AGG_FUNCS[name]
            if name == "count":
                if star:
                    return _AggCall("count_all", None)
                if distinct:
                    return _AggCall("count_distinct", arg)
                return _AggCall("count", arg)
            if distinct:
                self.fail(f"DISTINCT is only supported inside count()")
            if arg is None:
                self.fail(f"{name}() needs an argument")
            return _AggCall(func, arg)
        if name in _EXTRACT_FUNCS:
            if arg is None:
                self.fail(f"{name}() needs an argument")
            return Extract(_EXTRACT_FUNCS[name], arg)
        self.fail(f"Unknown function {name}")


# ---- lowering ----------------------------------------------------------

def _map(e: Expr, fn) -> Expr:
    from hyperspace_tpu.plan.subquery import _map_expr

    return _map_expr(e, fn)


def _contains_agg(e: Expr) -> bool:
    from hyperspace_tpu.plan.subquery import _contains

    return _contains(e, _AggCall)


def _contains_window(e: Expr) -> bool:
    from hyperspace_tpu.plan.subquery import _contains

    return _contains(e, _WindowCall)


def _lower(p: _Parser, ds, items, distinct, where, group_by, having,
           order_by, limit):
    if where is not None:
        _reject_markers(where, "WHERE")
        ds = ds.filter(where)

    star = len(items) == 1 and items[0][0] == "*" and items[0][1] is None
    has_agg = any(_contains_agg(e) for _a, e in items
                  if e is not None and not isinstance(e, _WindowCall))
    aggregate_query = bool(group_by) or has_agg

    # Output in SELECT-LIST ORDER: (name, None) for a plain column of the
    # current dataset, (name, expr) for a computed output.
    out_items: List[Tuple[str, Optional[Expr]]] = []
    windows_to_apply: List[Tuple[str, _WindowCall]] = []
    # ORDER BY may reference select items by EXPRESSION (TPC-DS's
    # ``ORDER BY sum(x) DESC``): map each original item's structure to
    # its output name for structural resolution below.
    repr_to_name: Dict[str, str] = {}

    if aggregate_query:
        if star:
            raise SqlError("SELECT * cannot be combined with GROUP "
                           "BY/aggregates; list the outputs")
        # Group keys: plain columns, or references to computed select
        # aliases (SELECT year(d) AS y ... GROUP BY y) which materialize
        # as with_column first.
        alias_exprs = {a: e for a, e in items
                       if a is not None and e is not None
                       and not _contains_window(e)
                       and not _contains_agg(e)}
        keys: List[str] = []
        for k in group_by:
            if isinstance(k, Col):
                if k.name in alias_exprs and not (
                        isinstance(alias_exprs[k.name], Col)
                        and alias_exprs[k.name].name == k.name):
                    # Renaming aliases (x AS g) materialize too — the
                    # group key must exist under the alias name.
                    ds = ds.with_column(k.name, alias_exprs[k.name])
                keys.append(k.name)
            else:
                raise SqlError(
                    f"GROUP BY keys must be column names or select "
                    f"aliases, got {k!r}")
        agg_specs: Dict[str, tuple] = {}
        hidden = [0]

        def agg_name(call: _AggCall, alias: Optional[str]) -> str:
            if alias is not None:
                name = alias
            else:
                name = f"__agg{hidden[0]}"
                hidden[0] += 1
            inp = "" if call.func == "count_all" else (
                call.child.name if isinstance(call.child, Col) else call.child)
            agg_specs[name] = (inp, call.func)
            return name

        def bind_window(w: _WindowCall) -> _WindowCall:
            """A window in an aggregate query runs over the GROUPED
            rows; an aggregate VALUE (sum(sum(x)) OVER ...) becomes a
            hidden aggregate output the window then reads."""
            if isinstance(w.value, _AggCall):
                hidden_name = agg_name(w.value, None)
                return _WindowCall(w.func, hidden_name, w.partition_by,
                                   w.order_by, w.offset, frame=w.frame)
            return w

        for alias, e in items:
            if e is None:
                continue
            if isinstance(e, _WindowCall):
                if alias is None:
                    raise SqlError("Window select items need AS aliases")
                windows_to_apply.append((alias, bind_window(e)))
                out_items.append((alias, None))
                repr_to_name[repr(e)] = alias
                continue
            if isinstance(e, _AggCall):
                name = agg_name(e, alias)
                out_items.append((name, None))
                repr_to_name[repr(e)] = name
                continue
            if _contains_window(e):
                # Window nested in an expression (TPC-DS q12's
                # ``agg*100/sum(sum(x)) over (...)`` ratio): each window
                # materializes as a hidden analytic column; the final
                # Compute (which runs after the windows apply) reads it.
                if alias is None:
                    raise SqlError(
                        f"Computed window select items need AS "
                        f"aliases: {e!r}")

                def repl(x):
                    if isinstance(x, _WindowCall):
                        hidden_w = f"__win{len(windows_to_apply)}"
                        windows_to_apply.append((hidden_w,
                                                 bind_window(x)))
                        return Col(hidden_w)
                    if isinstance(x, _AggCall):
                        return Col(agg_name(x, None))
                    return x

                out_items.append((alias, _map(e, repl)))
                repr_to_name[repr(e)] = alias
                continue
            if _contains_agg(e):
                # Unaliased computed aggregates auto-name positionally
                # (scalar subqueries read the single output by position:
                # TPC-DS q1's ``SELECT avg(x) * 1.2``).
                alias = alias or f"_c{len(out_items)}"
                new_e = _map(e, lambda x: Col(agg_name(x, None))
                             if isinstance(x, _AggCall) else x)
                _reject_markers(new_e, "SELECT expressions",
                                (_WindowCall,))
                out_items.append((alias, new_e))
                repr_to_name[repr(e)] = alias
                continue
            # Non-aggregate item: must be a group key (or its alias) —
            # possibly RENAMED in the output (``sr_customer_sk AS
            # ctr_customer_sk ... GROUP BY sr_customer_sk``, TPC-DS q1).
            if isinstance(e, Col) and e.name in keys:
                name = alias or e.name
                out_items.append(
                    (name, None if name == e.name else e))
                repr_to_name[repr(e)] = name
                continue
            name = alias or (e.name if isinstance(e, Col) else None)
            if name is None or name not in keys:
                raise SqlError(
                    f"Select item {e!r} is neither aggregated nor a "
                    f"GROUP BY key")
            out_items.append((name, None))
            repr_to_name[repr(e)] = name
        if not keys:
            ds = ds.agg(**agg_specs)
        else:
            ds = ds.group_by(*keys).agg(**agg_specs)
        if having is not None:
            _reject_markers(having, "HAVING", (_WindowCall,))

            def map_having(x):
                if isinstance(x, _AggCall):
                    # Match an existing SELECT output structurally; a
                    # HAVING-only aggregate is deliberately rejected (it
                    # would need a hidden output threaded through the
                    # final projection) — alias the aggregate in SELECT.
                    for name, (inp, func) in agg_specs.items():
                        want = "" if x.func == "count_all" else (
                            x.child.name if isinstance(x.child, Col)
                            else x.child)
                        if func == x.func and repr(inp) == repr(want):
                            return Col(name)
                    raise SqlError(
                        f"HAVING aggregate {x!r} must also appear in the "
                        f"SELECT list")
                return x

            ds = ds.filter(_map(having, map_having))
    else:
        if having is not None:
            raise SqlError("HAVING without GROUP BY/aggregates")
        if not star:
            for alias, e in items:
                if e is None:
                    continue
                if isinstance(e, _WindowCall):
                    if alias is None:
                        raise SqlError(
                            "Window select items need AS aliases")
                    if isinstance(e.value, _AggCall):
                        raise SqlError(
                            "Window over an aggregate needs a GROUP BY")
                    windows_to_apply.append((alias, e))
                    out_items.append((alias, None))
                elif isinstance(e, Col) and alias is None:
                    out_items.append((e.name, None))
                elif _contains_window(e):
                    if alias is None:
                        raise SqlError(
                            f"Computed window select items need AS "
                            f"aliases: {e!r}")

                    def repl(x):
                        if isinstance(x, _WindowCall):
                            if isinstance(x.value, _AggCall):
                                raise SqlError("Window over an "
                                               "aggregate needs a "
                                               "GROUP BY")
                            hidden_w = f"__win{len(windows_to_apply)}"
                            windows_to_apply.append((hidden_w, x))
                            return Col(hidden_w)
                        return x

                    out_items.append((alias, _map(e, repl)))
                else:
                    _reject_markers(e, "SELECT expressions",
                                    (_WindowCall,))
                    # Unaliased computed items auto-name (Spark names
                    # them after the expression text; `_c<i>` is stabler).
                    out_items.append((alias or f"_c{len(out_items)}", e))

    for alias, w in windows_to_apply:
        ds = ds.with_window(alias, w.func, partition_by=w.partition_by,
                            order_by=w.order_by, value=w.value,
                            offset=w.offset, frame=w.frame)

    # Resolve ORDER BY before the output projection: keys may be select
    # outputs, expressions matching select items (TPC-DS's ``ORDER BY
    # sum(x) DESC``), or columns available pre-projection but not
    # selected (q12 orders by the group key i_item_id without selecting
    # it) — those thread through as HIDDEN outputs and drop after the
    # sort.
    sort_keys: List[Tuple[str, bool]] = []
    hidden_sort_cols: List[str] = []
    if order_by:
        out_names = {n for n, _e in out_items}
        for k, asc in order_by:
            if isinstance(k, str):
                name = k
            else:
                name = repr_to_name.get(repr(k))
                if name is None:
                    raise SqlError(
                        f"ORDER BY expression {k!r} must match a select "
                        f"output; alias it in SELECT and order by the "
                        f"alias")
            if not star and out_items and name not in out_names:
                try:
                    available = name in ds.columns
                except Exception:
                    available = False
                if not available:
                    raise SqlError(
                        f"ORDER BY key {name!r} is neither a select "
                        f"output nor an available column")
                if distinct:
                    raise SqlError(
                        f"ORDER BY {name!r} with DISTINCT must be a "
                        f"select output")
                out_items.append((name, None))
                out_names.add(name)
                hidden_sort_cols.append(name)
            sort_keys.append((name, asc))

    if not star and out_items:
        names = [n for n, _e in out_items]
        if len(set(names)) != len(names):
            raise SqlError(f"Duplicate select output names: {names}")
        if all(e is None for _n, e in out_items):
            # Skip a no-op projection (SELECT exactly the current
            # output, in order): keeps plans identical to DSL forms
            # that never wrote a select — and leaves subquery plans as
            # bare Aggregates, the shape the correlated-scalar rewrite
            # requires.
            try:
                noop = ds.columns == names
            except Exception:
                noop = False
            if not noop:
                ds = ds.select(*names)
        else:
            # Computed outputs interleave with plain ones: build the
            # Compute in SELECT-LIST order (Dataset.select's
            # names-then-keywords signature would reorder them).
            from hyperspace_tpu.dataset import Dataset
            from hyperspace_tpu.plan.nodes import Compute

            exprs = [(n, Col(n) if e is None else e) for n, e in out_items]
            ds = Dataset(Compute(exprs, ds.plan), ds.session)
    if distinct:
        ds = ds.distinct()
    if sort_keys:
        ds = ds.sort(*sort_keys)
        if hidden_sort_cols:
            keep = [n for n, _e in out_items
                    if n not in hidden_sort_cols]
            ds = ds.select(*keep)
    if limit is not None:
        ds = ds.limit(limit)
    return ds


def _reject_markers(e: Expr, where: str, kinds=None) -> None:
    from hyperspace_tpu.plan.subquery import _walk_exprs

    kinds = kinds or (_AggCall, _WindowCall)

    def check(x):
        if isinstance(x, kinds):
            raise SqlError(f"Aggregate/window calls are not allowed in "
                           f"{where} (window calls must be top-level "
                           f"select items)")
    _walk_exprs(e, check)


def _fold_const_date(e: Expr):
    """datetime.date value of a constant date expression (DATE literal
    or cast of a string literal to date), else None."""
    if isinstance(e, Lit) and isinstance(e.value, datetime.date):
        return e.value
    if isinstance(e, Cast) and str(e.type_name).lower() in ("date",
                                                            "date32") \
            and isinstance(e.child, Lit) and isinstance(e.child.value,
                                                        str):
        try:
            return datetime.date.fromisoformat(e.child.value)
        except ValueError:
            return None
    return None


class _CommaJoin:
    """Placeholder for comma-separated FROM sources; resolved against
    the WHERE conjuncts by _assemble_comma_join."""

    def __init__(self, items) -> None:
        self.items = items


def _split_conjuncts(e: Expr) -> List[Expr]:
    if isinstance(e, And):
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


def _assemble_comma_join(p: "_Parser", items, where):
    """Build the inner-join tree for ``FROM a, b, c WHERE ...`` from the
    WHERE clause's column-equality conjuncts (classic implicit-join SQL,
    the TPC-DS corpus style): each step joins one not-yet-connected
    source through an equi predicate; everything else stays a filter
    above the joins.  Pure cross joins are rejected — the engine
    executes equi-joins."""
    if where is None:
        p.fail("comma-separated FROM needs WHERE equi-join predicates "
               "(cross joins are not supported)")
    cols_of = []
    for it in items:
        try:
            cols_of.append(set(it.columns))
        except Exception:
            p.fail("comma-joined sources need resolvable schemas")

    def owner(name: str):
        hits = [i for i, cs in enumerate(cols_of) if name in cs]
        return hits[0] if len(hits) == 1 else None

    conjuncts = _split_conjuncts(where)
    used: set = set()
    joined = {0}
    ds = items[0]
    while len(joined) < len(items):
        progressed = False
        for ci, c in enumerate(conjuncts):
            if ci in used:
                continue
            if not (isinstance(c, BinOp) and c.op == "=="
                    and isinstance(c.left, Col)
                    and isinstance(c.right, Col)):
                continue
            oa, ob = owner(c.left.name), owner(c.right.name)
            if oa is None or ob is None:
                continue
            if (oa in joined) == (ob in joined):
                continue
            new = ob if oa in joined else oa
            ds = ds.join(items[new], c, how="inner")
            joined.add(new)
            used.add(ci)
            progressed = True
            break
        if not progressed:
            # Distinguish the REAL limitation: an UNALIASED duplicate of
            # a table leaves every shared column ambiguous to owner(),
            # so no equi conjunct can ever connect them.  (An ALIASED
            # duplicate is lifted into an independent renamed instance
            # by parse_source and never reaches this branch.)
            pending = [i for i in range(len(items)) if i not in joined]
            if any(cols_of[i] == cols_of[j]
                   for i in pending for j in range(len(items)) if i != j):
                p.fail(
                    "comma-style self-join needs an alias on each "
                    "occurrence (FROM emp e, emp m): identical column "
                    "sets make the join columns ambiguous")
            p.fail(
                "comma-separated FROM requires WHERE equi-join "
                "predicates connecting every table (cross joins are "
                "not supported)")
    remaining = None
    for ci, c in enumerate(conjuncts):
        if ci in used:
            continue
        remaining = c if remaining is None else And(remaining, c)
    return ds, remaining


def _align_positional(op_name: str, ds, nxt):
    """Spark SQL resolves set operations BY POSITION: the second
    branch's columns are renamed to the first branch's names pairwise,
    regardless of their own names."""
    prev_cols, next_cols = None, None
    try:
        prev_cols, next_cols = ds.columns, nxt.columns
    except Exception:
        return nxt  # unresolvable schema: let execution surface it
    if len(prev_cols) != len(next_cols):
        raise SqlError(
            f"{op_name} branches must produce the same number of "
            f"columns: {prev_cols} vs {next_cols}")
    if len(set(prev_cols)) != len(prev_cols):
        raise SqlError(
            f"{op_name} over duplicate column names is not "
            f"supported: {prev_cols}; alias them apart")
    if list(prev_cols) != list(next_cols):
        nxt = nxt.select(**{pn: Col(nc) for pn, nc
                            in zip(prev_cols, next_cols)})
    return nxt


def _parse_intersect_chain(p: "_Parser", allow_tail: bool):
    """select (INTERSECT select)* — INTERSECT binds tighter than
    UNION/EXCEPT, per the SQL grammar."""
    ds = p.parse_select(allow_tail=allow_tail)
    while p.take_kw("INTERSECT"):
        if p.take_kw("ALL"):
            p.fail("INTERSECT ALL is not supported; use INTERSECT")
        p.take_kw("DISTINCT")
        branch = p.fork()
        nxt = branch.parse_select(allow_tail=False)
        p.i = branch.i
        ds = ds.intersect(_align_positional("INTERSECT", ds, nxt))
    return ds


def _parse_query(p: "_Parser"):
    """Full query expression: set-operation chain plus the trailing
    ORDER BY / LIMIT that binds the WHOLE chain (SQL)."""
    has_setop = _has_top_level_setop(p)
    ds = _parse_intersect_chain(p, allow_tail=not has_setop)
    while True:
        if p.take_kw("UNION"):
            # SQL set semantics: bare UNION dedups the accumulated
            # result; UNION ALL keeps bags.  Left-associative.
            dedup = True
            if p.take_kw("ALL"):
                dedup = False
            else:
                p.take_kw("DISTINCT")
            # Each branch is its own select scope (fresh sources /
            # aliases, like the INTERSECT fork): `FROM orders` in both
            # branches is two scans, not a duplicate registration.
            branch = p.fork()
            nxt = _parse_intersect_chain(branch, allow_tail=False)
            p.i = branch.i
            ds = ds.union(_align_positional("UNION", ds, nxt))
            if dedup:
                ds = ds.distinct()
        elif p.take_kw("EXCEPT") or p.take_kw("MINUS"):
            if p.take_kw("ALL"):
                p.fail("EXCEPT ALL is not supported; use EXCEPT")
            p.take_kw("DISTINCT")
            branch = p.fork()
            nxt = _parse_intersect_chain(branch, allow_tail=False)
            p.i = branch.i
            ds = ds.subtract(_align_positional("EXCEPT", ds, nxt))
        else:
            break
    if has_setop:
        if p.take_kw("ORDER"):
            p.expect_kw("BY")
            keys = p.parse_order_keys()
            if any(not isinstance(k, str) for k, _a in keys):
                p.fail("ORDER BY after a set operation must use output "
                       "column names")
            ds = ds.sort(*keys)
        if p.take_kw("LIMIT"):
            ds = ds.limit(p.parse_limit_count())
    return ds


def sql(session, text: str, tables: Dict[str, Any]):
    """Parse ``text`` and lower it to a Dataset against ``session``.

    ``tables`` maps SQL table names to Datasets or parquet directory
    paths (the FROM resolution — the engine has no catalog).  Supports
    WITH (common table expressions), UNION [ALL], INTERSECT, and
    EXCEPT/MINUS — the constructs the reference's TPC-DS plan-stability
    corpus leans on (goldstandard/TPCDSBase.scala:35; q51's
    ``WITH ... AS`` shape, q14's INTERSECT)."""
    p = _Parser(text, session, dict(tables))
    if p.take_kw("WITH"):
        if p.take_kw("RECURSIVE"):
            p.fail("WITH RECURSIVE is not supported")
        while True:
            t = p.next()
            if t[0] not in _NAME_KINDS:
                p.fail("expected a CTE name after WITH")
            cte_name = t[1]
            p.expect_kw("AS")
            p.expect_op("(")
            # fork() shares the token stream — re-tokenizing the whole
            # SQL text per CTE (the old _Parser(p.text, ...) constructor
            # route) cost one full lex per CTE for nothing.  The body
            # needs its OWN tables snapshot: earlier CTEs are visible,
            # its registrations must not leak back.
            body = p.fork()
            body.tables = dict(p.tables)
            cte_ds = _parse_query(body)
            p.i = body.i
            p.expect_op(")")
            # Later CTEs and the main query see this one by name;
            # same-named external tables are shadowed (SQL scoping).
            p.tables[cte_name] = cte_ds
            if not p.take_op(","):
                break
    ds = _parse_query(p)
    while p.take_op(";"):  # .sql files commonly end with a semicolon
        pass
    t = p.peek()
    if t[0] != "eof":
        p.fail("unexpected trailing input")
    return ds


_SETOP_KWS = ("UNION", "INTERSECT", "EXCEPT", "MINUS")


def _has_top_level_setop(p: "_Parser") -> bool:
    """Any set operator at THIS query's nesting level — the scan stops
    where the enclosing parenthesis closes, so a parenthesized subquery
    context never sees its parent's operators."""
    depth = 0
    for kind, val, _pos in p.tokens[p.i:]:
        if kind == "op" and val == "(":
            depth += 1
        elif kind == "op" and val == ")":
            depth -= 1
            if depth < 0:
                return False
        elif depth == 0 and kind == "ident" and val.upper() in _SETOP_KWS:
            return True
    return False
