"""Top-level user API.

Reference contract: Hyperspace.scala:26-166 — createIndex/deleteIndex/
restoreIndex/vacuumIndex/refreshIndex/optimizeIndex/cancel/indexes/index/
explain, each delegating to the IndexCollectionManager; ``explain`` renders
the with/without-index plan comparison (PlanAnalyzer).
"""

from __future__ import annotations


import pyarrow as pa

from hyperspace_tpu.dataset import Dataset
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.session import HyperspaceSession


class Hyperspace:
    def __init__(self, session: HyperspaceSession) -> None:
        self.session = session
        self.index_manager = session.index_collection_manager

    def create_index(self, dataset: Dataset, config: IndexConfig) -> None:
        self.index_manager.create(dataset, config)

    def delete_index(self, name: str) -> None:
        self.index_manager.delete(name)

    def restore_index(self, name: str) -> None:
        self.index_manager.restore(name)

    def vacuum_index(self, name: str) -> None:
        self.index_manager.vacuum(name)

    def refresh_index(self, name: str, mode: str = "full"):
        """Modes: ``full`` (rebuild), ``incremental``, ``quick``
        (metadata-only), and ``repair`` — rebuild only the buckets whose
        files are quarantined, then clear their quarantine records
        (docs/15-integrity.md).  Returns a
        :class:`~hyperspace_tpu.actions.refresh.RefreshSummary`:
        appended/deleted file counts the diff saw, the mode that ran,
        the committed log version — or ``outcome="noop"`` when the
        source was unchanged (a benign no-op, not an exception)."""
        return self.index_manager.refresh(name, mode)

    def verify_index(self, name: str, mode: str = "quick") -> pa.Table:
        """Scrub ``name``'s index data files against its log entry and
        return the per-file report (columns: file, status, detail,
        quarantined).  ``quick`` checks existence/size/mtime; ``full``
        additionally re-reads every file and re-hashes it against the
        content digest recorded at write time.  Damaged files are
        QUARANTINED: later queries keep using the index with only the
        damaged buckets read from source, and
        ``refresh_index(name, mode="repair")`` rebuilds them."""
        return self.index_manager.verify(name, mode)

    def optimize_index(self, name: str, mode: str = "quick"):
        """Compact small index files bucket-wise (``quick`` merges only
        files below ``hyperspace.index.optimize.fileSizeThreshold``,
        ``full`` rewrites every bucket).  Returns an
        :class:`~hyperspace_tpu.actions.optimize.OptimizeSummary`:
        files/buckets compacted, files written, the committed log
        version — or ``outcome="noop"`` when no bucket held mergeable
        files (a benign no-op, not an exception)."""
        return self.index_manager.optimize(name, mode)

    def cancel(self, name: str) -> None:
        self.index_manager.cancel(name)

    def indexes(self) -> pa.Table:
        return self.index_manager.indexes()

    def index(self, name: str) -> pa.Table:
        from hyperspace_tpu.index.statistics import index_statistics_table

        entry = self.index_manager.get_index(name)
        return index_statistics_table([entry] if entry else [], extended=True,
                                      path_resolver=self.index_manager
                                      .path_resolver)

    def explain(self, dataset: Dataset, verbose: bool = False) -> str:
        from hyperspace_tpu.plananalysis.explain import explain_string

        return explain_string(dataset, self.session, verbose=verbose)

    # -- the index advisor (docs/17-advisor.md) -----------------------------
    def whatif(self, dataset: Dataset, candidates):
        """Plan ``dataset`` as if ``candidates`` (IndexConfig specs or
        hypothetical entries) were built — the real optimizer's plan
        diff plus an estimated bytes-scanned delta, with zero files
        written and nothing executed.  Returns a
        :class:`~hyperspace_tpu.advisor.hypothetical.WhatIfReport`."""
        from hyperspace_tpu.advisor.hypothetical import whatif

        return whatif(self.session, dataset, candidates)

    def captured_workload(self) -> pa.Table:
        """The captured query-fingerprint workload
        (``hyperspace.advisor.capture.enabled``) as one row per distinct
        query shape: hit count, the filter/join/group/projected columns,
        measured bytes scanned."""
        from hyperspace_tpu.advisor.workload import workload_table

        return workload_table(self.session.conf)

    def clear_captured_workload(self) -> None:
        from hyperspace_tpu.advisor.workload import clear

        clear(self.session.conf)

    def recommend_indexes(self, top_k: int = 5) -> pa.Table:
        """Rank candidate covering indexes for the CAPTURED workload:
        columns ``candidate``, ``relation``, ``indexedColumns``,
        ``includedColumns``, ``supportingQueries``, ``supportingHits``,
        ``estBenefitBytes``, ``estBuildCostBytes``, ``score`` — benefit
        is workload-weighted measured-minus-estimated bytes, cost is one
        covered-column pass over the source (the model in
        advisor/candidates.py; docs/17-advisor.md)."""
        from hyperspace_tpu.advisor.recommend import recommend_indexes

        return recommend_indexes(self.session, top_k)

    def apply_recommendations(self, top_k: int = 1) -> list:
        """Build the top ``top_k`` recommendations through the normal
        ``create_index`` path (same validation/log protocol/build);
        returns the index names built.  Candidates an existing ACTIVE
        index already covers are skipped."""
        from hyperspace_tpu.advisor.recommend import apply_recommendations

        return apply_recommendations(self.session, top_k)

    def last_build_report(self):
        """The :class:`~hyperspace_tpu.telemetry.build_report.BuildReport`
        of the most recent action run through this session (create /
        refresh / repair / optimize / ...): per-phase wall seconds
        (read → route → sort → spill → finalize), device-compute vs host
        split, bytes moved, spill run/file counts, and peak host RSS /
        live device-buffer bytes.  None before the first action.  See
        docs/16-observability.md."""
        report = self.session.last_build_report_value
        if report is not None:
            return report
        from hyperspace_tpu.telemetry.build_report import last_report

        return last_report()

    def perf_history(self, index: str = None, section: str = None,
                     limit: int = None) -> pa.Table:
        """The persistent perf ledger (telemetry/perf_ledger.py) as an
        arrow table — one row per recorded action/bench-section run under
        ``<systemPath>/_hyperspace_perf``, oldest first, readable over
        both LogStore backends.  Columns: key, kind, name, ts,
        wallSeconds, outcome, phasesJson, bytesWritten, spillBytes,
        recordJson (the full record).

        Filters (also on the interop ``perf_history`` verb): ``index``
        keeps action records for that index, ``section`` keeps bench
        records for that section, ``limit`` keeps the most recent N
        after filtering — callers used to re-filter raw records by
        hand."""
        from hyperspace_tpu.telemetry.perf_ledger import history_table

        return history_table(self.session.conf, index=index,
                             section=section, limit=limit)

    # -- timeline profiler + health doctor (docs/16-observability.md) -------
    def export_timeline(self, path: str, trace_id: str = None,
                        ledger_key: str = None) -> str:
        """Write a Perfetto/Chrome trace-event JSON file to ``path``
        (load it in ui.perfetto.dev or chrome://tracing).

        Default: the live timeline ring — build-phase / executor /
        device-kernel lanes plus the memory counter track
        (``hyperspace.system.timeline.enabled`` must be on to have
        recorded anything) and the most recent query's span tree when
        one is attached.  ``trace_id`` instead reconstructs from that
        flight-recorder retained record's span tree; ``ledger_key``
        reconstructs from that perf-ledger record's phase seconds —
        both work after the fact, without the ring."""
        from hyperspace_tpu.telemetry import timeline

        if trace_id is not None:
            from hyperspace_tpu.telemetry import flight_recorder

            rec = flight_recorder.recorder().find(trace_id.lower())
            if rec is None:
                raise ValueError(
                    f"no retained flight record for trace id {trace_id!r}")
            timeline.export_chrome_trace(
                path, intervals=(), memory_samples=(),
                span_roots=[rec["spans"]] if rec.get("spans") else ())
            return path
        if ledger_key is not None:
            import json as _json

            from hyperspace_tpu.telemetry import perf_ledger

            for rec in perf_ledger.records(self.session.conf):
                if rec.get("key") == ledger_key:
                    events = timeline.ledger_to_trace_events(rec)
                    from hyperspace_tpu.telemetry.trace import span

                    with span("timeline.export", path=path) as sp:
                        # hslint: allow[io-seam] user-chosen export path
                        with open(path, "w", encoding="utf-8") as f:
                            _json.dump({"traceEvents": events,
                                        "displayTimeUnit": "ms"}, f)
                        sp.set(events=len(events))
                    return path
            raise ValueError(f"no perf-ledger record {ledger_key!r}")
        roots = []
        rep = self.session.last_run_report_value
        if rep is not None and rep.root_span is not None:
            roots.append(rep.root_span)
        timeline.export_chrome_trace(path, span_roots=roots)
        return path

    def doctor(self, fleet: bool = False):
        """One aggregated health report over everything the telemetry
        stack knows (telemetry/doctor.py): quarantine/containment state,
        per-index staleness via the lifecycle change detector, daemon
        failure backoffs, the perf-ledger trend, serving shed rate and
        latency-SLO burn, degraded events, per-device kernel-ms skew —
        graded ok/warn/crit, worst check wins, published as the
        ``health.status`` gauge.  Cheap (stat-level listings and
        process counters only), also served by the inline interop
        ``doctor`` verb so it works during overload.

        ``fleet=True`` adds the CLUSTER checks over the published
        heartbeats (telemetry/fleet.py): a stale heartbeat — a dead or
        hung process — is crit, more than one lifecycle daemon warns,
        the aggregate shed-ratio/SLO burn and cross-process/cross-device
        kernel-ms skew grade over the MERGED counters; their worst grade
        is published as ``health.fleet.status``."""
        from hyperspace_tpu.telemetry.doctor import doctor

        return doctor(self.session, fleet=fleet)

    # -- flight recorder / diagnostics (docs/16-observability.md) -----------
    def slow_queries(self, fleet: bool = False) -> pa.Table:
        """The flight recorder's retained ring as an arrow table, oldest
        first: slow (>= ``hyperspace.serving.flightRecorder.slowMs``),
        error, deadline-expired, and shed requests are always kept,
        healthy ones sampled 1-in-N.  Columns: ts, traceId, requestId,
        kind, outcome, latencyMs, queueWaitMs, slow, reason, error,
        recordJson (the full record: span tree + run report).  The same
        table the interop ``slow_queries`` verb serves.

        ``fleet=True`` federates across the fleet (telemetry/fleet.py):
        the union of this process's ring, every published heartbeat's
        interesting tail (live processes), and the persisted diagnostics
        bundles (drained ones), deduplicated, with a ``process`` column
        naming where each request ran."""
        if fleet:
            from hyperspace_tpu.telemetry.fleet import (
                fleet_slow_queries_table,
            )

            return fleet_slow_queries_table(self.session.conf)
        from hyperspace_tpu.telemetry.flight_recorder import (
            slow_queries_table,
        )

        return slow_queries_table(self.session.conf)

    def trace(self, trace_id: str, fleet: bool = False):
        """The full retained flight record (dict) for ``trace_id`` — the
        id every wire response echoes and every ``QueryFailedError``
        carries — or None when no record for it is retained.
        ``fleet=True`` resolves across the fleet too: the local ring
        first, then every published heartbeat's interesting tail, then
        the persisted diagnostics bundles — so a slow query served by
        ANOTHER process is found from here by its echoed id."""
        if fleet:
            from hyperspace_tpu.telemetry.fleet import find_trace

            return find_trace(self.session.conf, trace_id)
        from hyperspace_tpu.telemetry import flight_recorder

        return flight_recorder.recorder().find(trace_id.lower())

    # -- fleet telemetry federation (docs/16-observability.md) ---------------
    def fleet_status(self) -> pa.Table:
        """Every published fleet heartbeat as an arrow table
        (telemetry/fleet.py): process identity, host, pid, role
        (``server``/``daemon``/``client``), last published health grade,
        heartbeat age, freshness, and the carried snapshot.  The same
        table the inline interop ``fleet_status`` verb serves — it works
        during overload, exactly when an operator asks "which of my
        servers is sick"."""
        from hyperspace_tpu.telemetry.fleet import fleet_status_table

        return fleet_status_table(self.session.conf)

    def fleet_metrics(self) -> dict:
        """The fleet-merged metrics view over every fresh heartbeat plus
        this process's live registry: counters summed, gauges kept
        per-process (``name -> {process: value}``), fixed-bucket
        histograms merged by bucket-sum with exemplar carry.  Keys:
        ``processes``, ``counters``, ``gauges``, ``histograms``
        (docs/16-observability.md has the merge semantics)."""
        from hyperspace_tpu.telemetry.fleet import fleet_metrics

        return fleet_metrics(self.session.conf)

    def start_fleet_telemetry(self):
        """Start this session's heartbeat publisher thread
        (``hyperspace.fleet.telemetry.enabled`` must be true; it
        publishes every ``hyperspace.fleet.telemetry.publishIntervalS``
        seconds).  Sessions, ``QueryServer``, and the lifecycle daemon
        auto-start it when the conf gate is on — this is the explicit
        handle for conf set after construction.  Returns the
        :class:`~hyperspace_tpu.telemetry.fleet.FleetPublisher`."""
        from hyperspace_tpu.telemetry.fleet import publisher_for

        return publisher_for(self.session).start()

    def stop_fleet_telemetry(self) -> None:
        """Stop the heartbeat publisher thread (idempotent)."""
        from hyperspace_tpu.telemetry.fleet import publisher_for

        publisher_for(self.session).stop()

    # -- SLO alerting (docs/16-observability.md) ----------------------------
    def alerts(self, fleet: bool = False) -> pa.Table:
        """Current SLO alert states (telemetry/alerts.py), one row per
        declared objective — availability, latency, staleness,
        build-claim liveness — with state (pending/firing/resolved),
        severity, the since timestamp, and the incident-bundle key
        captured at the moment of firing.  The same table the inline
        interop ``alerts`` verb serves, so it answers during overload.

        ``fleet=True`` federates: every fresh heartbeat's carried
        active alerts ride along with a ``process`` column attributing
        each row — "which server is paging" in one call."""
        from hyperspace_tpu.telemetry.alerts import alerts_table

        return alerts_table(self.session, fleet=fleet)

    def alert_history(self) -> pa.Table:
        """The persisted alert transition log as an arrow table, oldest
        first — every state change (pending → firing → resolved) the
        engine recorded under ``<systemPath>/_hyperspace_alerts``
        through the LogStore seam, restart-proof across both
        backends."""
        from hyperspace_tpu.telemetry.alerts import history_table

        return history_table(self.session.conf)

    def start_alerting(self):
        """Start the SLO evaluator thread
        (``hyperspace.alerts.enabled`` must be true; evaluation rides
        the fleet-heartbeat cadence unless
        ``hyperspace.alerts.intervalS`` overrides it).  Returns the
        :class:`~hyperspace_tpu.telemetry.alerts.AlertEngine`."""
        from hyperspace_tpu.telemetry.alerts import engine_for

        return engine_for(self.session).start()

    def stop_alerting(self) -> None:
        """Stop the SLO evaluator thread (idempotent; the persisted
        alert state survives for the next engine)."""
        from hyperspace_tpu.telemetry.alerts import engine_for

        engine_for(self.session).stop()

    def diagnostics(self) -> dict:
        """The live diagnostics bundle: the flight recorder's retained
        ring, a metrics snapshot, and the recent perf-ledger tail — the
        exact payload :meth:`dump_diagnostics` persists."""
        from hyperspace_tpu.telemetry.flight_recorder import (
            diagnostics_bundle,
        )

        return diagnostics_bundle(self.session.conf)

    def dump_diagnostics(self):
        """Persist :meth:`diagnostics` as a bundle through the LogStore
        seam under ``<systemPath>/_hyperspace_diagnostics`` (both
        backends, restart-proof, bounded by
        ``hyperspace.serving.flightRecorder.maxBundles``); returns the
        bundle key, or None when disabled/failed.  ``QueryServer``'s
        drain (SIGTERM) does this automatically."""
        from hyperspace_tpu.telemetry.flight_recorder import (
            dump_diagnostics,
        )

        return dump_diagnostics(self.session.conf)

    def diagnostics_bundles(self) -> list:
        """Every persisted diagnostics bundle, oldest first — how "what
        happened yesterday" survives a restart (docs/10-faq.md)."""
        from hyperspace_tpu.telemetry.flight_recorder import bundles

        return bundles(self.session.conf)

    # -- autonomous lifecycle (docs/19-lifecycle.md) ------------------------
    def maintenance_cycle(self) -> list:
        """Run ONE maintenance cycle synchronously — the daemon's
        detect → decide → act → journal step, drivable without the
        daemon thread (tests, serving integration, cron).  Returns the
        journal records written this cycle (one per decision, including
        ``kind=none`` "did nothing" records)."""
        from hyperspace_tpu.lifecycle.daemon import daemon_for

        return daemon_for(self.session).run_once()

    def start_maintenance(self):
        """Start the opt-in maintenance daemon thread
        (``hyperspace.lifecycle.enabled`` must be true; it polls every
        ``hyperspace.lifecycle.intervalS`` seconds).  Returns the
        :class:`~hyperspace_tpu.lifecycle.daemon.MaintenanceDaemon`."""
        from hyperspace_tpu.lifecycle.daemon import daemon_for

        return daemon_for(self.session).start()

    def stop_maintenance(self) -> None:
        """Stop the maintenance daemon thread (idempotent; the session's
        daemon object survives for later restarts)."""
        from hyperspace_tpu.lifecycle.daemon import daemon_for

        daemon_for(self.session).stop()

    def lifecycle_history(self) -> pa.Table:
        """The lifecycle decision journal as an arrow table, oldest
        first — every daemon/maintenance-cycle decision (refresh mode
        chosen, advisor build/drop, backoff skip, or "did nothing, "
        "here's why"), persisted under
        ``<systemPath>/_hyperspace_lifecycle`` through the LogStore
        seam, restart-proof.  The same table the interop ``lifecycle``
        verb serves (docs/19-lifecycle.md has the schema)."""
        from hyperspace_tpu.lifecycle.journal import history_table

        return history_table(self.session.conf)

    def metrics(self) -> dict:
        """Point-in-time snapshot of the process-wide metrics registry
        (telemetry/metrics.py): counters like ``io.retry.attempts``,
        ``log.cas.conflicts``, ``rule.filter.applied``,
        ``degraded.fallbacks``, ``scrub.files_flagged``, and derived
        ratios like ``cache.device.hit_ratio`` — the operational
        aggregate across every query and action this process ran
        (docs/16-observability.md has the catalog)."""
        from hyperspace_tpu.telemetry import metrics as m

        return m.snapshot()

    def metrics_text(self) -> str:
        """The same registry as a Prometheus-style text exposition —
        scrape it, or drop it in a log line."""
        from hyperspace_tpu.telemetry import metrics as m

        return m.registry().render_prometheus()

    def reset_metrics(self) -> None:
        """Zero every series (tests; a bench section isolating deltas)."""
        from hyperspace_tpu.telemetry import metrics as m

        m.reset()
