"""Top-level user API.

Reference contract: Hyperspace.scala:26-166 — createIndex/deleteIndex/
restoreIndex/vacuumIndex/refreshIndex/optimizeIndex/cancel/indexes/index/
explain, each delegating to the IndexCollectionManager; ``explain`` renders
the with/without-index plan comparison (PlanAnalyzer).
"""

from __future__ import annotations

from typing import Optional

import pyarrow as pa

from hyperspace_tpu.dataset import Dataset
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.index.manager import IndexCollectionManager
from hyperspace_tpu.session import HyperspaceSession


class Hyperspace:
    def __init__(self, session: HyperspaceSession) -> None:
        self.session = session
        self.index_manager = session.index_collection_manager

    def create_index(self, dataset: Dataset, config: IndexConfig) -> None:
        self.index_manager.create(dataset, config)

    def delete_index(self, name: str) -> None:
        self.index_manager.delete(name)

    def restore_index(self, name: str) -> None:
        self.index_manager.restore(name)

    def vacuum_index(self, name: str) -> None:
        self.index_manager.vacuum(name)

    def refresh_index(self, name: str, mode: str = "full") -> None:
        """Modes: ``full`` (rebuild), ``incremental``, ``quick``
        (metadata-only), and ``repair`` — rebuild only the buckets whose
        files are quarantined, then clear their quarantine records
        (docs/15-integrity.md)."""
        self.index_manager.refresh(name, mode)

    def verify_index(self, name: str, mode: str = "quick") -> pa.Table:
        """Scrub ``name``'s index data files against its log entry and
        return the per-file report (columns: file, status, detail,
        quarantined).  ``quick`` checks existence/size/mtime; ``full``
        additionally re-reads every file and re-hashes it against the
        content digest recorded at write time.  Damaged files are
        QUARANTINED: later queries keep using the index with only the
        damaged buckets read from source, and
        ``refresh_index(name, mode="repair")`` rebuilds them."""
        return self.index_manager.verify(name, mode)

    def optimize_index(self, name: str, mode: str = "quick") -> None:
        self.index_manager.optimize(name, mode)

    def cancel(self, name: str) -> None:
        self.index_manager.cancel(name)

    def indexes(self) -> pa.Table:
        return self.index_manager.indexes()

    def index(self, name: str) -> pa.Table:
        from hyperspace_tpu.index.statistics import index_statistics_table

        entry = self.index_manager.get_index(name)
        return index_statistics_table([entry] if entry else [], extended=True)

    def explain(self, dataset: Dataset, verbose: bool = False) -> str:
        from hyperspace_tpu.plananalysis.explain import explain_string

        return explain_string(dataset, self.session, verbose=verbose)

    def metrics(self) -> dict:
        """Point-in-time snapshot of the process-wide metrics registry
        (telemetry/metrics.py): counters like ``io.retry.attempts``,
        ``log.cas.conflicts``, ``rule.filter.applied``,
        ``degraded.fallbacks``, ``scrub.files_flagged``, and derived
        ratios like ``cache.device.hit_ratio`` — the operational
        aggregate across every query and action this process ran
        (docs/16-observability.md has the catalog)."""
        from hyperspace_tpu.telemetry import metrics as m

        return m.snapshot()

    def metrics_text(self) -> str:
        """The same registry as a Prometheus-style text exposition —
        scrape it, or drop it in a log line."""
        from hyperspace_tpu.telemetry import metrics as m

        return m.registry().render_prometheus()

    def reset_metrics(self) -> None:
        """Zero every series (tests; a bench section isolating deltas)."""
        from hyperspace_tpu.telemetry import metrics as m

        m.reset()
