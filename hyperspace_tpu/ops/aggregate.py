"""Device-side grouped aggregation kernel: sort by key words, segment-reduce.

Reference contract: Spark's HashAggregateExec is what executes the
reference's GROUP BY plans (the reference itself ships no aggregation code —
SURVEY.md §2.4's "components Spark provides" note); this engine previously
ran every aggregation on host arrow.  The device path reuses the bucket
machinery's normalization: group keys become monotone uint32 order words
(io/columnar.to_order_words), rows lexsort by them, group boundaries fall
out of adjacent-word comparison, and every aggregate is one XLA
``segment_sum``/``segment_min``/``segment_max`` over the sorted rows.

Two static-shape programs, like the join kernels:
  1. sort + boundary detection; only the GROUP COUNT crosses to host
     (perm/boundaries stay device-resident),
  2. capacity-padded segment reduction (capacity = next pow2 of the group
     count, so repeated queries share compiled programs).

Supported: non-empty integer/bool group keys, null-free numeric inputs,
sum/min/max/mean/count/count_all.  Everything else stays on the arrow host
path (the executor gates, execution/executor.py).  Floating-point KEYS are
excluded: NaN bit patterns would split arrow's single NaN group.
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hyperspace_tpu.execution import sync_guard
from hyperspace_tpu.utils.compat import enable_x64 as _enable_x64
from hyperspace_tpu.utils.shapes import round_up_pow2

AGG_OPS = ("sum", "min", "max", "mean", "count", "count_all")


@jax.jit
def _group_sort(key_words, n_valid):
    """(perm, boundaries, n_groups): rows lexsorted by key words with
    padding parked last (validity is the PRIMARY sort key, as in the join
    kernel); boundaries mark the first row of each group among the valid
    prefix."""
    n = key_words[0].shape[0]
    positions = jnp.arange(n, dtype=jnp.int32)
    invalid = (positions >= n_valid).astype(jnp.uint32)
    keys = []
    for w in reversed(key_words):
        keys.append(w[:, 1])
        keys.append(w[:, 0])
    keys.append(invalid)  # LAST key = primary: valid rows first
    perm = jnp.lexsort(tuple(keys)).astype(jnp.int32)
    is_valid = positions < n_valid
    diff = jnp.zeros(n, dtype=bool)
    for w in key_words:
        sorted_w = w[perm]
        d = (sorted_w[1:] != sorted_w[:-1]).any(axis=-1)
        diff = diff.at[1:].set(diff[1:] | d)
    boundaries = (diff | (positions == 0)) & is_valid
    return perm, boundaries, jnp.sum(boundaries, dtype=jnp.int32)


@partial(jax.jit, static_argnames=("ops", "capacity"))
def _segment_reduce(perm, boundaries, n_valid, value_cols, *, ops, capacity):
    """Per-group reductions over the sorted rows.  Returns
    (first_positions, counts, per-op arrays), each (capacity,); slots past
    the real group count are zeros/identities and sliced off on host."""
    n = perm.shape[0]
    positions = jnp.arange(n, dtype=jnp.int32)
    is_valid = positions < n_valid
    seg_ids = jnp.cumsum(boundaries.astype(jnp.int32)) - 1
    # Padded rows (sorted past the valid prefix) get segment `capacity` —
    # out of every real segment's range.
    seg_ids = jnp.where(is_valid, seg_ids, capacity)
    first_pos = jnp.nonzero(boundaries, size=capacity, fill_value=n - 1)[0]
    first_rows = perm[first_pos].astype(jnp.int32)
    counts = jax.ops.segment_sum(is_valid.astype(jnp.int32), seg_ids,
                                 num_segments=capacity + 1)[:capacity]
    outs = []
    vi = 0
    for op in ops:
        if op in ("count", "count_all"):
            # No value column — counts need nothing shipped or gathered.
            outs.append(counts)
            continue
        col = value_cols[vi]
        vi += 1
        vals = col[perm]
        if op in ("sum", "mean"):
            r = jax.ops.segment_sum(
                jnp.where(is_valid, vals, jnp.zeros_like(vals)), seg_ids,
                num_segments=capacity + 1)[:capacity]
            if op == "mean":
                r = r.astype(jnp.float64) / jnp.maximum(counts, 1)
        elif op == "min":
            r = jax.ops.segment_min(vals, seg_ids,
                                    num_segments=capacity + 1)[:capacity]
        elif op == "max":
            r = jax.ops.segment_max(vals, seg_ids,
                                    num_segments=capacity + 1)[:capacity]
        else:  # unreachable: AGG_OPS is validated by the caller
            raise AssertionError(op)
        outs.append(r)
    return (first_rows, counts) + tuple(outs)


def grouped_aggregate_mesh(
    key_words: Sequence[np.ndarray],
    value_cols: Sequence[np.ndarray],
    ops: Sequence[str],
    mesh,
    pad_to: int = 0,
) -> Tuple[np.ndarray, np.ndarray, List[np.ndarray]]:
    """Sharding-aware entry of the grouped aggregation: same contract
    and group order as :func:`grouped_aggregate`, computed over ``mesh``
    with group-key bucket ownership (a group's rows all land on one
    device, so every reduction is exact — parallel/aggregate.py)."""
    from hyperspace_tpu.parallel.aggregate import mesh_grouped_aggregate

    return mesh_grouped_aggregate(key_words, value_cols, ops, mesh,
                                  pad_to=pad_to)


def grouped_aggregate(
    key_words: Sequence[np.ndarray],
    value_cols: Sequence[np.ndarray],
    ops: Sequence[str],
    pad_to: int = 0,
) -> Tuple[np.ndarray, np.ndarray, List[np.ndarray]]:
    """Device grouped aggregation.

    Args:
      key_words: per group-key column, (n, 2) uint32 monotone order words.
      value_cols: one length-n numeric array per NON-count aggregate, in
        ops order (count/count_all ship no data — nothing to reduce).
      ops: per aggregate, one of AGG_OPS.
      pad_to: round the row dimension up to a multiple (compile-cache
        sharing across row counts, conf device_batch_rows).

    Returns:
      (first_row_indices, counts, results): for each of G groups, the index
      of its first row in the ORIGINAL order (host gathers the key values
      from the arrow table — no dtype round trip), the row count, and one
      result array per aggregate.  Groups are emitted in ascending key
      order.
    """
    from hyperspace_tpu.ops.sort import _pad_rows
    from hyperspace_tpu.utils.xla_cache import ensure_persistent_xla_cache

    for op in ops:
        if op not in AGG_OPS:
            raise ValueError(f"Unsupported device aggregate {op!r}")
    ensure_persistent_xla_cache()
    n = int(key_words[0].shape[0])
    capacity_rows = n
    if pad_to and pad_to > 0:
        capacity_rows = -(-max(n, 1) // pad_to) * pad_to
    from hyperspace_tpu.telemetry import timeline

    t0 = timeline.kernel_begin()
    if t0 is not None:
        timeline.record_transfer("h2d", sum(
            int(getattr(a, "nbytes", 0))
            for a in (*key_words, *value_cols)
            if not isinstance(a, jax.Array)))
    with _enable_x64():
        # Device-resident inputs (jax arrays from the HBM cache) pass
        # through _pad_rows untouched — it pads them on device instead of
        # pulling.  Padding must run INSIDE the x64 region: jnp.pad of a
        # float64/int64 device array under 32-bit mode silently downcasts,
        # which cost float sums ~1e-6 relative error.
        kw = tuple(_pad_rows(w, capacity_rows) for w in key_words)
        vc = tuple(_pad_rows(v, capacity_rows) for v in value_cols)
        perm, boundaries, n_groups = _group_sort(kw, n)
        # The one dynamic-shape sync point: only the group COUNT crosses.
        g = int(sync_guard.scalar(n_groups, "aggregate.groups"))
        if g == 0:
            timeline.kernel_end("aggregate", t0, perm)
            return (np.empty(0, np.int32), np.empty(0, np.int32),
                    [np.empty(0) for _ in ops])
        capacity = round_up_pow2(g)
        out = _segment_reduce(perm, boundaries, n, vc,
                              ops=tuple(ops), capacity=capacity)
    timeline.kernel_end("aggregate", t0, out)
    first_rows = sync_guard.pull(out[0], "aggregate.first_rows")[:g]
    counts = sync_guard.pull(out[1], "aggregate.counts")[:g]
    results = [sync_guard.pull(r, "aggregate.results")[:g]
               for r in out[2:]]
    return first_rows, counts, results
