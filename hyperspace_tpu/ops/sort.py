"""Device-side bucket/sort permutation kernel — the heart of the index build.

Reference contract: ``repartition(numBuckets, cols)`` + sort-within-bucket
(actions/CreateActionBase.scala:124-142 and the bucketed writer
DataFrameWriterExtensions.scala:49-67).  Spark does this as a cluster-wide
hash shuffle followed by per-task sorts; on TPU the whole thing is ONE fused
XLA program: hash → lexicographic sort by (bucket, key columns) → output a
gather permutation.  The host then applies the permutation to the arrow
table (zero-copy take) and slices per-bucket runs for the writer.

Sort keys are normalized host-side to numeric arrays (order-preserving ranks
for strings, hyperspace_tpu.io.columnar.to_order_key), so the kernel is
dtype-monomorphic like the hash kernel.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from hyperspace_tpu.ops.hash import combine_hashes


@partial(jax.jit, static_argnames=("num_buckets",))
def bucket_sort_permutation(
    word_cols: Sequence[jnp.ndarray],
    order_keys: Sequence[jnp.ndarray],
    num_buckets: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused hash + sort kernel.

    Args:
      word_cols: per key column (n, 2) uint32 hash words.
      order_keys: per key column (n,) numeric ordering keys.
      num_buckets: static bucket count.

    Returns:
      (bucket_ids int32 (n,), perm int32 (n,)) where perm orders rows by
      (bucket, *order_keys) — ready for ``write_bucketed``.
    """
    h = combine_hashes(word_cols)
    buckets = (h % jnp.uint32(num_buckets)).astype(jnp.int32)
    # lexsort: last key is the primary. Order: bucket first, then keys.
    keys = tuple(reversed(order_keys)) + (buckets,)
    perm = jnp.lexsort(keys).astype(jnp.int32)
    return buckets, perm


@partial(jax.jit, static_argnames=("num_buckets",))
def bucket_counts(buckets: jnp.ndarray, num_buckets: int) -> jnp.ndarray:
    """Rows per bucket — one segment-sum over HBM."""
    return jax.ops.segment_sum(
        jnp.ones_like(buckets, dtype=jnp.int32), buckets, num_segments=num_buckets)
