"""Device-side bucket/sort permutation kernel — the heart of the index build.

Reference contract: ``repartition(numBuckets, cols)`` + sort-within-bucket
(actions/CreateActionBase.scala:124-142 and the bucketed writer
DataFrameWriterExtensions.scala:49-67).  Spark does this as a cluster-wide
hash shuffle followed by per-task sorts; on TPU the whole thing is ONE fused
XLA program: hash → lexicographic sort by (bucket, key columns) → output a
gather permutation.  The host then applies the permutation to the arrow
table (zero-copy take) and slices per-bucket runs for the writer.

All kernel inputs are uint32 words (hash words from
``hyperspace_tpu.io.columnar.to_hash_words``; monotone order words from
``to_order_words``): the kernel is dtype-monomorphic AND pure 32-bit, so it
never leans on x64 int64 emulation — TPU's VPU lanes are 32-bit native.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from hyperspace_tpu.ops.hash import _route_sort_impl, use_pallas

# One bucket-assignment-and-sort implementation for the monolithic build,
# the external build's per-chunk route pass, and the query paths —
# duplicating it risks the programs silently diverging, which corrupts
# the durable on-disk bucket layout.  The shared impl lives with the
# hash kernel (ops/hash._route_sort_impl); ``n_valid`` is a TRACED
# scalar there, so row-count changes never retrace, and a Z-order build
# passes ONE precomputed Morton-word column (the host ranks in
# io/parquet.zorder_codes_host define the layout AND the file-split
# keys, so the device never re-ranks).  One stacked (2, n) output = ONE
# device->host transfer for both arrays (the pull dominates build
# latency on a remote-tunnel chip).
_bucket_sort_impl = _route_sort_impl


def _pad_rows(arr, capacity: int):
    import numpy as np

    if isinstance(arr, jax.Array):
        # HBM-resident input (execution/device_cache.py): pad on device —
        # np.asarray would pull the whole array back to host.
        if arr.shape[0] == capacity:
            return arr
        widths = [(0, capacity - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
        return jnp.pad(arr, widths)
    arr = np.asarray(arr)
    if arr.shape[0] == capacity:
        return arr
    pad = np.zeros((capacity - arr.shape[0],) + arr.shape[1:], dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def bucket_sort_permutation(
    word_cols: Sequence[jnp.ndarray],
    order_words: Sequence[jnp.ndarray],
    num_buckets: int,
    pad_to: int = 0,
) -> "Tuple[np.ndarray, np.ndarray]":
    """Fused hash + sort kernel.

    Args:
      word_cols: per key column (n, 2) uint32 hash words.
      order_words: per key column (n, 2) uint32 monotone order words.
      num_buckets: static bucket count.
      pad_to: when > 0, pad the row dimension up to the next multiple so
        every build shares one compiled program per (capacity, key count) —
        without this each distinct dataset size pays a fresh XLA compile
        (tens of seconds on a real chip).  The conf knob is
        ``device_batch_rows``.

    Returns:
      (bucket_ids int32 (n,), perm int32 (n,)) HOST numpy arrays (pulled in
      one transfer) where perm orders rows by (bucket, *key columns) —
      ready for ``write_bucketed``.

    On TPU the hash stage runs as the fused pallas kernel; the choice is a
    static jit arg so env flips retrace (see ``ops.hash.use_pallas``).
    """
    from hyperspace_tpu.execution import sync_guard
    from hyperspace_tpu.telemetry import timeline
    from hyperspace_tpu.utils.xla_cache import ensure_persistent_xla_cache

    ensure_persistent_xla_cache()
    n = int(word_cols[0].shape[0])
    if pad_to and pad_to > 0:
        capacity = -(-max(n, 1) // pad_to) * pad_to
        word_cols = [_pad_rows(w, capacity) for w in word_cols]
        order_words = [_pad_rows(w, capacity) for w in order_words]
    t0 = timeline.kernel_begin()
    if t0 is not None:
        timeline.record_transfer("h2d", sum(
            int(getattr(a, "nbytes", 0))
            for a in (*word_cols, *order_words)
            if not isinstance(a, jax.Array)))
    out = _bucket_sort_impl(
        tuple(word_cols), tuple(order_words), n, num_buckets, use_pallas())
    timeline.kernel_end("bucket_sort", t0, out)
    stacked = sync_guard.pull(out, "sort.permutation")
    return stacked[0, :n], stacked[1, :n]


def bucket_sort_permutation_np(
    word_cols,
    order_words,
    num_buckets: int,
) -> "Tuple[np.ndarray, np.ndarray]":
    """Bit-identical HOST mirror of ``bucket_sort_permutation`` (same cost
    model as the filter/join host mirrors: below
    ``device_build_min_rows`` the device round trip's transfer + compile
    latency over a remote tunnel dwarfs a numpy lexsort).  Identity holds
    because bucket assignment shares ``bucket_ids_np`` (parity-tested
    against the device kernel) and both sorts are stable lexsorts over the
    SAME (bucket, order-word) key sequence — padding in the device path
    parks only pad rows at the tail, never reordering real ties.  The
    host mirror IS the external build's route mirror
    (``ops.hash.route_partition_np``): one implementation, one ordering."""
    from hyperspace_tpu.ops.hash import route_partition_np

    return route_partition_np(word_cols, order_words, num_buckets)


@partial(jax.jit, static_argnames=("num_buckets",))
def _bucket_counts_xla(buckets: jnp.ndarray, num_buckets: int) -> jnp.ndarray:
    return jax.ops.segment_sum(
        jnp.ones_like(buckets, dtype=jnp.int32), buckets, num_segments=num_buckets)


def bucket_counts(buckets: jnp.ndarray, num_buckets: int) -> jnp.ndarray:
    """Rows per bucket.  On TPU: the pallas one-hot histogram kernel
    (ops/pallas_kernels.py) — VPU compares instead of segment_sum's
    serialized scatter-add; elsewhere one XLA segment-sum over HBM."""
    if use_pallas():
        from hyperspace_tpu.ops.pallas_kernels import bucket_histogram

        return bucket_histogram(buckets, num_buckets)
    return _bucket_counts_xla(buckets, num_buckets)
