"""Device-side equi-join kernel over sorted keys.

Reference analog: the shuffle-free sort-merge join the covering indexes
enable (JoinIndexRule.scala:36-50).  Spark's SMJ streams row iterators; the
XLA-native formulation is vectorized:

  1. sort the right side by key (one ``jnp.sort`` — on bucketed index data
     the input is already sorted, making this a near-no-op merge),
  2. ``searchsorted`` left keys into the right keys → per-left-row match
     ranges [lo, hi),
  3. expand to output pairs with ``jnp.repeat(..., total_repeat_length=N)``.

Step 3 needs the total match count N as a static shape, so the kernel is
two-phase with one host sync in between (count → materialize) — the standard
XLA pattern for dynamic-size outputs.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _match_ranges(left_keys: jnp.ndarray, right_keys_sorted: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    lo = jnp.searchsorted(right_keys_sorted, left_keys, side="left")
    hi = jnp.searchsorted(right_keys_sorted, left_keys, side="right")
    return lo, hi


@partial(jax.jit, static_argnames=("total",))
def _expand(lo: jnp.ndarray, hi: jnp.ndarray, total: int
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    counts = hi - lo
    left_idx = jnp.repeat(jnp.arange(lo.shape[0]), counts, total_repeat_length=total)
    # Offset of each output row within its left-row group.
    starts = jnp.cumsum(counts) - counts
    within = jnp.arange(total) - jnp.repeat(starts, counts, total_repeat_length=total)
    right_pos = lo[left_idx] + within
    return left_idx, right_pos


def sorted_equi_join(left_keys: np.ndarray, right_keys: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Inner equi-join on single numeric keys.

    Returns (left_indices, right_indices) into the ORIGINAL (unsorted)
    inputs.  Right side is sorted on device; left side order is preserved.
    """
    # Scoped x64: int64 keys (TPC-H orderkey at SF100 exceeds 2^31) must not
    # truncate inside jnp.asarray, but flipping x64 globally would change
    # dtype defaults for every other JAX user in the process.
    with jax.enable_x64():
        lk = jnp.asarray(left_keys)
        rk = jnp.asarray(right_keys)
        r_perm = jnp.argsort(rk)
        rk_sorted = rk[r_perm]
        lo, hi = _match_ranges(lk, rk_sorted)
        total = int(jnp.sum(hi - lo))  # host sync: the one dynamic-shape point
        if total == 0:
            return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64)
        left_idx, right_pos = _expand(lo, hi, total)
        right_idx = r_perm[right_pos]
        return np.asarray(left_idx), np.asarray(right_idx)
