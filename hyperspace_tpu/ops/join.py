"""Device-side equi-join kernel over sorted keys.

Reference analog: the shuffle-free sort-merge join the covering indexes
enable (JoinIndexRule.scala:36-50).  Spark's SMJ streams row iterators; the
XLA-native formulation is vectorized:

  1. sort the right side by key (one ``jnp.sort`` — on bucketed index data
     the input is already sorted, making this a near-no-op merge),
  2. ``searchsorted`` left keys into the right keys → per-left-row match
     ranges [lo, hi),
  3. expand to output pairs with ``jnp.repeat(..., total_repeat_length=N)``.

Step 3 needs the total match count N as a static shape, so the kernel is
two-phase with one host sync in between (count → materialize) — the standard
XLA pattern for dynamic-size outputs.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from hyperspace_tpu.execution import sync_guard
from hyperspace_tpu.utils.compat import enable_x64 as _enable_x64
from hyperspace_tpu.utils.shapes import round_up_pow2


@jax.jit
def _match_ranges(left_keys: jnp.ndarray, right_keys_sorted: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    lo = jnp.searchsorted(right_keys_sorted, left_keys, side="left")
    hi = jnp.searchsorted(right_keys_sorted, left_keys, side="right")
    return lo, hi


@partial(jax.jit, static_argnames=("capacity",))
def _expand(lo: jnp.ndarray, hi: jnp.ndarray, capacity: int
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    # ``capacity`` is the match count rounded UP to a power of two (caller
    # slices to the true count): the static output shape must not track the
    # exact count or every distinct query result size costs a fresh XLA
    # compile — ruinous over a real-chip tunnel at 20-40 s per compile.
    counts = hi - lo
    left_idx = jnp.repeat(jnp.arange(lo.shape[0]), counts,
                          total_repeat_length=capacity)
    # Offset of each output row within its left-row group.
    starts = jnp.cumsum(counts) - counts
    within = jnp.arange(capacity) - jnp.repeat(starts, counts,
                                               total_repeat_length=capacity)
    right_pos = lo[left_idx] + within
    return left_idx, right_pos


def sorted_equi_join_np(left_keys: np.ndarray, right_keys: np.ndarray
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Host mirror of ``sorted_equi_join`` — the same sort/searchsorted/
    expand formulation in numpy.  Below the device row threshold a device
    round trip is pure tunnel latency; covering-index data arrives sorted
    within buckets, so the mergesort argsort here is near-linear."""
    left_keys = np.asarray(left_keys)
    right_keys = np.asarray(right_keys)
    if left_keys.size == 0 or right_keys.size == 0:
        return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64)
    r_perm = np.argsort(right_keys, kind="stable")
    rk_sorted = right_keys[r_perm]
    lo = np.searchsorted(rk_sorted, left_keys, side="left")
    hi = np.searchsorted(rk_sorted, left_keys, side="right")
    counts = hi - lo
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64)
    left_idx = np.repeat(np.arange(left_keys.shape[0]), counts)
    starts = np.cumsum(counts) - counts
    within = np.arange(total) - np.repeat(starts, counts)
    right_idx = r_perm[lo[left_idx] + within]
    return left_idx.astype(np.int64), right_idx.astype(np.int64)


_FNV_OFFSET = np.uint64(0xcbf29ce484222325)
_FNV_PRIME = np.uint64(0x100000001b3)


def key_digests(table, key_columns, null_salt: int = 1) -> np.ndarray:
    """(n,) uint64 digest per row over ``key_columns`` — FNV-1a over each
    column's 64-bit hash words (io/columnar.to_hash_words: equal values,
    including -0.0/0.0 and equal strings, always produce equal words).
    Equal key tuples get equal digests; collisions are possible and are
    removed by ``hashed_equi_join``'s verification pass.

    Rows with a null in ANY key column get a digest unique to (row,
    ``null_salt``): inner-join semantics can never match them, and letting
    them share to_hash_words' null sentinel would make the digest join
    emit an n_left_nulls x n_right_nulls candidate cross product just for
    verification to discard."""
    import pyarrow.compute as pc

    from hyperspace_tpu.io import columnar

    n = table.num_rows
    acc = np.full(n, _FNV_OFFSET, dtype=np.uint64)
    nulls = np.zeros(n, dtype=bool)
    with np.errstate(over="ignore"):
        for c in key_columns:
            col = table.column(c)
            if col.null_count > 0:
                nulls |= np.asarray(pc.is_null(col))
            words = np.asarray(columnar.to_hash_words(col))
            w64 = (words[:, 0].astype(np.uint64) << np.uint64(32)) \
                | words[:, 1].astype(np.uint64)
            acc = (acc ^ w64) * _FNV_PRIME
        if nulls.any():
            acc[nulls] = (np.flatnonzero(nulls).astype(np.uint64)
                          * _FNV_PRIME) ^ (np.uint64(null_salt) << np.uint64(62))
    return acc


class UnsupportedJoinKeys(Exception):
    """Key pair the hashed join cannot handle exactly (e.g. string vs int)."""


def hashed_equi_join(left, right, l_keys, r_keys,
                     device: bool) -> Tuple[np.ndarray, np.ndarray]:
    """Inner equi-join for COMPOSITE and STRING keys: 64-bit digests joined
    with the sorted kernel (device or host mirror), then candidate pairs
    verified column-by-column against the actual values — hash collisions
    can only ADD candidates, never hide a match, so the verified result is
    exact.  Mixed numeric/numeric key pairs are compared as float64 (the
    Spark cast); NaN keys match NaN (Spark normalizes NaN for joins).

    Raises UnsupportedJoinKeys for key pairs with no exact common domain
    (caller falls back to the host hash join)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    lcols, rcols = [], []
    for lc, rc in zip(l_keys, r_keys):
        la, ra = left.column(lc), right.column(rc)
        if la.type != ra.type:
            if (pa.types.is_floating(la.type) or pa.types.is_integer(la.type)) \
                    and (pa.types.is_floating(ra.type)
                         or pa.types.is_integer(ra.type)):
                la = pc.cast(la, pa.float64())
                ra = pc.cast(ra, pa.float64())
            else:
                raise UnsupportedJoinKeys(f"{la.type} vs {ra.type}")
        lcols.append(la)
        rcols.append(ra)
    ltab = pa.table({f"k{i}": c for i, c in enumerate(lcols)})
    rtab = pa.table({f"k{i}": c for i, c in enumerate(rcols)})
    join = sorted_equi_join if device else sorted_equi_join_np
    li, ri = join(
        key_digests(ltab, ltab.column_names, null_salt=1).view(np.int64),
        key_digests(rtab, rtab.column_names, null_salt=2).view(np.int64))
    if li.size == 0:
        return li, ri
    keep = np.ones(li.size, dtype=bool)
    for lc, rc in zip(ltab.columns, rtab.columns):
        la = lc.take(pa.array(li))
        ra = rc.take(pa.array(ri))
        eq = pc.fill_null(pc.equal(la, ra), False)
        mask = np.asarray(eq.to_numpy(zero_copy_only=False), dtype=bool)
        if pa.types.is_floating(la.type):
            both_nan = (
                np.asarray(pc.fill_null(pc.is_nan(la), False))
                & np.asarray(pc.fill_null(pc.is_nan(ra), False)))
            mask |= both_nan
        keep &= mask
    return li[keep], ri[keep]


def _key_owner_shards(keys: np.ndarray, n_devices: int):
    """(shards, originals): per mesh device, the key values it owns and
    their original indices.  Ownership is the key's hash bucket mod the
    device count — the same mod ownership as the sharded build route,
    through the bit-identical host hash mirror, so EQUAL keys always
    share an owner and the per-device joins are exhaustive."""
    import pyarrow as pa

    from hyperspace_tpu.io import columnar
    from hyperspace_tpu.ops.hash import bucket_ids_np

    words = np.asarray(columnar.to_hash_words(
        pa.chunked_array([pa.array(keys)])))
    owner = bucket_ids_np([words], n_devices)
    order = np.argsort(owner, kind="stable")
    owner_sorted = owner[order]
    starts = np.searchsorted(owner_sorted, np.arange(n_devices), "left")
    ends = np.searchsorted(owner_sorted, np.arange(n_devices), "right")
    shards = [keys[order[starts[d]:ends[d]]] for d in range(n_devices)]
    originals = [order[starts[d]:ends[d]] for d in range(n_devices)]
    return shards, originals


def sorted_equi_join_mesh(left_keys: np.ndarray, right_keys: np.ndarray,
                          mesh) -> Tuple[np.ndarray, np.ndarray]:
    """Sharding-aware entry of the inner equi-join: the same MATCH SET
    as :func:`sorted_equi_join` (pair order is not contractual), with
    both sides co-partitioned by key-hash bucket ownership and every
    device joining only its owned keys under ``shard_map``
    (parallel/join.copartitioned_join_ragged — zero collectives; the
    only host traffic is the final gather of match indices).  Host
    inputs only: resident arrays keep the single-device kernel, whose
    HBM placement is its own layout."""
    from hyperspace_tpu.parallel.join import copartitioned_join_ragged

    left_keys = np.asarray(left_keys)
    right_keys = np.asarray(right_keys)
    if left_keys.size == 0 or right_keys.size == 0:
        return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64)
    n_devices = int(mesh.devices.size)
    l_shards, l_orig = _key_owner_shards(left_keys, n_devices)
    r_shards, r_orig = _key_owner_shards(right_keys, n_devices)
    dev_ids, l_local, r_local = copartitioned_join_ragged(
        l_shards, r_shards, mesh)
    li_parts, ri_parts = [], []
    for d in range(n_devices):
        sel = dev_ids == d
        if not sel.any():
            continue
        li_parts.append(l_orig[d][l_local[sel]])
        ri_parts.append(r_orig[d][r_local[sel]])
    if not li_parts:
        return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64)
    return (np.concatenate(li_parts).astype(np.int64),
            np.concatenate(ri_parts).astype(np.int64))


def sorted_equi_join(left_keys: np.ndarray, right_keys: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Inner equi-join on single numeric keys.

    Returns (left_indices, right_indices) into the ORIGINAL (unsorted)
    inputs.  Right side is sorted on device; left side order is preserved.
    """
    # Narrow integer keys to int32 when every value fits: TPU has no native
    # int64 (XLA emulates it as two u32 passes), so a 32-bit sort/searchsorted
    # is the fast path.  Keys that genuinely need 64 bits (TPC-H orderkey at
    # SF100 exceeds 2^31) take the scoped-x64 path — scoped, not global,
    # because flipping x64 globally would change dtype defaults for every
    # other JAX user in the process.
    from hyperspace_tpu.utils.xla_cache import ensure_persistent_xla_cache

    ensure_persistent_xla_cache()
    # HBM-resident inputs (jax arrays from the device column cache) stay
    # on device: np.asarray would pull them back through the very
    # transfer residency exists to avoid.  Value-scan narrowing is
    # host-only for the same reason — resident int64 keys sort in x64.
    resident = isinstance(left_keys, jax.Array) \
        or isinstance(right_keys, jax.Array)
    if not resident:
        left_keys = np.asarray(left_keys)
        right_keys = np.asarray(right_keys)
    if (not resident
            and np.issubdtype(left_keys.dtype, np.integer)
            and np.issubdtype(right_keys.dtype, np.integer)
            and left_keys.size and right_keys.size):

        def fits32(a: np.ndarray) -> bool:
            if np.can_cast(a.dtype, np.int32):
                return True  # dtype already guarantees it: skip the scan
            return bool(a.min() >= -2**31 and a.max() <= 2**31 - 1)

        if fits32(left_keys) and fits32(right_keys):
            left_keys = left_keys.astype(np.int32, copy=False)
            right_keys = right_keys.astype(np.int32, copy=False)
    from hyperspace_tpu.telemetry import timeline

    t0 = timeline.kernel_begin()
    if t0 is not None and not resident:
        # Attribution seam (conf-gated): host inputs are about to ship.
        timeline.record_transfer(
            "h2d", int(left_keys.nbytes) + int(right_keys.nbytes))
    with _enable_x64():
        lk = jnp.asarray(left_keys)
        rk = jnp.asarray(right_keys)
        r_perm = jnp.argsort(rk)
        rk_sorted = rk[r_perm]
        lo, hi = _match_ranges(lk, rk_sorted)
        # The one dynamic-shape sync point: only the match count crosses.
        total = int(sync_guard.scalar(jnp.sum(hi - lo), "join.matches"))
        if total == 0:
            timeline.kernel_end("join", t0, (lo, hi))
            return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64)
        capacity = round_up_pow2(total)
        left_idx, right_pos = _expand(lo, hi, capacity)
        right_idx = r_perm[jnp.clip(right_pos, 0, rk.shape[0] - 1)]
        timeline.kernel_end("join", t0, (left_idx, right_idx))
        # Attributed pulls (exec.transfer.d2h counted inside the seam).
        out_l = sync_guard.pull(left_idx, "join.left_idx")[:total]
        out_r = sync_guard.pull(right_idx, "join.right_idx")[:total]
        return out_l, out_r
