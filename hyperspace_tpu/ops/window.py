"""Vectorized window-function kernels over sorted partition segments.

The executor sorts the table once by (partition, order keys) and hands the
engine plain numpy arrays in that sorted layout; every window function is
then a segment operation with NO per-partition Python or pandas loop:

  - ranking (row_number/rank/dense_rank/ntile): arithmetic on the
    partition/tie boundary masks;
  - frame aggregates (sum/count/mean): prefix-sum differences, with an
    exact int64 path for integer inputs (no float64 round-trip — values
    above 2^53 stay exact);
  - frame min/max: ARGmin/ARGmax so the result is always taken from the
    source Arrow column and keeps its type bit-for-bit (dates stay
    dates).  Prefix/suffix frames use a Hillis–Steele doubling scan
    (O(n log n), clamped at partition starts); frames bounded on both
    sides use a sparse-table range query;
  - first_value/last_value: a take at the frame boundary row.

Frames are ROWS frames [lo_i, hi_i] (inclusive, sorted coordinates)
computed by :func:`frame_bounds`; the default SQL RANGE frame (UNBOUNDED
PRECEDING .. CURRENT ROW with peers) is expressed as lo = partition
start, hi = tie-group end, so one engine serves both.

Reference contract: Spark's window exec consumed by the corpus queries
(TPC-DS q51 `ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW`,
/root/reference/src/test/resources/tpcds/queries/q51.sql:1-8; q36/q44
rank() shapes).  Spark semantics matched: null order, peers share RANGE
frame values, aggregate null-if-empty-frame, NaN treated as missing in
running min/max (matching the round-4 pandas engine).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

__all__ = [
    "partition_codes", "segment_bounds", "frame_bounds",
    "row_number", "rank_from_ties", "dense_rank_from_ties", "ntile",
    "frame_count", "frame_sum", "frame_mean", "frame_min_max",
    "frame_first_last",
]


def partition_codes(table: pa.Table, keys: Sequence[str]) -> np.ndarray:
    """Null-safe group codes (int64) for the partition columns: equal
    tuples (nulls equal to nulls, Spark grouping semantics) share a
    code.  Codes are dense but NOT ordered by value — only identity
    matters, the sort orders them."""
    n = table.num_rows
    if not keys:
        return np.zeros(n, dtype=np.int64)
    combined = np.zeros(n, dtype=np.int64)
    for name in keys:
        col = table.column(name)
        if isinstance(col, pa.ChunkedArray):
            col = col.combine_chunks()
        enc = col.dictionary_encode()
        idx = pc.fill_null(enc.indices, -1).to_numpy(zero_copy_only=False)
        card = len(enc.dictionary) + 1  # +1 for the null slot
        codes = idx.astype(np.int64) + 1
        if combined.size and card > 1:
            hi = combined.max() if n else 0
            if hi > (2**62) // card:
                # Re-densify to dodge int64 overflow on wide key spaces.
                _, combined = np.unique(combined, return_inverse=True)
        combined = combined * card + codes
    _, dense = np.unique(combined, return_inverse=True)
    return dense.astype(np.int64)


def segment_bounds(new_seg: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-row segment start/end indices (inclusive) from a boundary
    mask over the SORTED layout (``new_seg[0]`` must be True)."""
    n = new_seg.shape[0]
    idx = np.arange(n, dtype=np.int64)
    start = np.maximum.accumulate(np.where(new_seg, idx, 0))
    seg_id = np.cumsum(new_seg) - 1
    last = np.zeros(seg_id[-1] + 1 if n else 0, dtype=np.int64)
    last[seg_id] = idx  # later rows win: per-segment last index
    end = last[seg_id]
    return start, end


def frame_bounds(part_start: np.ndarray, part_end: np.ndarray,
                 tie_end: Optional[np.ndarray],
                 frame: Optional[Tuple[Optional[int], Optional[int]]],
                 has_order: bool) -> Tuple[np.ndarray, np.ndarray]:
    """Inclusive [lo, hi] row-index bounds per row, sorted coordinates.

    frame=None reproduces SQL defaults: whole partition without ORDER
    BY, RANGE UNBOUNDED PRECEDING..CURRENT ROW (peers included, via
    ``tie_end``) with one.  An explicit ROWS frame (lo_off, hi_off) uses
    offsets relative to the current row, None meaning unbounded."""
    n = part_start.shape[0]
    idx = np.arange(n, dtype=np.int64)
    if frame is None:
        if not has_order:
            return part_start, part_end
        return part_start, tie_end
    lo_off, hi_off = frame
    lo = part_start if lo_off is None else \
        np.maximum(part_start, idx + lo_off)
    hi = part_end if hi_off is None else np.minimum(part_end, idx + hi_off)
    return lo, hi


# ---------------------------------------------------------------- ranking

def row_number(part_start: np.ndarray) -> np.ndarray:
    n = part_start.shape[0]
    return (np.arange(n, dtype=np.int64) - part_start + 1) \
        .astype(np.int32)


def dense_rank_from_ties(new_part: np.ndarray,
                         new_tie: np.ndarray) -> np.ndarray:
    n = new_part.shape[0]
    cum = np.cumsum(new_tie.astype(np.int64))
    # Tie-changes counted before each partition start (the start row's
    # own tie flag is always set, hence cum-1 there).
    offset = np.maximum.accumulate(np.where(new_part, cum - 1, 0))
    return (cum - offset).astype(np.int32)


def rank_from_ties(part_start: np.ndarray,
                   new_tie: np.ndarray) -> np.ndarray:
    n = part_start.shape[0]
    rn = np.arange(n, dtype=np.int64) - part_start + 1
    tie_start = np.maximum.accumulate(
        np.where(new_tie, np.arange(n, dtype=np.int64), 0))
    return rn[tie_start].astype(np.int32)


def ntile(part_start: np.ndarray, part_end: np.ndarray,
          k: int) -> np.ndarray:
    """Spark NTile: the first ``size % k`` buckets get one extra row."""
    i = np.arange(part_start.shape[0], dtype=np.int64) - part_start
    size = part_end - part_start + 1
    base, rem = size // k, size % k
    cut = rem * (base + 1)
    big = i // np.maximum(base + 1, 1)
    small = rem + (i - cut) // np.maximum(base, 1)
    return (np.where(i < cut, big, small) + 1).astype(np.int32)


# ----------------------------------------------------------- frame aggs

def _prefix(x: np.ndarray) -> np.ndarray:
    out = np.zeros(x.shape[0] + 1, dtype=x.dtype)
    np.cumsum(x, out=out[1:])
    return out


def frame_count(valid: Optional[np.ndarray], lo: np.ndarray,
                hi: np.ndarray) -> np.ndarray:
    """count(value) over the frame (valid=None → count(*))."""
    n = lo.shape[0]
    if valid is None:
        return np.maximum(hi - lo + 1, 0)
    c = _prefix(valid.astype(np.int64))
    safe_hi = np.minimum(hi + 1, n)
    out = c[safe_hi] - c[np.minimum(lo, n)]
    return np.where(hi < lo, 0, out)


def frame_sum(vals: np.ndarray, valid: np.ndarray, lo: np.ndarray,
              hi: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(sums, valid_counts).  Integer input sums exactly in int64 (never
    through float64 — the round-4 advisor caught 2^55+3 rounding);
    uint64 sums in its own domain (an int64 view would wrap values
    above 2^63); floats sum in float64.  NaN values are treated as
    missing — a prefix-sum engine would otherwise poison EVERY frame at
    or after one NaN row, not just the frames containing it (and
    frame_min_max already skips NaN, as the round-4 engine did)."""
    if vals.dtype.kind == "u":
        work = np.where(valid, vals, 0).astype(np.uint64)
    elif vals.dtype.kind in "ib":
        work = np.where(valid, vals, 0).astype(np.int64)
    else:
        valid = valid & ~np.isnan(vals.astype(np.float64))
        work = np.where(valid, vals, 0.0).astype(np.float64)
    s, c = _prefix(work), _prefix(valid.astype(np.int64))
    n = vals.shape[0]
    safe_hi, safe_lo = np.minimum(hi + 1, n), np.minimum(lo, n)
    sums = s[safe_hi] - s[safe_lo]
    cnt = np.where(hi < lo, 0, c[safe_hi] - c[safe_lo])
    return np.where(cnt > 0, sums, 0), cnt


def frame_mean(vals: np.ndarray, valid: np.ndarray, lo: np.ndarray,
               hi: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    if vals.dtype.kind in "iub":
        work = np.where(valid, vals, 0).astype(np.float64)
    else:
        # NaN as missing, like frame_sum/frame_min_max.
        valid = valid & ~np.isnan(vals.astype(np.float64))
        work = np.where(valid, vals, 0.0).astype(np.float64)
    s, c = _prefix(work), _prefix(valid.astype(np.int64))
    n = vals.shape[0]
    safe_hi, safe_lo = np.minimum(hi + 1, n), np.minimum(lo, n)
    cnt = np.where(hi < lo, 0, c[safe_hi] - c[safe_lo])
    with np.errstate(invalid="ignore", divide="ignore"):
        mean = (s[safe_hi] - s[safe_lo]) / cnt
    return mean, cnt


def _arg_scan(work: np.ndarray, part_start: np.ndarray,
              pick_smaller: bool) -> np.ndarray:
    """Hillis–Steele prefix ARGmin/ARGmax clamped at partition starts:
    after the k-th pass res[i] is the argext over
    [max(part_start_i, i-2^k+1), i]; log2(n) numpy passes, no
    per-partition loop."""
    n = work.shape[0]
    idx = np.arange(n, dtype=np.int64)
    arg = idx.copy()
    best = work.copy()
    shift = 1
    while shift < n:
        src = idx - shift
        ok = src >= part_start
        if not ok.any():
            break
        s_best = best[src[ok]]
        s_arg = arg[src[ok]]
        cur = best[ok]
        take = s_best < cur if pick_smaller else s_best > cur
        # Ties keep the earlier (leftmost) row for determinism.
        tie = (s_best == cur) & (s_arg < arg[ok])
        take |= tie
        nb, na = cur.copy(), arg[ok].copy()
        nb[take], na[take] = s_best[take], s_arg[take]
        best = best.copy()
        arg = arg.copy()
        best[ok], arg[ok] = nb, na
        shift *= 2
    return arg


def _sparse_arg(work: np.ndarray, lo: np.ndarray, hi: np.ndarray,
                pick_smaller: bool) -> np.ndarray:
    """Sparse-table range ARGext for frames bounded on both sides.
    Memory O(n · log max_width); widths here are the (small) constant
    ROWS offsets, clamped at partition edges."""
    n = work.shape[0]
    width = np.maximum(hi - lo + 1, 1)
    max_w = int(width.max()) if n else 1
    levels = max(int(np.floor(np.log2(max_w))), 0)
    val_tab = [work]
    arg_tab = [np.arange(n, dtype=np.int64)]
    for k in range(1, levels + 1):
        half = 1 << (k - 1)
        if half >= n:
            break
        prev_v, prev_a = val_tab[-1], arg_tab[-1]
        left_v, right_v = prev_v[:n - half], prev_v[half:]
        left_a, right_a = prev_a[:n - half], prev_a[half:]
        take = right_v < left_v if pick_smaller else right_v > left_v
        take = take | ((right_v == left_v) & (right_a < left_a))
        nv, na = left_v.copy(), left_a.copy()
        nv[take], na[take] = right_v[take], right_a[take]
        val_tab.append(np.concatenate([nv, prev_v[n - half:]]))
        arg_tab.append(np.concatenate([na, prev_a[n - half:]]))
    k_i = np.floor(np.log2(width)).astype(np.int64)
    out = np.empty(n, dtype=np.int64)
    for k in range(levels + 1):
        mask = k_i == k
        if not mask.any():
            continue
        span = 1 << k
        a = lo[mask]
        b = hi[mask] - span + 1
        va, aa = val_tab[k][a], arg_tab[k][a]
        vb, ab = val_tab[k][np.maximum(b, 0)], arg_tab[k][np.maximum(b, 0)]
        take = vb < va if pick_smaller else vb > va
        take = take | ((vb == va) & (ab < aa))
        res = aa.copy()
        res[take] = ab[take]
        out[mask] = res
    return out


def frame_min_max(vals: np.ndarray, valid: np.ndarray, lo: np.ndarray,
                  hi: np.ndarray, part_start: np.ndarray,
                  part_end: np.ndarray,
                  frame: Optional[Tuple[Optional[int], Optional[int]]],
                  is_min: bool) -> Tuple[np.ndarray, np.ndarray]:
    """(arg_rows, valid_counts): the row index (sorted coordinates) of
    the frame extremum per row — the caller takes from the source Arrow
    column so any orderable type keeps its exact representation.  NaN
    and null are skipped (sentinel-filled), matching the round-4
    engine; an all-skipped frame is nulled via the count."""
    if vals.dtype.kind == "u":
        # uint64 stays in its own domain: an int64 view would wrap
        # values above 2^63 and mis-order the comparisons.
        work = vals.astype(np.uint64, copy=True)
        sentinel = np.iinfo(np.uint64).max if is_min else 0
        skip = ~valid
    elif vals.dtype.kind == "i":
        work = vals.astype(np.int64, copy=True)
        sentinel = np.iinfo(np.int64).max if is_min \
            else np.iinfo(np.int64).min
        skip = ~valid
    elif vals.dtype.kind == "b":
        work = vals.astype(np.int64)
        sentinel = np.iinfo(np.int64).max if is_min \
            else np.iinfo(np.int64).min
        skip = ~valid
    elif vals.dtype.kind == "M":  # datetime64 — view as int64, NaT skip
        work = vals.view("i8").astype(np.int64, copy=True)
        sentinel = np.iinfo(np.int64).max if is_min \
            else np.iinfo(np.int64).min
        skip = ~valid
    elif vals.dtype.kind == "f":
        work = vals.astype(np.float64, copy=True)
        sentinel = np.inf if is_min else -np.inf
        skip = ~valid | np.isnan(vals.astype(np.float64))
    else:
        raise ValueError(
            f"Running window min/max over a {vals.dtype} column is not "
            f"supported; drop the ORDER BY for a whole-partition "
            f"reduction, or cast the column to a numeric/temporal type")
    work[skip] = sentinel
    eff_valid = ~skip

    # Empty frames (hi < lo, possible when a bounded offset lands past
    # the partition) are masked by cnt==0 below — clamp the indexing so
    # the gather itself can't go out of bounds.
    n_rows = work.shape[0]
    lo_c = np.clip(lo, 0, n_rows - 1)
    hi_c = np.clip(hi, 0, n_rows - 1)
    lo_unbounded = frame is None or frame[0] is None
    hi_unbounded = frame is not None and frame[1] is None
    if lo_unbounded:
        scan = _arg_scan(work, part_start, pick_smaller=is_min)
        arg = scan[hi_c]
    elif hi_unbounded:
        # Suffix frame: mirror the array and run the prefix scan.
        rev_work = work[::-1].copy()
        rev_start = (n_rows - 1) - part_end[::-1]
        scan = _arg_scan(rev_work, rev_start, pick_smaller=is_min)
        arg = (n_rows - 1) - scan[(n_rows - 1) - lo_c]
    else:
        arg = _sparse_arg(work, np.minimum(lo_c, hi_c), hi_c,
                          pick_smaller=is_min)
    c = _prefix(eff_valid.astype(np.int64))
    n = vals.shape[0]
    cnt = np.where(hi < lo, 0,
                   c[np.minimum(hi + 1, n)] - c[np.minimum(lo, n)])
    return arg, cnt


def frame_first_last(lo: np.ndarray, hi: np.ndarray,
                     first: bool) -> Tuple[np.ndarray, np.ndarray]:
    """(arg_rows, nonempty_mask) for first_value/last_value: the frame
    boundary row itself (Spark default respects nulls)."""
    arg = lo if first else hi
    nonempty = hi >= lo
    return np.where(nonempty, arg, 0), nonempty
