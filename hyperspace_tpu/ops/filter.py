"""Device-side predicate evaluation kernel.

Reference analog: Spark's predicate evaluation inside FileSourceScanExec —
here compiled by XLA into a fused elementwise pass over HBM-resident numeric
columns (§2.4 "predicate-pushdown kernel").  The executor routes predicates
whose referenced columns are all numeric through this kernel; string
predicates evaluate host-side via arrow compute (variable-length data stays
out of XLA's static-shape world).

The predicate is compiled to a closed JAX function keyed by expression
structure, so repeated queries with different literals still hit the XLA
compile cache (literals are traced as scalar arguments, not baked in).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from hyperspace_tpu.plan.expr import (
    And,
    Arith,
    BinOp,
    Col,
    Expr,
    IsIn,
    Lit,
    Neg,
    Not,
    Or,
)

_CMP = {
    "==": lambda a, b: a == b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


# Structure-keyed cache of jitted predicate programs.  jax.jit caches
# compiled executables PER FUNCTION OBJECT: without this memo every query
# would build a fresh lambda and pay a full XLA compile (~0.5 s/query on a
# real chip).  Keys are (expression structure + baked IsIn values, column
# order); literal VALUES are not in the key — they are traced arguments.
_PREDICATE_CACHE: Dict[Tuple, Callable] = {}
_PREDICATE_CACHE_MAX = 512  # queries have few distinct shapes; safety bound


def _structure_value_key(e: Expr, parts: List, literals: List[float]) -> None:
    """Pre-order fingerprint of a VALUE expression (column, literal, or
    arithmetic over those); collects literals in the SAME traversal order
    ``build`` appends them."""
    if isinstance(e, Col):
        parts += ("c", e.name)
        return
    if isinstance(e, Lit):
        parts.append("L")
        literals.append(e.value)
        return
    if isinstance(e, Arith):
        if e.op == "/":
            # Division is host-only: x/0 must become null (drops the row
            # in a comparison), and the device path has no validity plane.
            raise ValueError(f"Division is not device-evaluable: {e!r}")
        parts += ("a", e.op)
        _structure_value_key(e.left, parts, literals)
        _structure_value_key(e.right, parts, literals)
        return
    if isinstance(e, Neg):
        parts.append("neg")
        _structure_value_key(e.child, parts, literals)
        return
    raise ValueError(f"Unsupported value expression: {e!r}")


def _structure_key(e: Expr, parts: List, literals: List[float]) -> None:
    """Pre-order structural fingerprint of ``e``; collects literals in the
    SAME traversal order ``_build`` appends them."""
    if isinstance(e, BinOp):
        parts += ("b", e.op)
        _structure_value_key(e.left, parts, literals)
        _structure_value_key(e.right, parts, literals)
        return
    if isinstance(e, (And, Or)):
        parts.append("&" if isinstance(e, And) else "|")
        _structure_key(e.left, parts, literals)
        _structure_key(e.right, parts, literals)
        return
    if isinstance(e, Not):
        parts.append("~")
        _structure_key(e.child, parts, literals)
        return
    if isinstance(e, IsIn):
        if not isinstance(e.child, Col):
            raise ValueError(f"IsIn over non-column: {e!r}")
        parts += ("in", e.child.name, tuple(e.values))
        return
    raise ValueError(f"Unsupported predicate node: {e!r}")


def build_value_fn(expr: Expr, column_order: Sequence[str]
                   ) -> Tuple[Callable, List[float]]:
    """(fn, literals) for a pure VALUE expression (column refs, literals,
    + - * arithmetic, negation): ``fn(columns, literals)`` returns the
    elementwise result.  Used by the fused join+aggregate pipeline to
    evaluate expression aggregate inputs (sum(price * (1 - discount)))
    on device-gathered columns.  Not jitted here — callers splice it
    into a larger jitted program.  Raises ValueError on anything outside
    the device-arithmetic subset (division's x/0→null 3VL is host-only,
    matching compile_predicate)."""
    col_ix = {name: i for i, name in enumerate(column_order)}
    literals: List[float] = []

    def build(e: Expr) -> Callable:
        if isinstance(e, Col):
            i = col_ix[e.name]
            return lambda cols, lits: cols[i]
        if isinstance(e, Lit):
            j = len(literals)
            literals.append(e.value)
            return lambda cols, lits: lits[j]
        if isinstance(e, Arith):
            if e.op == "/":
                raise ValueError(
                    f"Division is not device-evaluable: {e!r}")
            fl, fr = build(e.left), build(e.right)
            fn = {"+": lambda a, b: a + b,
                  "-": lambda a, b: a - b,
                  "*": lambda a, b: a * b}[e.op]
            return lambda cols, lits: fn(fl(cols, lits), fr(cols, lits))
        if isinstance(e, Neg):
            f = build(e.child)
            return lambda cols, lits: -f(cols, lits)
        raise ValueError(f"Unsupported value expression: {e!r}")

    return build(expr), literals


def compile_predicate(expr: Expr, column_order: Sequence[str]
                      ) -> Tuple[Callable, List[float]]:
    """Build (jitted_fn, literals) where ``jitted_fn(columns, literals)``
    returns a boolean mask.  ``columns`` are device arrays in
    ``column_order``; literals are scalars traced as arguments so the
    compiled program is reusable across queries with different constants.
    ``IsIn`` value lists are static (baked in): their length changes the
    program shape anyway.  The jitted function is memoized by expression
    structure so repeated queries hit XLA's compile cache.
    """
    from hyperspace_tpu.utils.xla_cache import ensure_persistent_xla_cache

    ensure_persistent_xla_cache()
    parts: List = []
    extracted: List[float] = []
    _structure_key(expr, parts, extracted)
    key = (tuple(parts), tuple(column_order))
    cached = _PREDICATE_CACHE.get(key)
    if cached is not None:
        return cached, extracted

    col_ix = {name: i for i, name in enumerate(column_order)}
    literals: List[float] = []

    def build_value(e: Expr) -> Callable:
        if isinstance(e, Col):
            i = col_ix[e.name]
            return lambda cols, lits: cols[i]
        if isinstance(e, Lit):
            j = len(literals)
            literals.append(e.value)
            return lambda cols, lits: lits[j]
        if isinstance(e, Arith):
            if e.op == "/":
                raise ValueError(f"Division is not device-evaluable: {e!r}")
            fl, fr = build_value(e.left), build_value(e.right)
            fn = {"+": lambda a, b: a + b,
                  "-": lambda a, b: a - b,
                  "*": lambda a, b: a * b}[e.op]
            return lambda cols, lits: fn(fl(cols, lits), fr(cols, lits))
        if isinstance(e, Neg):
            f = build_value(e.child)
            return lambda cols, lits: -f(cols, lits)
        raise ValueError(f"Unsupported value expression: {e!r}")

    def build(e: Expr) -> Callable:
        if isinstance(e, BinOp):
            op = _CMP[e.op]
            fl, fr = build_value(e.left), build_value(e.right)
            return lambda cols, lits: op(fl(cols, lits), fr(cols, lits))
        if isinstance(e, And):
            fl, fr = build(e.left), build(e.right)
            return lambda cols, lits: fl(cols, lits) & fr(cols, lits)
        if isinstance(e, Or):
            fl, fr = build(e.left), build(e.right)
            return lambda cols, lits: fl(cols, lits) | fr(cols, lits)
        if isinstance(e, Not):
            f = build(e.child)
            return lambda cols, lits: ~f(cols, lits)
        if isinstance(e, IsIn):
            if not isinstance(e.child, Col):
                raise ValueError(f"IsIn over non-column: {e!r}")
            i = col_ix[e.child.name]
            values = tuple(e.values)
            return lambda cols, lits: jnp.isin(
                cols[i], jnp.asarray(values, dtype=cols[i].dtype))
        raise ValueError(f"Unsupported predicate node: {e!r}")

    fn = build(expr)
    jitted = jax.jit(lambda cols, lits: fn(cols, lits))
    # Order matters: a diverged entry must never reach the cache — later
    # identical queries would hit it and bind literals to wrong positions.
    assert literals == extracted, "literal traversal order diverged"
    if len(_PREDICATE_CACHE) >= _PREDICATE_CACHE_MAX:
        _PREDICATE_CACHE.clear()  # degenerate workload: reset, don't grow
    _PREDICATE_CACHE[key] = jitted
    return jitted, literals
