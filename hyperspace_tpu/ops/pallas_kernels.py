"""Pallas TPU kernels for the per-row hot ops of the data plane.

Two kernels, both tiled over row blocks resident in VMEM:

  - ``hash_buckets``: the fused murmur3-mix → bucket-id chain
    (reference contract ``repartition(numBuckets, cols)`` bucket
    assignment, actions/CreateActionBase.scala:131-132).  The XLA
    fallback (`hyperspace_tpu.ops.hash.combine_hashes`) emits ~10
    elementwise HLOs per key column; the pallas kernel runs the whole
    mix chain in one VMEM pass per row tile — one HBM read per input
    word, one HBM write for the bucket ids, nothing materialized in
    between.
  - ``bucket_histogram``: rows-per-bucket counts via a 2-D one-hot
    compare + row-sum per tile, accumulated across the sequential TPU
    grid.  This avoids ``segment_sum``'s scatter-add lowering, which
    XLA serializes; the one-hot compare is pure VPU work.

Both kernels run in interpret mode off-TPU (CPU CI, SURVEY.md §4
"single host" test idiom) and are exact-parity with the XLA paths —
``tests/test_pallas_kernels.py`` asserts bit-equality.

Layout: callers pass (n, 2) uint32 hash-word columns
(`hyperspace_tpu.io.columnar.to_hash_words`).  The wrapper pads n up to
a whole number of (ROWS_PER_TILE × 128) tiles and views each word
column as (rows, 128) — the native int32 VREG shape — so the kernel
never sees a ragged edge; padding rows hash to garbage that the caller
slices off.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (TPU lowering)

_LANES = 128
# 256 sublanes × 128 lanes × 4 B = 128 KiB per ref per tile — comfortably
# inside the ~16 MiB VMEM budget even with several key columns.
_HASH_TILE_ROWS = 256
# The histogram tile holds a (ROWS, 128) one-hot block: 4096 element rows
# × 128 bucket lanes × 4 B = 2 MiB.
_HIST_TILE_ROWS = 4096

# Same constants as ops/hash.py — numpy scalars so importing this module
# never initializes the JAX backend (tunnel-latency hazard, see ops/hash.py).
_C1 = np.uint32(0x85EBCA6B)
_C2 = np.uint32(0xC2B2AE35)
_SEED = np.uint32(0x3C074A61)
_THIRTY_ONE = np.uint32(31)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fmix32(h):
    h = h ^ (h >> 16)
    h = h * _C1
    h = h ^ (h >> 13)
    h = h * _C2
    h = h ^ (h >> 16)
    return h


def _hash_kernel(num_buckets: int, n_cols: int, *refs) -> None:
    """refs = [hi_0, lo_0, hi_1, lo_1, ..., out]; every block (T, 128) u32."""
    out_ref = refs[-1]
    h = jnp.full(out_ref.shape, _SEED, dtype=jnp.uint32)
    for c in range(n_cols):
        hi = refs[2 * c][...]
        lo = refs[2 * c + 1][...]
        h = _fmix32(h * _THIRTY_ONE ^ _fmix32(hi))
        h = _fmix32(h * _THIRTY_ONE ^ _fmix32(lo))
    if num_buckets:
        h = h % jnp.uint32(num_buckets)
    out_ref[...] = h


def _pad_to_tiles(flat: jnp.ndarray, tile_elems: int) -> jnp.ndarray:
    n = flat.shape[0]
    padded = -(-n // tile_elems) * tile_elems
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    return flat.reshape(-1, _LANES)


@partial(jax.jit, static_argnames=("num_buckets",))
def hash_buckets(word_cols: Sequence[jnp.ndarray], num_buckets: int = 0
                 ) -> jnp.ndarray:
    """Fused row hash (num_buckets=0) or bucket ids, as (n,) uint32.

    ``word_cols``: per key column (n, 2) uint32 hash words.  Bit-identical
    to ``ops.hash.combine_hashes`` / ``% num_buckets``.
    """
    n = word_cols[0].shape[0]
    tile_elems = _HASH_TILE_ROWS * _LANES
    flats = []
    for w in word_cols:
        flats.append(_pad_to_tiles(w[:, 0], tile_elems))
        flats.append(_pad_to_tiles(w[:, 1], tile_elems))
    rows = flats[0].shape[0]
    grid = rows // _HASH_TILE_ROWS
    spec = pl.BlockSpec((_HASH_TILE_ROWS, _LANES), lambda i: (i, 0))
    out = pl.pallas_call(
        partial(_hash_kernel, num_buckets, len(word_cols)),
        grid=(grid,),
        in_specs=[spec] * len(flats),
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.uint32),
        interpret=_interpret(),
    )(*flats)
    return out.reshape(-1)[:n]


def _hist_kernel(ids_ref, out_ref) -> None:
    """ids (T, 1) int32 column; out (1, 128) int32 — bucket-block j's counts.

    The ids come in as a COLUMN vector so the one-hot is a lane-broadcast
    compare — Mosaic has no (T, 128) → (T*128, 1) shape cast, but
    broadcasting (T, 1) against a (T, 128) lane iota is native VPU work.
    Grid is (bucket_blocks, row_tiles): the reduction dimension (row
    tiles) is MINORMOST so each output block is revisited on consecutive
    grid steps — the only accumulation order pallas TPU guarantees.
    """
    j = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _zero():
        out_ref[...] = jnp.zeros_like(out_ref)

    ids = ids_ref[...]                                      # (T, 1)
    lane = jax.lax.broadcasted_iota(jnp.int32, (ids.shape[0], _LANES), 1)
    onehot = (ids == lane + j * _LANES).astype(jnp.int32)   # broadcast compare
    out_ref[...] += jnp.sum(onehot, axis=0, keepdims=True)


@partial(jax.jit, static_argnames=("num_buckets",))
def bucket_histogram(bucket_ids: jnp.ndarray, num_buckets: int) -> jnp.ndarray:
    """(num_buckets,) int32 counts; parity with ``ops.sort.bucket_counts``.

    Padding rows are tagged with bucket id -1, which matches no lane, so
    they vanish from every count.
    """
    ids = bucket_ids.astype(jnp.int32)
    n = ids.shape[0]
    if n == 0:
        # Zero row tiles would mean the kernel (and its output zeroing)
        # never runs — the buffer would be uninitialized device memory.
        return jnp.zeros((num_buckets,), dtype=jnp.int32)
    padded = -(-n // _HIST_TILE_ROWS) * _HIST_TILE_ROWS
    if padded != n:
        ids = jnp.pad(ids, (0, padded - n), constant_values=-1)
    ids = ids.reshape(-1, 1)
    bucket_blocks = -(-num_buckets // _LANES)
    grid = (bucket_blocks, padded // _HIST_TILE_ROWS)
    out = pl.pallas_call(
        _hist_kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((_HIST_TILE_ROWS, 1), lambda j, i: (i, 0))],
        out_specs=pl.BlockSpec((1, _LANES), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((1, bucket_blocks * _LANES), jnp.int32),
        interpret=_interpret(),
    )(ids)
    return out.reshape(-1)[:num_buckets]


def bucket_ids_pallas(word_cols: Sequence[jnp.ndarray], num_buckets: int
                      ) -> jnp.ndarray:
    """Bucket assignment as int32 — drop-in for ``ops.hash.bucket_ids``."""
    return hash_buckets(word_cols, num_buckets).astype(jnp.int32)
