"""Device-side bucket-hash kernel.

This is the TPU re-expression of the reference's bucket assignment
(``repartition(numBuckets, indexedCols)`` = Murmur3Hash pmod numBuckets,
actions/CreateActionBase.scala:131-132).  We use our own murmur3-style mix —
self-consistent hashing is sufficient because indexes are only ever read by
this engine (SURVEY.md §7 "hard parts"); there is no interop with
Spark-written buckets.

Every key column is first normalized host-side to an ``(n, 2)`` uint32
"hash words" array (hyperspace_tpu.io.columnar.to_hash_words) so the device
kernel is dtype-monomorphic: one compiled program serves any key schema,
which keeps XLA's compile cache hot across heterogeneous datasets.  The
kernel itself is pure elementwise uint32 math — XLA fuses the whole chain
into a single VPU pass over HBM-resident batches.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

# numpy scalars, NOT jnp: a module-scope jnp constant would initialize the
# JAX backend at import time — with a TPU attached over a tunnel that is a
# multi-second (or, tunnel down, hanging) import of the whole package.
_C1 = np.uint32(0x85EBCA6B)
_C2 = np.uint32(0xC2B2AE35)
_SEED = np.uint32(0x3C074A61)


def _fmix32(h: jnp.ndarray) -> jnp.ndarray:
    """Murmur3 finalizer (public algorithm)."""
    h = h ^ (h >> 16)
    h = h * _C1
    h = h ^ (h >> 13)
    h = h * _C2
    h = h ^ (h >> 16)
    return h


def combine_hashes(word_cols: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """uint32 row hash from per-column (n, 2) uint32 hash words."""
    h = jnp.full(word_cols[0].shape[0], _SEED, dtype=jnp.uint32)
    for words in word_cols:
        h = _fmix32(h * jnp.uint32(31) ^ _fmix32(words[:, 0]))
        h = _fmix32(h * jnp.uint32(31) ^ _fmix32(words[:, 1]))
    return h


@partial(jax.jit, static_argnames=("num_buckets",))
def bucket_ids(word_cols: Sequence[jnp.ndarray], num_buckets: int) -> jnp.ndarray:
    """Per-row bucket assignment in [0, num_buckets) as int32."""
    h = combine_hashes(word_cols)
    return (h % jnp.uint32(num_buckets)).astype(jnp.int32)
