"""Device-side bucket-hash kernel.

This is the TPU re-expression of the reference's bucket assignment
(``repartition(numBuckets, indexedCols)`` = Murmur3Hash pmod numBuckets,
actions/CreateActionBase.scala:131-132).  We use our own murmur3-style mix —
self-consistent hashing is sufficient because indexes are only ever read by
this engine (SURVEY.md §7 "hard parts"); there is no interop with
Spark-written buckets.

Every key column is first normalized host-side to an ``(n, 2)`` uint32
"hash words" array (hyperspace_tpu.io.columnar.to_hash_words) so the device
kernel is dtype-monomorphic: one compiled program serves any key schema,
which keeps XLA's compile cache hot across heterogeneous datasets.  The
kernel itself is pure elementwise uint32 math — XLA fuses the whole chain
into a single VPU pass over HBM-resident batches.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

# numpy scalars, NOT jnp: a module-scope jnp constant would initialize the
# JAX backend at import time — with a TPU attached over a tunnel that is a
# multi-second (or, tunnel down, hanging) import of the whole package.
_C1 = np.uint32(0x85EBCA6B)
_C2 = np.uint32(0xC2B2AE35)
_SEED = np.uint32(0x3C074A61)


def _fmix32(h: jnp.ndarray) -> jnp.ndarray:
    """Murmur3 finalizer (public algorithm)."""
    h = h ^ (h >> 16)
    h = h * _C1
    h = h ^ (h >> 13)
    h = h * _C2
    h = h ^ (h >> 16)
    return h


def use_pallas() -> bool:
    """Route per-row kernels through pallas?  ``HYPERSPACE_TPU_PALLAS`` =
    on | off | auto (default).  Auto: pallas on real TPU, plain XLA
    elsewhere — interpret-mode pallas on CPU is a correctness tool, not a
    fast path, so CPU CI opts in explicitly (tests/test_pallas_kernels.py).
    """
    mode = os.environ.get("HYPERSPACE_TPU_PALLAS", "auto").lower()
    if mode == "on":
        return True
    if mode == "off":
        return False
    return jax.default_backend() == "tpu"


def combine_hashes_xla(word_cols: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Reference XLA implementation (kept for parity testing + fallback)."""
    h = jnp.full(word_cols[0].shape[0], _SEED, dtype=jnp.uint32)
    for words in word_cols:
        h = _fmix32(h * jnp.uint32(31) ^ _fmix32(words[:, 0]))
        h = _fmix32(h * jnp.uint32(31) ^ _fmix32(words[:, 1]))
    return h


def _fmix32_np(h: np.ndarray) -> np.ndarray:
    h = h ^ (h >> np.uint32(16))
    h = h * _C1
    h = h ^ (h >> np.uint32(13))
    h = h * _C2
    h = h ^ (h >> np.uint32(16))
    return h


def bucket_ids_np(word_cols: Sequence[np.ndarray], num_buckets: int) -> np.ndarray:
    """Host mirror of ``bucket_ids`` — bit-identical uint32 math in numpy
    (wrap-around multiplication is exact in both).  For tiny inputs (bucket
    pruning probes a handful of key combinations per query) a device round
    trip costs pure latency; this keeps pruning on host while provably
    agreeing with device placement (parity-tested in tests/test_ops.py)."""
    with np.errstate(over="ignore"):
        h = np.full(np.asarray(word_cols[0]).shape[0], _SEED, dtype=np.uint32)
        for words in word_cols:
            words = np.asarray(words, dtype=np.uint32)
            h = _fmix32_np(h * np.uint32(31) ^ _fmix32_np(words[:, 0]))
            h = _fmix32_np(h * np.uint32(31) ^ _fmix32_np(words[:, 1]))
    return (h % np.uint32(num_buckets)).astype(np.int32)


def combine_hashes(word_cols: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """uint32 row hash from per-column (n, 2) uint32 hash words.

    On TPU this is the fused pallas kernel (ops/pallas_kernels.py) — one
    VMEM pass over the word columns; elsewhere the plain XLA chain.  Both
    are bit-identical.
    """
    if use_pallas():
        from hyperspace_tpu.ops.pallas_kernels import hash_buckets

        return hash_buckets(tuple(word_cols), 0)
    return combine_hashes_xla(word_cols)


@partial(jax.jit, static_argnames=("num_buckets", "pallas"))
def _bucket_ids_impl(word_cols, num_buckets: int, pallas: bool) -> jnp.ndarray:
    if pallas:
        from hyperspace_tpu.ops.pallas_kernels import hash_buckets

        return hash_buckets(word_cols, num_buckets).astype(jnp.int32)
    h = combine_hashes_xla(word_cols)
    return (h % jnp.uint32(num_buckets)).astype(jnp.int32)


def bucket_ids(word_cols: Sequence[jnp.ndarray], num_buckets: int) -> jnp.ndarray:
    """Per-row bucket assignment in [0, num_buckets) as int32.

    The pallas/XLA choice is part of the jit cache key (static arg): env
    flips between calls retrace instead of silently reusing the old path.
    """
    from hyperspace_tpu.telemetry import timeline

    t0 = timeline.kernel_begin()
    out = _bucket_ids_impl(tuple(word_cols), num_buckets, use_pallas())
    timeline.kernel_end("bucket_ids", t0, out)
    return out


# ---------------------------------------------------------------------------
# Fused route+partition kernel (the external build's per-chunk pass)
# ---------------------------------------------------------------------------
@partial(jax.jit, static_argnames=("num_buckets", "pallas"))
def _route_sort_impl(
    word_cols,
    order_words,
    n_valid,
    num_buckets: int,
    pallas: bool,
) -> jnp.ndarray:  # (2, n) stacked [buckets, perm] — one host transfer
    """Hash → (bucket, *keys) stable lexsort → stacked (buckets, perm).

    THE bucket/sort program: ``ops.sort.bucket_sort_permutation`` (the
    monolithic build) and :func:`route_partition` (the external build's
    per-chunk pass) both trace exactly this function, so the two paths
    share one compiled program per capacity and can never diverge in
    bucket assignment or tie order.  ``order_words`` may be EMPTY: the
    lexsort then groups rows by bucket only, original order preserved
    within each bucket (the partition-only mode for rank-mapped key
    types whose chunk-local order words are not globally comparable).
    """
    buckets = _bucket_ids_impl(word_cols, num_buckets, pallas)
    # Capacity padding: rows at positions >= n_valid get bucket id
    # ``num_buckets`` — past every real bucket, so the stable lexsort
    # parks them after all real rows and ``perm[:n]`` is real.
    n = word_cols[0].shape[0]
    buckets = jnp.where(jnp.arange(n) < n_valid, buckets,
                        jnp.int32(num_buckets))
    # jnp.lexsort: LAST key is the primary.  Order: bucket first, then
    # key columns in config order, each (hi, lo) word pair hi-major.
    keys = []
    for w in reversed(order_words):
        keys.append(w[:, 1])
        keys.append(w[:, 0])
    keys.append(buckets)
    perm = jnp.lexsort(tuple(keys)).astype(jnp.int32)
    return jnp.stack([buckets, perm])


def _pad_host_rows(arr: np.ndarray, capacity: int) -> np.ndarray:
    arr = np.asarray(arr)
    if arr.shape[0] == capacity:
        return arr
    pad = np.zeros((capacity - arr.shape[0],) + arr.shape[1:],
                   dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def route_partition(
    word_cols: Sequence[np.ndarray],
    order_words: Sequence[np.ndarray],
    num_buckets: int,
    pad_to: int = 0,
):
    """Fused route+partition device pass for one spill chunk.

    One kernel computes bucket ids AND the permutation that groups the
    chunk's rows into per-bucket runs (sorted within bucket when
    ``order_words`` is non-empty; original order otherwise) — replacing
    the old two-step of a device ``bucket_ids`` pull followed by a host
    argsort.  Returns ``(bucket_ids, perm)`` as host int32 arrays,
    pulled in ONE stacked device→host transfer through the attributed
    ``sync_guard.pull`` seam.

    ``pad_to`` follows ``bucket_sort_permutation``'s capacity-padding
    contract (one compiled program per capacity/key-count).
    """
    from hyperspace_tpu.execution import sync_guard
    from hyperspace_tpu.telemetry import timeline
    from hyperspace_tpu.utils.xla_cache import ensure_persistent_xla_cache

    ensure_persistent_xla_cache()
    n = int(word_cols[0].shape[0])
    if pad_to and pad_to > 0:
        capacity = -(-max(n, 1) // pad_to) * pad_to
        word_cols = [_pad_host_rows(w, capacity) for w in word_cols]
        order_words = [_pad_host_rows(w, capacity) for w in order_words]
    t0 = timeline.kernel_begin()
    if t0 is not None:
        timeline.record_transfer("h2d", sum(
            int(getattr(a, "nbytes", 0))
            for a in (*word_cols, *order_words)
            if not isinstance(a, jax.Array)))
    out = _route_sort_impl(
        tuple(word_cols), tuple(order_words), n, num_buckets, use_pallas())
    timeline.kernel_end("route_partition", t0, out)
    stacked = sync_guard.pull(out, "route.partition")
    return stacked[0, :n], stacked[1, :n]


def route_partition_mesh(
    word_cols: Sequence[np.ndarray],
    order_words: Sequence[np.ndarray],
    num_buckets: int,
    mesh,
    pad_to: int = 0,
):
    """Sharding-aware entry of the fused route+partition: the SAME
    ``(bucket_ids, perm)`` contract (bit-identical output — layout can
    never depend on the route), computed over ``mesh`` with per-device
    bucket ownership ``bucket_id % n_devices`` and a host gather seam of
    one attributed pull per device (parallel/sharded_build.py)."""
    from hyperspace_tpu.parallel.sharded_build import mesh_route_partition

    return mesh_route_partition(word_cols, order_words, num_buckets,
                                mesh, pad_to=pad_to)


def route_partition_np(
    word_cols: Sequence[np.ndarray],
    order_words: Sequence[np.ndarray],
    num_buckets: int,
):
    """Bit-identical HOST mirror of :func:`route_partition` (the same
    cost model as ``bucket_sort_permutation_np``: below the calibrated
    build threshold a per-chunk device round trip costs pure latency).
    Shares ``bucket_ids_np`` and the identical stable-lexsort ordering,
    so chunk layout can never depend on where it was computed.

    The host lexsort keys on ONE uint64 per column — the same total
    order as the (hi, lo) uint32 pair in half the stable-sort passes
    (numpy is 64-bit native; the 32-bit split exists for the TPU's VPU
    lanes).  ``order_words`` items may be either (n, 2) uint32 word
    pairs or (n,) uint64 codes (``columnar.to_order_codes64``) —
    callers that already hold the joined form skip the round trip."""
    with np.errstate(over="ignore"):
        buckets = bucket_ids_np([np.asarray(w) for w in word_cols],
                                num_buckets)
    keys = []
    for w in reversed(list(order_words)):
        w = np.asarray(w)
        keys.append(w if w.ndim == 1
                    else (w[:, 0].astype(np.uint64) << np.uint64(32))
                    | w[:, 1].astype(np.uint64))
    keys.append(buckets)
    perm = np.lexsort(tuple(keys)).astype(np.int32)
    return buckets.astype(np.int32), perm
