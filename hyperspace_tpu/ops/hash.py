"""Device-side bucket-hash kernel.

This is the TPU re-expression of the reference's bucket assignment
(``repartition(numBuckets, indexedCols)`` = Murmur3Hash pmod numBuckets,
actions/CreateActionBase.scala:131-132).  We use our own murmur3-style mix —
self-consistent hashing is sufficient because indexes are only ever read by
this engine (SURVEY.md §7 "hard parts"); there is no interop with
Spark-written buckets.

Every key column is first normalized host-side to an ``(n, 2)`` uint32
"hash words" array (hyperspace_tpu.io.columnar.to_hash_words) so the device
kernel is dtype-monomorphic: one compiled program serves any key schema,
which keeps XLA's compile cache hot across heterogeneous datasets.  The
kernel itself is pure elementwise uint32 math — XLA fuses the whole chain
into a single VPU pass over HBM-resident batches.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

# numpy scalars, NOT jnp: a module-scope jnp constant would initialize the
# JAX backend at import time — with a TPU attached over a tunnel that is a
# multi-second (or, tunnel down, hanging) import of the whole package.
_C1 = np.uint32(0x85EBCA6B)
_C2 = np.uint32(0xC2B2AE35)
_SEED = np.uint32(0x3C074A61)


def _fmix32(h: jnp.ndarray) -> jnp.ndarray:
    """Murmur3 finalizer (public algorithm)."""
    h = h ^ (h >> 16)
    h = h * _C1
    h = h ^ (h >> 13)
    h = h * _C2
    h = h ^ (h >> 16)
    return h


def use_pallas() -> bool:
    """Route per-row kernels through pallas?  ``HYPERSPACE_TPU_PALLAS`` =
    on | off | auto (default).  Auto: pallas on real TPU, plain XLA
    elsewhere — interpret-mode pallas on CPU is a correctness tool, not a
    fast path, so CPU CI opts in explicitly (tests/test_pallas_kernels.py).
    """
    mode = os.environ.get("HYPERSPACE_TPU_PALLAS", "auto").lower()
    if mode == "on":
        return True
    if mode == "off":
        return False
    return jax.default_backend() == "tpu"


def combine_hashes_xla(word_cols: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Reference XLA implementation (kept for parity testing + fallback)."""
    h = jnp.full(word_cols[0].shape[0], _SEED, dtype=jnp.uint32)
    for words in word_cols:
        h = _fmix32(h * jnp.uint32(31) ^ _fmix32(words[:, 0]))
        h = _fmix32(h * jnp.uint32(31) ^ _fmix32(words[:, 1]))
    return h


def _fmix32_np(h: np.ndarray) -> np.ndarray:
    h = h ^ (h >> np.uint32(16))
    h = h * _C1
    h = h ^ (h >> np.uint32(13))
    h = h * _C2
    h = h ^ (h >> np.uint32(16))
    return h


def bucket_ids_np(word_cols: Sequence[np.ndarray], num_buckets: int) -> np.ndarray:
    """Host mirror of ``bucket_ids`` — bit-identical uint32 math in numpy
    (wrap-around multiplication is exact in both).  For tiny inputs (bucket
    pruning probes a handful of key combinations per query) a device round
    trip costs pure latency; this keeps pruning on host while provably
    agreeing with device placement (parity-tested in tests/test_ops.py)."""
    with np.errstate(over="ignore"):
        h = np.full(np.asarray(word_cols[0]).shape[0], _SEED, dtype=np.uint32)
        for words in word_cols:
            words = np.asarray(words, dtype=np.uint32)
            h = _fmix32_np(h * np.uint32(31) ^ _fmix32_np(words[:, 0]))
            h = _fmix32_np(h * np.uint32(31) ^ _fmix32_np(words[:, 1]))
    return (h % np.uint32(num_buckets)).astype(np.int32)


def combine_hashes(word_cols: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """uint32 row hash from per-column (n, 2) uint32 hash words.

    On TPU this is the fused pallas kernel (ops/pallas_kernels.py) — one
    VMEM pass over the word columns; elsewhere the plain XLA chain.  Both
    are bit-identical.
    """
    if use_pallas():
        from hyperspace_tpu.ops.pallas_kernels import hash_buckets

        return hash_buckets(tuple(word_cols), 0)
    return combine_hashes_xla(word_cols)


@partial(jax.jit, static_argnames=("num_buckets", "pallas"))
def _bucket_ids_impl(word_cols, num_buckets: int, pallas: bool) -> jnp.ndarray:
    if pallas:
        from hyperspace_tpu.ops.pallas_kernels import hash_buckets

        return hash_buckets(word_cols, num_buckets).astype(jnp.int32)
    h = combine_hashes_xla(word_cols)
    return (h % jnp.uint32(num_buckets)).astype(jnp.int32)


def bucket_ids(word_cols: Sequence[jnp.ndarray], num_buckets: int) -> jnp.ndarray:
    """Per-row bucket assignment in [0, num_buckets) as int32.

    The pallas/XLA choice is part of the jit cache key (static arg): env
    flips between calls retrace instead of silently reusing the old path.
    """
    from hyperspace_tpu.telemetry import timeline

    t0 = timeline.kernel_begin()
    out = _bucket_ids_impl(tuple(word_cols), num_buckets, use_pallas())
    timeline.kernel_end("bucket_ids", t0, out)
    return out
