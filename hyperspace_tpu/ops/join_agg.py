"""Fused device join→aggregate pipeline: the whole Q3/Q10 hot path on chip.

The north-star workloads (BASELINE.md: TPC-H Q3/Q10 wall-clock) are
``aggregate(filter ⨝ index)`` shapes.  Executing the join and the
aggregation as separate engines forces the full joined row set through
host memory — and over a narrow attachment, back across the wire.  This
pipeline keeps the intermediate entirely in HBM:

  1. sorted equi-join over the (resident) key columns — searchsorted
     match ranges, one host sync for the match count (the standard XLA
     dynamic-shape point, same as ops/join.py);
  2. device gather of every referenced column through the match indices
     (group keys, aggregate inputs) — the joined table never
     materializes anywhere;
  3. expression aggregate inputs (sum(price * (1 - discount))) evaluated
     elementwise on the gathered arrays (ops/filter.build_value_fn);
  4. group-by via the segment machinery (ops/aggregate._group_sort /
     _segment_reduce) — second host sync for the group count;
  5. only the per-group results cross back to host: counts, reductions,
     and one (left, right) row-index pair per group so the executor can
     take the group-key VALUES from the host arrow tables in their exact
     original types.

Reference contract: Spark executes the rewritten plans of
JoinIndexRule.scala:36-50 as exchange-free SMJ + HashAggregate; this is
the TPU-native fusion of the two with O(groups) — not O(matches) —
host traffic.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hyperspace_tpu.execution import sync_guard
from hyperspace_tpu.ops.aggregate import _group_sort, _segment_reduce
from hyperspace_tpu.utils.compat import enable_x64 as _enable_x64
from hyperspace_tpu.ops.join import _expand, _match_ranges
from hyperspace_tpu.utils.shapes import round_up_pow2


@partial(jax.jit, static_argnames=("k", "ascending", "capacity"))
def _topk_groups(col, n_valid, *, k: int, ascending: bool,
                 capacity: int):
    """Indices of the top/bottom-k VALID group slots by ``col`` —
    the device form of ORDER BY <agg> LIMIT k, so only k groups (not
    all of them) ever cross the attachment.  Invalid (padding) slots
    are parked with sentinels; ``k`` and the capacity are static, the
    valid count is traced."""
    valid = jnp.arange(capacity) < n_valid
    if jnp.issubdtype(col.dtype, jnp.floating):
        sentinel = jnp.array(-jnp.inf, dtype=col.dtype)
        work = col if not ascending else -col
        # NaN must map to the sentinel BEFORE top_k: lax.top_k ranks NaN
        # unpredictably, so an ORDER BY <agg> LIMIT k could otherwise
        # pick different boundary groups than the host sort.  (-NaN is
        # still NaN, so one check after the flip covers both orders.)
        work = jnp.where(jnp.isnan(work), sentinel, work)
    else:
        sentinel = jnp.iinfo(col.dtype).min
        # Ascending via BITWISE not (monotone decreasing, total on the
        # whole domain): arithmetic negation overflows at iinfo.min, so
        # ORDER BY <agg> ASC could mis-rank a group whose count/sum hit
        # the extreme value.
        work = col if not ascending else ~col
    work = jnp.where(valid, work, sentinel)
    _vals, idx = jax.lax.top_k(work, k)
    return idx


def _int_order_words(x: jnp.ndarray) -> jnp.ndarray:
    """(n, 2) uint32 monotone order words from an int64-domain array
    (ints, bools, temporals in their numeric normalization): flip the
    sign bit, split halves.  Bit layout matches what the group sort
    needs — any monotone injective encoding works, order falls out."""
    ux = (x.astype(jnp.int64) ^ jnp.int64(-(2 ** 63))).astype(jnp.uint64)
    hi = (ux >> np.uint64(32)).astype(jnp.uint32)
    lo = (ux & np.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    return jnp.stack([hi, lo], axis=-1)


def _int_order_words_np(x: np.ndarray) -> np.ndarray:
    """Host mirror of :func:`_int_order_words` — the same (n, 2) uint32
    monotone encoding, bit-identical, for the sharded wrapper's
    group-key partitioning."""
    with np.errstate(over="ignore"):
        ux = (x.astype(np.int64) ^ np.int64(-(2 ** 63))).astype(np.uint64)
        hi = (ux >> np.uint64(32)).astype(np.uint32)
        lo = (ux & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return np.stack([hi, lo], axis=-1)


def join_group_aggregate_mesh(
    l_key,
    r_key,
    columns: Sequence,
    column_sides: Sequence[str],
    group_col_ix: Sequence[int],
    agg_ops: Sequence[str],
    value_fns: Sequence[Callable],
    literals: Sequence[Sequence[float]],
    mesh,
    pad_to: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, List[np.ndarray]]:
    """Sharding-aware entry of the join→aggregate pipeline: the same
    result contract as :func:`join_group_aggregate` (groups ascending by
    key), computed as three mesh stages —

      1. co-partitioned inner join by join-key bucket ownership
         (``ops.join.sorted_equi_join_mesh``: zero cross-device shuffle,
         only the match-index gather),
      2. elementwise aggregate-input evaluation sharded row-wise over
         the mesh (GSPMD partitions the expression with zero
         collectives, ``parallel/filter.eval_predicate_on_mesh``),
      3. grouped aggregation with GROUP-key bucket ownership
         (``parallel/aggregate.mesh_grouped_aggregate`` — each group is
         reduced whole on one device, no partial-merge pass).

    Unlike the fused single-device kernel the joined intermediate
    transits host between stages (O(matches) traffic — the price of
    re-partitioning from join-key to group-key ownership); the win is
    that every stage scales with the mesh.  ``topn`` fusion is not
    supported — callers wanting it keep the single-device kernel.
    Host inputs only (resident arrays keep the fused kernel)."""
    from hyperspace_tpu.ops.join import sorted_equi_join_mesh
    from hyperspace_tpu.parallel.aggregate import mesh_grouped_aggregate
    from hyperspace_tpu.parallel.filter import eval_predicate_on_mesh

    l_key = np.asarray(l_key)
    r_key = np.asarray(r_key)
    host_cols = [np.asarray(c) for c in columns]

    def _empty():
        return (np.empty(0, np.int64), np.empty(0, np.int64),
                np.empty(0, np.int32), [np.empty(0) for _ in agg_ops])

    if l_key.shape[0] == 0 or r_key.shape[0] == 0:
        return _empty()
    li, ri = sorted_equi_join_mesh(l_key, r_key, mesh)
    if li.size == 0:
        return _empty()
    gathered = [c[li if side == "l" else ri]
                for c, side in zip(host_cols, column_sides)]
    key_words = [_int_order_words_np(gathered[i]) for i in group_col_ix]
    # Literal dtype follows numpy inference (all-int vectors stay
    # integral), exactly like the fused kernel's literal handling.
    value_cols = [
        np.asarray(eval_predicate_on_mesh(
            fn, gathered,
            np.asarray(lits) if lits else np.zeros(0), mesh))
        for fn, lits in zip(value_fns, literals)]
    first_rows, counts, results = mesh_grouped_aggregate(
        key_words, value_cols, agg_ops, mesh, pad_to=pad_to)
    li_first = li[first_rows.astype(np.int64)]
    ri_first = ri[first_rows.astype(np.int64)]
    return li_first, ri_first, counts, results


def join_group_aggregate(
    l_key,
    r_key,
    columns: Sequence,
    column_sides: Sequence[str],
    group_col_ix: Sequence[int],
    agg_ops: Sequence[str],
    value_fns: Sequence[Callable],
    literals: Sequence[Sequence[float]],
    topn: Optional[Tuple[int, bool, int]] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, List[np.ndarray]]:
    """Inner-join two sides on single numeric keys, then group-aggregate
    the joined rows — all on device.

    Args:
      l_key/r_key: numeric key arrays (device-resident jax arrays pass
        through untouched; numpy ships once).
      columns: referenced column arrays, each tagged "l"/"r" in
        ``column_sides`` (lengths match their side's key).
      group_col_ix: indices into ``columns`` forming the group key, in
        group-by order (int64-domain values).
      agg_ops: per aggregate, one of sum/min/max/mean/count/count_all.
      value_fns/literals: per NON-count aggregate, an elementwise
        builder over the gathered columns (ops/filter.build_value_fn)
        and its literal vector.
      topn: optional (agg_index, ascending, k) — keep only the k
        groups ranking first by that aggregate's result (ORDER BY
        <agg> LIMIT k fused on device; host traffic drops from
        O(groups) to O(k)).

    Returns:
      (li_first, ri_first, counts, results): per group, the ORIGINAL
      (left, right) row indices of its first joined row — the executor
      takes group-key values from the host tables with these — plus row
      counts and one result array per aggregate.
    """
    from hyperspace_tpu.telemetry import timeline
    from hyperspace_tpu.utils.xla_cache import ensure_persistent_xla_cache

    ensure_persistent_xla_cache()
    t0 = timeline.kernel_begin()
    if t0 is not None:
        # Attribution seam (conf-gated): host inputs are about to ship.
        timeline.record_transfer("h2d", sum(
            int(getattr(a, "nbytes", 0))
            for a in (l_key, r_key, *columns)
            if not isinstance(a, jax.Array)))
    with _enable_x64():
        lk = jnp.asarray(l_key)
        rk = jnp.asarray(r_key)
        if lk.shape[0] == 0 or rk.shape[0] == 0:
            return (np.empty(0, np.int64), np.empty(0, np.int64),
                    np.empty(0, np.int32), [np.empty(0) for _ in agg_ops])
        r_perm = jnp.argsort(rk)
        lo, hi = _match_ranges(lk, rk[r_perm])
        # sync 1: match count (the standard XLA dynamic-shape point)
        total = int(sync_guard.scalar(jnp.sum(hi - lo), "join_agg.matches"))
        if total == 0:
            timeline.kernel_end("join_agg", t0, (lo, hi))
            return (np.empty(0, np.int64), np.empty(0, np.int64),
                    np.empty(0, np.int32), [np.empty(0) for _ in agg_ops])
        capacity = round_up_pow2(total)
        li, right_pos = _expand(lo, hi, capacity)
        ri = r_perm[jnp.clip(right_pos, 0, rk.shape[0] - 1)]
        gathered = [
            jnp.asarray(c)[li if side == "l" else ri]
            for c, side in zip(columns, column_sides)]
        key_words = tuple(_int_order_words(gathered[i])
                          for i in group_col_ix)
        # Literal dtype follows numpy inference: all-int literal vectors
        # stay integral so int expression aggregates don't silently
        # promote to float (host arrow keeps them int64).
        value_cols = tuple(
            fn(gathered, jnp.asarray(np.asarray(lits))
               if lits else jnp.zeros(0))
            for fn, lits in zip(value_fns, literals))
        perm, boundaries, n_groups = _group_sort(key_words, total)
        # sync 2: group count
        g = int(sync_guard.scalar(n_groups, "join_agg.groups"))
        if g == 0:
            timeline.kernel_end("join_agg", t0, perm)
            return (np.empty(0, np.int64), np.empty(0, np.int64),
                    np.empty(0, np.int32), [np.empty(0) for _ in agg_ops])
        gcap = round_up_pow2(g)
        out = _segment_reduce(perm, boundaries, total, value_cols,
                              ops=tuple(agg_ops), capacity=gcap)
        if topn is not None:
            agg_i, ascending, k = topn
            k_eff = min(int(k), g)
            sel = _topk_groups(out[2 + agg_i], g, k=k_eff,
                               ascending=bool(ascending), capacity=gcap)
            timeline.kernel_end("join_agg", t0, (out, sel))
            first_rows = out[0][sel]
            li_first = sync_guard.pull(
                li[first_rows], "join_agg.li_first").astype(np.int64)
            ri_first = sync_guard.pull(
                ri[first_rows], "join_agg.ri_first").astype(np.int64)
            counts = sync_guard.pull(out[1][sel], "join_agg.counts")
            results = [sync_guard.pull(r[sel], "join_agg.results")
                       for r in out[2:]]
            return li_first, ri_first, counts, results
        timeline.kernel_end("join_agg", t0, out)
        first_rows = out[0][:g]
        li_first = sync_guard.pull(
            li[first_rows], "join_agg.li_first").astype(np.int64)
        ri_first = sync_guard.pull(
            ri[first_rows], "join_agg.ri_first").astype(np.int64)
        counts = sync_guard.pull(out[1], "join_agg.counts")[:g]
        results = [sync_guard.pull(r, "join_agg.results")[:g]
                   for r in out[2:]]
    return li_first, ri_first, counts, results
