"""Z-order (Morton) code computation.

Multi-column covering indexes sorted lexicographically only cluster the
FIRST indexed column; range predicates on the others touch every file.
Z-ordering interleaves the bits of all indexed columns' rank codes so file
value-ranges stay narrow on EVERY dimension — per-file min/max sketches
then prune files for range queries on any indexed column
(BASELINE.json's Z-order config; capability beyond the reference snapshot).

Pipeline (host-side: global dense ranks need a global pass, and the codes
double as the writer's Z-cell-aligned file-split keys —
io/parquet.zorder_codes_host):
  1. per column: dense rank via stable argsort of the 64-bit monotone order
     words (hyperspace_tpu.io.columnar.to_order_words);
  2. ranks are scaled to 16 bits (quantile-uniform by construction: ranks
     are dense), float32-exact up to 2^24 rows;
  3. bit interleave of K x 16-bit codes into a (hi, lo) uint32 pair.

The resulting (n, 2) words feed the device build kernel as ONE precomputed
order column (ops/sort.bucket_sort_permutation) — the device sorts by the
code but never re-ranks, so layout and split keys can never diverge.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

MAX_ZORDER_COLUMNS = 4  # 4 x 16 bits = the 64-bit (hi, lo) code


def zorder_order_words_np(order_words: Sequence[np.ndarray]) -> np.ndarray:
    """Host-side Morton words as ONE synthetic (n, 2) order column — the
    distributed build feeds this to the bucket shuffle, whose per-device
    lexsort then yields Z-order within buckets.  Global ranks need a global
    sort, so this runs before the data is sharded; the query side never
    recomputes codes (file pruning reads min/max sketches), so build paths
    need self-consistency, not cross-path bit-parity."""
    k_cols = len(order_words)
    if not 1 <= k_cols <= MAX_ZORDER_COLUMNS:
        raise ValueError(
            f"Z-order supports 1..{MAX_ZORDER_COLUMNS} columns, got {k_cols}")
    n = order_words[0].shape[0]
    denom = np.float32(max(n - 1, 1))
    codes = []
    for w in order_words:
        w = np.asarray(w, dtype=np.uint32)
        key = (w[:, 0].astype(np.uint64) << np.uint64(32)) | w[:, 1]
        rank = np.empty(n, np.int64)
        rank[np.argsort(key, kind="stable")] = np.arange(n)
        codes.append(np.clip(rank.astype(np.float32) * (np.float32(65535.0) / denom),
                             0, 65535).astype(np.uint32))
    hi, lo = interleave16_np(codes)
    return np.stack([hi, lo], axis=1)


def interleave16_np(codes: List[np.ndarray]) -> Tuple[np.ndarray, np.ndarray]:
    """Host reference of the bit interleave (parity tests)."""
    k_cols = len(codes)
    n = codes[0].shape[0]
    hi = np.zeros(n, np.uint64)
    lo = np.zeros(n, np.uint64)
    for j in range(16):
        for k, code in enumerate(codes):
            bit = (code.astype(np.uint64) >> j) & 1
            pos = j * k_cols + (k_cols - 1 - k)
            if pos < 32:
                lo |= bit << pos
            else:
                hi |= bit << (pos - 32)
    return hi.astype(np.uint32), lo.astype(np.uint32)
