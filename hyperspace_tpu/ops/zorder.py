"""Z-order (Morton) clustering kernel.

Multi-column covering indexes sorted lexicographically only cluster the
FIRST indexed column; range predicates on the others touch every file.
Z-ordering interleaves the bits of all indexed columns' rank codes so file
value-ranges stay narrow on EVERY dimension — per-file min/max sketches
then prune files for range queries on any indexed column
(BASELINE.json's Z-order config; capability beyond the reference snapshot).

Pipeline (all on device, fused into the build program by XLA):
  1. per column: dense rank via double argsort of the 64-bit monotone order
     words (hyperspace_tpu.io.columnar.to_order_words) — padded rows are
     forced to sort last so real ranks stay dense in [0, n_valid);
  2. ranks are scaled to 16 bits (quantile-uniform by construction: ranks
     are dense), float32-exact up to 2^24 rows per batch;
  3. bit interleave of K x 16-bit codes into a (hi, lo) uint32 pair — pure
     VPU shift/or work, the kind of elementwise uint32 math TPU eats.

Everything is 32-bit; no x64 emulation anywhere.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

MAX_ZORDER_COLUMNS = 4  # 4 x 16 bits = the 64-bit (hi, lo) code


def _ranks(order_words: jnp.ndarray, n_valid) -> jnp.ndarray:
    """Dense rank of each row's 64-bit key, padded rows ranked last."""
    n = order_words.shape[0]
    pad = jnp.arange(n) >= n_valid
    hi = jnp.where(pad, jnp.uint32(0xFFFFFFFF), order_words[:, 0])
    lo = jnp.where(pad, jnp.uint32(0xFFFFFFFF), order_words[:, 1])
    perm = jnp.lexsort((lo, hi))  # stable: ties broken by position, so
    # equal-key real rows (earlier positions) rank before padding.
    return jnp.zeros(n, jnp.int32).at[perm].set(
        jnp.arange(n, dtype=jnp.int32))


def _rank16(rank: jnp.ndarray, n_valid) -> jnp.ndarray:
    """Scale dense ranks to [0, 65535].  float32 is exact for ranks < 2^24
    (device_batch_rows is far below that)."""
    denom = jnp.maximum(n_valid - 1, 1).astype(jnp.float32)
    return jnp.clip((rank.astype(jnp.float32) * (65535.0 / denom)),
                    0, 65535).astype(jnp.uint32)


def zorder_words(order_words: Sequence[jnp.ndarray],
                 n_valid) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(hi, lo) uint32 Morton words for rows whose per-column 64-bit keys
    are ``order_words`` (each (n, 2) uint32).  Bit j of column k lands at
    interleaved position j*K + (K-1-k), so earlier config columns take the
    more significant bits within each level."""
    k_cols = len(order_words)
    if not 1 <= k_cols <= MAX_ZORDER_COLUMNS:
        raise ValueError(
            f"Z-order supports 1..{MAX_ZORDER_COLUMNS} columns, got {k_cols}")
    codes = [_rank16(_ranks(w, n_valid), n_valid) for w in order_words]
    n = order_words[0].shape[0]
    hi = jnp.zeros(n, jnp.uint32)
    lo = jnp.zeros(n, jnp.uint32)
    for j in range(16):
        for k, code in enumerate(codes):
            bit = (code >> jnp.uint32(j)) & jnp.uint32(1)
            pos = j * k_cols + (k_cols - 1 - k)
            if pos < 32:
                lo = lo | (bit << jnp.uint32(pos))
            else:
                hi = hi | (bit << jnp.uint32(pos - 32))
    return hi, lo


def zorder_order_words_np(order_words: Sequence[np.ndarray]) -> np.ndarray:
    """Host-side Morton words as ONE synthetic (n, 2) order column — the
    distributed build feeds this to the bucket shuffle, whose per-device
    lexsort then yields Z-order within buckets.  Global ranks need a global
    sort, so this runs before the data is sharded; the query side never
    recomputes codes (file pruning reads min/max sketches), so build paths
    need self-consistency, not cross-path bit-parity."""
    k_cols = len(order_words)
    if not 1 <= k_cols <= MAX_ZORDER_COLUMNS:
        raise ValueError(
            f"Z-order supports 1..{MAX_ZORDER_COLUMNS} columns, got {k_cols}")
    n = order_words[0].shape[0]
    denom = np.float32(max(n - 1, 1))
    codes = []
    for w in order_words:
        w = np.asarray(w, dtype=np.uint32)
        key = (w[:, 0].astype(np.uint64) << np.uint64(32)) | w[:, 1]
        rank = np.empty(n, np.int64)
        rank[np.argsort(key, kind="stable")] = np.arange(n)
        codes.append(np.clip(rank.astype(np.float32) * (np.float32(65535.0) / denom),
                             0, 65535).astype(np.uint32))
    hi, lo = interleave16_np(codes)
    return np.stack([hi, lo], axis=1)


def interleave16_np(codes: List[np.ndarray]) -> Tuple[np.ndarray, np.ndarray]:
    """Host reference of the bit interleave (parity tests)."""
    k_cols = len(codes)
    n = codes[0].shape[0]
    hi = np.zeros(n, np.uint64)
    lo = np.zeros(n, np.uint64)
    for j in range(16):
        for k, code in enumerate(codes):
            bit = (code.astype(np.uint64) >> j) & 1
            pos = j * k_cols + (k_cols - 1 - k)
            if pos < 32:
                lo |= bit << pos
            else:
                hi |= bit << (pos - 32)
    return hi.astype(np.uint32), lo.astype(np.uint32)
