"""Physical-operator analysis for explain.

Reference contract: PhysicalOperatorAnalyzer.scala:30-58 counts PHYSICAL
operators of both compiled plans and spells out the expensive ones
(Shuffle/BroadcastExchange) so users see WHY the indexed plan wins.  Our
engine makes its physical choices in the executor at run time; this module
predicts them statically from the optimized plan using the executor's own
applicability checks (execution/executor.bucketed_join_precheck), so the
predicted operator can never diverge from the executed one — plus per-scan
file and byte counts, the numbers a pruning engine's users actually want.
"""

from __future__ import annotations

import os
from collections import Counter
from typing import List, Optional, Tuple

from hyperspace_tpu.io import columnar
from hyperspace_tpu.io.parquet import bucket_id_of_file, schema_to_arrow
from hyperspace_tpu.plan.nodes import (
    Aggregate,
    BucketUnion,
    Compute,
    Distinct,
    Filter,
    InMemory,
    Join,
    Limit,
    LogicalPlan,
    Project,
    Scan,
    Sort,
    Union,
    WithColumns,
)


def _scan_detail(session, scan: Scan) -> Tuple[str, str]:
    """(operator name, detail) for a scan: files read / listed and bytes,
    honoring bucket pruning and sketch pruning annotations."""
    rel = scan.relation
    name = "IndexScanExec" if rel.index_scan_of else "FileScanExec"
    target = rel.index_scan_of or ",".join(rel.root_paths)
    if rel.file_paths is not None:
        paths = list(rel.file_paths)
    else:
        try:
            from hyperspace_tpu.io.files import list_data_files

            paths = [f.name for f in list_data_files(rel.root_paths)]
        except OSError:
            return name, target
    total = len(paths)
    if rel.prune_to_buckets is not None:
        wanted = set(rel.prune_to_buckets)
        paths = [p for p in paths
                 if (b := bucket_id_of_file(p)) is None or b in wanted]
    read_bytes = 0
    for p in paths:
        try:
            read_bytes += os.path.getsize(p)
        except OSError:
            pass
    mb = read_bytes / (1024 * 1024)
    stats = rel.data_skipping_stats
    if stats is not None:
        total = max(total, stats[1])
    return name, f"{target}: files {len(paths)}/{total}, {mb:.2f} MB"


def _join_key_types(session, plan: Join):
    """Arrow types of the (single-pair) join keys, resolved against the
    leaf scans' schemas; (None, None) when unresolvable."""
    from hyperspace_tpu.plan.expr import as_equi_join_pairs

    pairs = as_equi_join_pairs(plan.condition)
    if pairs is None or len(pairs) != 1:
        return None, None
    by_name = {}
    for leaf in plan.leaf_relations():
        try:
            for col, t in session.schema_map_of(leaf).items():
                by_name.setdefault(col.lower(), t)
        except Exception:
            continue
    a, b = pairs[0]
    return by_name.get(a.lower()), by_name.get(b.lower())


def _join_operator(session, plan: Join) -> str:
    """The strategy the executor will take, named like Spark's physical
    operators — decided by the executor's OWN precheck."""
    from hyperspace_tpu.execution.executor import bucketed_join_precheck
    from hyperspace_tpu.plan.expr import as_equi_join_pairs

    try:
        if bucketed_join_precheck(session, plan) is not None:
            return "PerBucketMergeJoinExec"  # shuffle-free, bucket-aligned
    except Exception:
        pass
    pairs = as_equi_join_pairs(plan.condition)
    if pairs is not None and len(pairs) == 1:
        lt, rt = _join_key_types(session, plan)
        if lt is not None and rt is not None:
            try:
                is_num = (columnar.is_numeric_type(
                    schema_to_arrow({"c": lt}).field(0).type)
                    and columnar.is_numeric_type(
                        schema_to_arrow({"c": rt}).field(0).type))
            except Exception:
                is_num = False
            if is_num:
                return "SortMergeJoinExec"
    return "DigestHashJoinExec"  # composite/string keys (exact, verified)


def physical_operators(session, plan: Optional[LogicalPlan]
                       ) -> Tuple[Counter, List[str]]:
    """(operator counts, per-scan detail lines) for one optimized plan."""
    counts: Counter = Counter()
    details: List[str] = []
    if plan is None:
        return counts, details

    def walk(node: LogicalPlan) -> None:
        if isinstance(node, Scan):
            name, detail = _scan_detail(session, node)
            counts[name] += 1
            details.append(detail)
        elif isinstance(node, Join):
            counts[_join_operator(session, node)] += 1
        elif isinstance(node, Aggregate):
            counts["HashAggregateExec"] += 1
        elif isinstance(node, Distinct):
            counts["DistinctExec"] += 1
        elif isinstance(node, Sort):
            counts["SortExec"] += 1
        elif isinstance(node, Limit):
            counts["LimitExec"] += 1
        elif isinstance(node, Filter):
            counts["FilterExec"] += 1
        elif isinstance(node, Project):
            counts["ProjectExec"] += 1
        elif isinstance(node, (Compute, WithColumns)):
            counts["ProjectExec"] += 1  # computed projection, same phys op
        elif isinstance(node, BucketUnion):
            counts["BucketUnionExec"] += 1
        elif isinstance(node, Union):
            counts["UnionExec"] += 1
        elif isinstance(node, InMemory):
            counts["InMemoryExec"] += 1
        else:
            counts[type(node).__name__] += 1
        for c in node.children:
            walk(c)

    walk(plan)
    return counts, details
