"""Display modes for the explain output.

Reference contract: index/plananalysis/DisplayMode.scala:61-89 — PlainText
highlights changed plan sections with ``<----``/``---->``, HTML wraps the
output in ``<pre>`` and highlights with a green ``<b>``, Console uses ANSI
green background; custom highlight tags from conf override the mode default
(DisplayMode.scala:46-55).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Tag:
    open: str
    close: str


class DisplayMode:
    highlight_tag: Tag = Tag("", "")
    begin_end_tag: Tag = Tag("", "")
    new_line: str = "\n"

    def __init__(self, conf=None) -> None:
        # Both tags must be set for the override to apply — a lone tag keeps
        # the mode default (getHighlightTagOrElse, DisplayMode.scala:46-55).
        begin = getattr(conf, "highlight_begin_tag", "") if conf else ""
        end = getattr(conf, "highlight_end_tag", "") if conf else ""
        if begin and end:
            self.highlight_tag = Tag(begin, end)


class PlainTextMode(DisplayMode):
    def __init__(self, conf=None) -> None:
        self.highlight_tag = Tag("<----", "---->")
        super().__init__(conf)


class HTMLMode(DisplayMode):
    begin_end_tag = Tag("<pre>", "</pre>")
    new_line = "<br>"

    def __init__(self, conf=None) -> None:
        self.highlight_tag = Tag('<b style="background:LightGreen">', "</b>")
        super().__init__(conf)


class ConsoleMode(DisplayMode):
    def __init__(self, conf=None) -> None:
        self.highlight_tag = Tag("\033[42m", "\033[0m")
        super().__init__(conf)


_MODES = {"plaintext": PlainTextMode, "html": HTMLMode, "console": ConsoleMode}


def get_display_mode(conf) -> DisplayMode:
    """PlanAnalyzer.getDisplayMode analog: conf-selected, defaulting to
    plain text."""
    name = getattr(conf, "display_mode", "plaintext").lower()
    mode = _MODES.get(name)
    if mode is None:
        raise ValueError(
            f"Unknown display mode {name!r}; expected one of {sorted(_MODES)}")
    return mode(conf)


class BufferStream:
    """String builder aware of the display mode's newline and highlight tags
    (BufferStream.scala:20-80)."""

    def __init__(self, mode: DisplayMode) -> None:
        self._mode = mode
        self._parts: list = []

    def write(self, s: str = "") -> "BufferStream":
        self._parts.append(s)
        return self

    def write_line(self, s: str = "") -> "BufferStream":
        self._parts.append(s)
        self._parts.append(self._mode.new_line)
        return self

    def highlight(self, s: str) -> "BufferStream":
        """Highlight ``s``, keeping leading/trailing whitespace outside the
        tags (indentation must stay aligned across modes)."""
        stripped = s.strip()
        if not stripped:
            return self.write(s)
        start = s.index(stripped[0])
        end = start + len(stripped)
        tag = self._mode.highlight_tag
        return self.write(s[:start] + tag.open + stripped + tag.close + s[end:])

    def with_tag(self) -> str:
        """The buffered output wrapped in the mode's begin/end tag
        (BufferStream.scala's withTag)."""
        body = "".join(self._parts)
        tag = self._mode.begin_end_tag
        return f"{tag.open}{body}{tag.close}"

    def __str__(self) -> str:
        return "".join(self._parts)
