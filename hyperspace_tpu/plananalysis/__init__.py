from hyperspace_tpu.plananalysis.display import (
    BufferStream,
    ConsoleMode,
    DisplayMode,
    HTMLMode,
    PlainTextMode,
    Tag,
    get_display_mode,
)
from hyperspace_tpu.plananalysis.explain import explain_string

__all__ = ["BufferStream", "ConsoleMode", "DisplayMode", "HTMLMode",
           "PlainTextMode", "Tag", "get_display_mode", "explain_string"]
