"""Explain: side-by-side plans with and without indexes, diff-highlighted.

Reference contract: index/plananalysis/PlanAnalyzer.scala:46-130 — compile
the plan twice (hyperspace enabled/disabled around the optimizer, :167-182),
diff the two trees top-down and highlight the differing subtrees (:60-105:
when nodes differ, the whole subtrees from the first differing node are
highlighted), list the indexes used with their locations (:212-223), and in
verbose mode a physical-operator count comparison
(PhysicalOperatorAnalyzer.scala:30-58).  Output rendering goes through the
display modes (plananalysis/display.py).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from hyperspace_tpu.plan.nodes import LogicalPlan
from hyperspace_tpu.plananalysis.display import BufferStream, get_display_mode

# (text, highlighted) per rendered plan line.
_Line = Tuple[str, bool]


def _used_indexes(plan: LogicalPlan) -> List[str]:
    used = {s.relation.index_scan_of for s in plan.leaf_relations()
            if s.relation.index_scan_of}
    used |= {s.relation.data_skipping_of for s in plan.leaf_relations()
             if s.relation.data_skipping_of}
    return sorted(used)


def _subtree_lines(node: LogicalPlan, indent: int,
                   highlighted: bool) -> List[_Line]:
    lines = [("  " * indent + node.simple_string(), highlighted)]
    for c in node.children:
        lines.extend(_subtree_lines(c, indent + 1, highlighted))
    return lines


def _diff_lines(a: Optional[LogicalPlan], b: Optional[LogicalPlan],
                indent: int = 0) -> Tuple[List[_Line], List[_Line]]:
    """Render both trees, highlighting differing subtrees — once two nodes
    differ, their whole subtrees are highlighted (the reference's
    moveNextSubtree behavior, PlanAnalyzer.scala:88-97)."""
    if a is None and b is None:
        return [], []
    if a is None or b is None or a.simple_string() != b.simple_string() \
            or len(a.children) != len(b.children):
        return (_subtree_lines(a, indent, True) if a else [],
                _subtree_lines(b, indent, True) if b else [])
    out_a = [("  " * indent + a.simple_string(), False)]
    out_b = [("  " * indent + b.simple_string(), False)]
    for ca, cb in zip(a.children, b.children):
        la, lb = _diff_lines(ca, cb, indent + 1)
        out_a.extend(la)
        out_b.extend(lb)
    return out_a, out_b


def _write_plan(stream: BufferStream, lines: List[_Line]) -> None:
    for text, highlighted in lines:
        if highlighted:
            stream.highlight(text)
            stream.write_line()
        else:
            stream.write_line(text)


def _build_header(stream: BufferStream, title: str) -> None:
    bar = "=" * 64
    stream.write_line(bar).write_line(title).write_line(bar)


def explain_string(dataset, session, verbose: bool = False) -> str:
    """Hyperspace.explain analog (Hyperspace.scala:152-155)."""
    was_enabled = session.is_hyperspace_enabled()
    # A run report around the with-indexes pass captures which indexes
    # were considered and what each rule decided (applied / no match /
    # skipped + reason) — the verbose section renders it below.
    from hyperspace_tpu.telemetry import report as run_report

    try:
        session.enable_hyperspace()
        token = run_report.start()
        try:
            plan_with = session.optimize(dataset.plan)
        finally:
            optimize_report = run_report.finish(token)
        session.disable_hyperspace()
        # Optimized without the index rules (column pruning still runs), the
        # same both-sides-compiled comparison as PlanAnalyzer.scala:167-182.
        plan_without = session.optimize(dataset.plan)
    finally:
        if was_enabled:
            session.enable_hyperspace()
        else:
            session.disable_hyperspace()

    mode = get_display_mode(session.conf)
    stream = BufferStream(mode)
    lines_with, lines_without = _diff_lines(plan_with, plan_without)

    _build_header(stream, "Plan with indexes:")
    _write_plan(stream, lines_with)
    stream.write_line()

    _build_header(stream, "Plan without indexes:")
    _write_plan(stream, lines_without)
    stream.write_line()

    _build_header(stream, "Indexes used:")
    used = _used_indexes(plan_with)
    if used:
        mgr = session.index_collection_manager  # TTL-cached accessor
        for name in used:
            entry = mgr.get_index(name)
            location = ""
            if entry is not None:
                files = entry.content.file_infos()
                if files:
                    import os

                    location = os.path.dirname(files[0].name)
            stream.write_line(f"{name}:{location}")
    else:
        stream.write_line("(none)")
    stream.write_line()

    if verbose:
        from hyperspace_tpu.plananalysis.physical import physical_operators

        _build_header(stream, "Physical operator stats:")
        with_counts, with_details = physical_operators(session, plan_with)
        without_counts, without_details = physical_operators(
            session, plan_without)
        ops = sorted(set(with_counts) | set(without_counts))
        stream.write_line(
            f"{'Physical Operator':<24}{'Hyperspace Disabled':>22}"
            f"{'Enabled':>10}{'Diff':>8}")
        for op in ops:
            a, b = without_counts.get(op, 0), with_counts.get(op, 0)
            stream.write_line(f"{op:<24}{a:>22}{b:>10}{b - a:>+8}")
        stream.write_line()
        # The numbers a pruning engine's users actually want: what will
        # each scan read (after bucket + sketch pruning)?
        _build_header(stream, "Scan IO (with indexes):")
        for line in with_details:
            stream.write_line(line)
        _build_header(stream, "Scan IO (without indexes):")
        for line in without_details:
            stream.write_line(line)
        stream.write_line()
        # Which indexes the optimizer pass above considered/used/skipped,
        # and each rule's decision — the run-report view of PLANNING.
        _build_header(stream, "Optimizer decisions:")
        stream.write_line(
            "indexes considered: "
            + (", ".join(optimize_report.indexes_considered) or "(none)"))
        stream.write_line(
            "indexes used:       "
            + (", ".join(optimize_report.indexes_used) or "(none)"))
        skipped = optimize_report.skipped_indexes()
        if skipped:
            stream.write_line("indexes skipped:    " + ", ".join(skipped))
        for d in optimize_report.rules():
            state = "applied" if d.get("applied") else (
                f"skipped ({d['skipped_reason']})"
                if d.get("skipped_reason") else "no match")
            stream.write_line(f"rule {d.get('rule')}: {state}")
        stream.write_line()
        # Where time went the last time this SESSION ran a query
        # (ds.last_run_report() — per-span timings need tracing enabled).
        last = session.last_run_report_value
        if last is not None:
            _build_header(stream, "Last run report:")
            for line in last.render().splitlines():
                stream.write_line(line)
            stream.write_line()
    return stream.with_tag()
