"""Explain: side-by-side plans with and without indexes.

Reference contract: index/plananalysis/PlanAnalyzer.scala:46-130 — compile
the plan twice (hyperspace enabled/disabled around the optimizer,
:167-182), render both trees, list the indexes used, and in verbose mode a
physical-operator count comparison (PhysicalOperatorAnalyzer.scala:30-58 —
the operators the rewrite removes, e.g. shuffles, are what users look for).
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List

from hyperspace_tpu.plan.nodes import LogicalPlan, Scan


def _used_indexes(plan: LogicalPlan) -> List[str]:
    return sorted({s.relation.index_scan_of for s in plan.leaf_relations()
                   if s.relation.index_scan_of})


def _operator_counts(plan: LogicalPlan) -> Counter:
    counts: Counter = Counter()

    def walk(node: LogicalPlan) -> None:
        counts[type(node).__name__] += 1
        for c in node.children:
            walk(c)

    walk(plan)
    return counts


def explain_string(dataset, session, verbose: bool = False) -> str:
    """Hyperspace.explain analog (Hyperspace.scala:152-155)."""
    was_enabled = session.is_hyperspace_enabled()
    try:
        session.enable_hyperspace()
        plan_with = session.optimize(dataset.plan)
        session.disable_hyperspace()
        plan_without = dataset.plan
    finally:
        if was_enabled:
            session.enable_hyperspace()
        else:
            session.disable_hyperspace()

    lines: List[str] = []
    bar = "=" * 64
    lines += [bar, "Plan with indexes:", bar, plan_with.tree_string(), ""]
    lines += [bar, "Plan without indexes:", bar, plan_without.tree_string(), ""]
    lines += [bar, "Indexes used:", bar]
    used = _used_indexes(plan_with)
    if used:
        from hyperspace_tpu.index.manager import IndexCollectionManager

        mgr = IndexCollectionManager(session)
        for name in used:
            entry = mgr.get_index(name)
            location = ""
            if entry is not None:
                files = entry.content.file_infos()
                if files:
                    import os

                    location = os.path.dirname(files[0].name)
            lines.append(f"{name}:{location}")
    else:
        lines.append("(none)")
    lines.append("")
    if verbose:
        lines += [bar, "Physical operator stats:", bar]
        with_counts = _operator_counts(plan_with)
        without_counts = _operator_counts(plan_without)
        ops = sorted(set(with_counts) | set(without_counts))
        header = f"{'Physical Operator':<24}{'Hyperspace Disabled':>22}{'Enabled':>10}{'Diff':>8}"
        lines.append(header)
        for op in ops:
            a, b = without_counts.get(op, 0), with_counts.get(op, 0)
            lines.append(f"{op:<24}{a:>22}{b:>10}{b - a:>+8}")
        lines.append("")
    return "\n".join(lines)
