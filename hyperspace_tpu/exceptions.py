"""Framework error types.

Reference contract: ``HyperspaceException`` (HyperspaceException.scala:19) and
the ``NoChangesException`` no-op control-flow signal used by the action state
machine (actions/RefreshActionBase.scala, Action.scala:84-105).
"""

from __future__ import annotations


class HyperspaceError(Exception):
    """Base error for all hyperspace_tpu failures."""


class NoChangesError(HyperspaceError):
    """Raised by an action's validate() when the operation would be a no-op.

    The action runner treats this as success-without-commit, mirroring the
    reference's NoChangesException handling (Action.scala:92-99).
    """


class ConcurrentWriteError(HyperspaceError):
    """Optimistic-concurrency conflict: a log id was committed by another
    writer between ``base_id`` capture and ``write_log`` (IndexLogManager.scala:149-165)."""


class CorruptMetadataError(HyperspaceError):
    """A source table's metadata file (Delta ``_delta_log`` commit,
    Iceberg metadata JSON or Avro manifest) is truncated or corrupt.
    Always names the bad file so the operator can repair or vacuum it —
    a raw JSONDecodeError with no path is not a diagnosis."""


class DegradedIndexError(HyperspaceError):
    """An index's operation log is unreadable and degraded-mode fallback
    (``hyperspace.system.degraded.fallbackToSource``) is disabled."""


class DeviceSyncError(HyperspaceError):
    """Strict-mode device guard (execution/sync_guard.py,
    ``hyperspace.system.deviceGuard.enabled``): a device→host sync ran
    outside the attributed seams (``sync_guard.pull``/``scalar``, the
    timeline kernel seams).  Like a deadline expiry, this must propagate
    — re-planning would just repeat the unattributed sync."""


class DeadlineExceededError(HyperspaceError):
    """The per-request deadline (utils/deadline.py) expired: the query was
    aborted at a phase boundary.  Deliberately NOT a degraded-mode
    trigger — an expired deadline must propagate to the caller as a
    retryable condition, never silently re-plan (which would spend even
    more time past the deadline)."""
