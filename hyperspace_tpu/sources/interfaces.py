"""Source provider abstraction: how the engine talks to concrete data
formats/lakes.

Reference contract: sources/interfaces.scala —
  - ``FileBasedRelation`` (:43-146): wraps one plan leaf; exposes file
    listing, signature, partition info, relation-metadata creation for the
    log, lineage pairs, and the ``closest_index`` hook (Delta time travel).
  - ``FileBasedSourceProvider`` (:184-234): decides whether it supports a
    relation, reconstructs relations from logged metadata for refresh, names
    the internal file format, and enriches index properties.

Each provider answers each API with Some/None; the manager dispatches to
exactly one (FileBasedSourceProviderManager.scala:117-155).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from hyperspace_tpu.index.log_entry import FileIdTracker, FileInfo, IndexLogEntry, Relation
from hyperspace_tpu.plan.nodes import Scan


# Lake formats whose data files are a different physical format than the
# table format name.  Single source of truth for the engine's read paths
# (executor scans, hybrid-scan file subsets, schema resolution) — mirrors
# internalFileFormatName (interfaces.scala:210).
LAKE_DATA_FORMATS = {"delta": "parquet", "iceberg": "parquet"}


def physical_read_format(file_format: str) -> str:
    """Format to read a relation's data files with."""
    return LAKE_DATA_FORMATS.get(file_format.lower(), file_format)


class FileBasedRelation:
    """One supported leaf relation of a plan (interfaces.scala:43-146)."""

    def __init__(self, scan: Scan) -> None:
        self.scan = scan

    @property
    def root_paths(self) -> List[str]:
        return list(self.scan.relation.root_paths)

    @property
    def file_format(self) -> str:
        return self.scan.relation.file_format

    @property
    def options(self) -> Dict[str, str]:
        return self.scan.relation.options_dict

    @property
    def read_format(self) -> str:
        """Format to READ data files with (Delta/Iceberg data files are
        Parquet — internalFileFormatName, interfaces.scala:210)."""
        return physical_read_format(self.file_format)

    def all_files(self, tracker: Optional[FileIdTracker] = None) -> List[FileInfo]:
        """Every data file of this relation (interfaces.scala:60-66)."""
        raise NotImplementedError

    def schema(self) -> Dict[str, str]:
        raise NotImplementedError

    def signature(self) -> str:
        """Relation-level validity signature (interfaces.scala:52-58)."""
        raise NotImplementedError

    def create_relation_metadata(self, tracker: FileIdTracker) -> Relation:
        """Snapshot for the log entry (interfaces.scala:101-110)."""
        raise NotImplementedError

    def lineage_pairs(self, tracker: FileIdTracker) -> List[Tuple[str, int]]:
        """(file path, file id) pairs for the lineage column
        (interfaces.scala:120-126)."""
        return [(f.name, f.id) for f in self.all_files(tracker)]

    def closest_index(self, entry: IndexLogEntry) -> IndexLogEntry:
        """Hook for multi-version index selection (Delta time travel,
        interfaces.scala:138-146); default: the entry itself."""
        return entry

    def _select_closest_version(self, entry: IndexLogEntry, session,
                                versions, current_pos) -> IndexLogEntry:
        """Shared floor/exact/diff-bytes selection over a recorded version
        history (DeltaLakeRelation.scala:186-243's algorithm, reused by
        every versioned source).  ``versions`` is [(index log version,
        position)] ascending by position; ``current_pos`` is the read
        snapshot's position in the same ordering."""
        if not versions or session is None or current_pos is None:
            return entry

        def load(log_version: int) -> Optional[IndexLogEntry]:
            return session.index_collection_manager.get_index(
                entry.name, log_version)

        floor_i = -1
        for i, (_, pos) in enumerate(versions):
            if pos <= current_pos:
                floor_i = i
        if floor_i == len(versions) - 1:
            return entry  # at or past the latest indexed version
        if floor_i == -1:
            return load(versions[0][0]) or entry  # before the first
        if versions[floor_i][1] == current_pos:
            return load(versions[floor_i][0]) or entry  # exact
        # Between two indexed versions: fewer diff bytes wins so Hybrid
        # Scan has less to patch.
        current = {(f.name, f.size, f.mtime): f.size
                   for f in self.all_files()}
        total = sum(current.values())

        def diff_bytes(candidate: IndexLogEntry) -> int:
            keys = {(f.name, f.size, f.mtime)
                    for f in candidate.source_file_infos()}
            common = sum(size for key, size in current.items() if key in keys)
            return (total - common) + (candidate.source_files_size() - common)

        prev_log = load(versions[floor_i][0])
        next_log = load(versions[floor_i + 1][0])
        if prev_log is None or next_log is None:
            return next_log or prev_log or entry
        return prev_log if diff_bytes(prev_log) < diff_bytes(next_log) \
            else next_log


class FileBasedSourceProvider:
    """Format plug-in (interfaces.scala:184-234)."""

    name: str = ""

    def is_supported_relation(self, scan: Scan) -> Optional[bool]:
        raise NotImplementedError

    def get_relation(self, scan: Scan) -> Optional[FileBasedRelation]:
        raise NotImplementedError

    def internal_file_format_name(self, relation: Relation) -> Optional[str]:
        raise NotImplementedError

    def refresh_relation_metadata(self, relation: Relation) -> Optional[Relation]:
        """Drop snapshot-pinning options so refresh sees latest data
        (interfaces.scala:193-201)."""
        raise NotImplementedError

    def enrich_index_properties(self, relation: Relation,
                                properties: Dict[str, str]) -> Optional[Dict[str, str]]:
        raise NotImplementedError
