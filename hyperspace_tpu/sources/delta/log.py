"""Delta transaction log: read and write the `_delta_log` protocol.

A Delta table is a directory of Parquet data files plus an ordered log of
JSON commits under ``_delta_log/``; the active file set at version N is the
replay of add/remove actions through commit N.  This reader speaks the open
Delta protocol (20-digit zero-padded ``N.json`` commits, newline-delimited
action objects, optional ``N.checkpoint.parquet`` + ``_last_checkpoint``)
so it can read tables written by Spark/delta-rs as well as by our writer.

Reference parity: this module replaces what the reference gets from the
``delta-core`` dependency (``TahoeLogFileIndex`` snapshots,
sources/delta/DeltaLakeRelation.scala:47-56's ``getSnapshot`` +
``filesForScan``) — re-implemented host-side because the TPU engine owns its
own reader instead of riding Spark's.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import urllib.parse
from typing import Any, Dict, List, Optional

from hyperspace_tpu.exceptions import CorruptMetadataError

DELTA_LOG_DIR = "_delta_log"
_COMMIT_RE = re.compile(r"^(\d{20})\.json$")
_CHECKPOINT_RE = re.compile(r"^(\d{20})\.checkpoint\.parquet$")


@dataclasses.dataclass(frozen=True)
class AddFile:
    """One active data file of a snapshot (absolute path)."""

    path: str
    size: int
    modification_time: int  # milliseconds, from the log — not the filesystem


@dataclasses.dataclass(frozen=True)
class RemoveFile:
    """Tombstone for a removed data file (absolute path).  Carried in
    snapshots and checkpoints until the retention window expires so
    concurrent readers of an older version can still resolve the file —
    the protocol's VACUUM-safety mechanism."""

    path: str
    deletion_timestamp: int  # milliseconds


@dataclasses.dataclass
class DeltaMetadata:
    schema_string: str = ""
    partition_columns: List[str] = dataclasses.field(default_factory=list)
    configuration: Dict[str, str] = dataclasses.field(default_factory=dict)
    id: str = ""  # stable table id; a schema-changing commit must keep it


@dataclasses.dataclass
class Snapshot:
    version: int
    files: List[AddFile]
    metadata: DeltaMetadata
    tombstones: List[RemoveFile] = dataclasses.field(default_factory=list)


class DeltaLog:
    """Reader for one table's ``_delta_log``."""

    def __init__(self, table_path: str) -> None:
        self.table_path = os.path.abspath(table_path)
        self.log_path = os.path.join(self.table_path, DELTA_LOG_DIR)

    # -- discovery ----------------------------------------------------------
    def exists(self) -> bool:
        return os.path.isdir(self.log_path) and bool(
            self.commit_versions() or self.checkpoint_versions())

    def commit_versions(self) -> List[int]:
        if not os.path.isdir(self.log_path):
            return []
        out = []
        for name in os.listdir(self.log_path):
            m = _COMMIT_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def checkpoint_versions(self) -> List[int]:
        if not os.path.isdir(self.log_path):
            return []
        out = []
        for name in os.listdir(self.log_path):
            m = _CHECKPOINT_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_version(self) -> int:
        versions = self.commit_versions()
        checkpoints = self.checkpoint_versions()
        if not versions and not checkpoints:
            raise FileNotFoundError(f"Not a Delta table: {self.table_path}")
        return max(versions + checkpoints)

    def version_for_timestamp(self, timestamp_ms: int) -> int:
        """Latest version committed at or before ``timestamp_ms`` (the
        ``timestampAsOf`` resolution rule)."""
        best: Optional[int] = None
        for v in self.commit_versions():
            ts = self._commit_timestamp(v)
            if ts is not None and ts > timestamp_ms:
                break  # commit timestamps are monotonic — nothing later matches
            if ts is not None:
                best = v
        if best is None:
            raise ValueError(
                f"No commit at or before timestamp {timestamp_ms} in "
                f"{self.table_path}")
        return best

    def _commit_timestamp(self, version: int) -> Optional[int]:
        if not os.path.isfile(self._commit_path(version)):
            return None  # superseded by a checkpoint
        for action in self._commit_actions(version):
            info = action.get("commitInfo")
            if info and "timestamp" in info:
                return int(info["timestamp"])
        # Fall back to the commit file's mtime (protocol-compliant readers do
        # the same when commitInfo is absent).
        path = self._commit_path(version)
        if os.path.isfile(path):
            return int(os.stat(path).st_mtime * 1000)
        return None

    # -- replay -------------------------------------------------------------
    def snapshot(self, version: Optional[int] = None) -> Snapshot:
        latest = self.latest_version()
        if version is None:
            version = latest
        if version > latest or version < 0:
            raise ValueError(
                f"Version {version} does not exist in {self.table_path} "
                f"(latest is {latest})")
        active: Dict[str, AddFile] = {}
        tombstones: Dict[str, RemoveFile] = {}
        metadata = DeltaMetadata()

        # Start from the newest checkpoint at or below the target version.
        start = 0
        usable = [c for c in self.checkpoint_versions() if c <= version]
        if usable:
            cp = usable[-1]
            metadata, active, tombstones = self._read_checkpoint(cp)
            start = cp + 1

        commits = [v for v in self.commit_versions() if start <= v <= version]
        expect = list(range(start, version + 1))
        if commits != expect:
            missing = sorted(set(expect) - set(commits))
            raise ValueError(
                f"Delta log is missing commits {missing} for version "
                f"{version} of {self.table_path}")
        for v in commits:
            for action in self._commit_actions(v):
                self._apply(action, active, metadata, tombstones)
        return Snapshot(version, sorted(active.values(), key=lambda f: f.path),
                        metadata,
                        sorted(tombstones.values(), key=lambda f: f.path))

    def _apply(self, action: Dict[str, Any], active: Dict[str, AddFile],
               metadata: DeltaMetadata,
               tombstones: Optional[Dict[str, RemoveFile]] = None) -> None:
        if "add" in action and action["add"]:
            a = action["add"]
            path = self._absolute(a["path"])
            active[path] = AddFile(path, int(a["size"]),
                                   int(a.get("modificationTime", 0)))
            if tombstones is not None:
                tombstones.pop(path, None)
        elif "remove" in action and action["remove"]:
            r = action["remove"]
            path = self._absolute(r["path"])
            active.pop(path, None)
            if tombstones is not None:
                tombstones[path] = RemoveFile(
                    path, int(r.get("deletionTimestamp") or 0))
        elif "metaData" in action and action["metaData"]:
            m = action["metaData"]
            metadata.schema_string = m.get("schemaString", "")
            metadata.partition_columns = list(m.get("partitionColumns", []))
            metadata.configuration = dict(m.get("configuration", {}))
            metadata.id = m.get("id", "")

    def _absolute(self, path: str) -> str:
        path = urllib.parse.unquote(path)
        if os.path.isabs(path):
            return path
        return os.path.join(self.table_path, path)

    def _commit_path(self, version: int) -> str:
        return os.path.join(self.log_path, f"{version:020d}.json")

    def _commit_actions(self, version: int) -> List[Dict[str, Any]]:
        path = self._commit_path(version)
        out: List[Dict[str, Any]] = []
        with open(path, "r", encoding="utf-8") as f:
            for lineno, line in enumerate(f, start=1):
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError as e:
                    # A truncated/torn commit (writer died mid-append, or a
                    # partial object-store upload) must name the bad file —
                    # a bare JSONDecodeError is undebuggable at lake scale.
                    raise CorruptMetadataError(
                        f"Truncated or corrupt Delta log entry {path!r} "
                        f"(action line {lineno}): {e}") from e
        return out

    def _read_checkpoint(self, version: int):
        import pyarrow as pa

        path = os.path.join(self.log_path, f"{version:020d}.checkpoint.parquet")
        from hyperspace_tpu.io.parquet import read_parquet_file

        try:
            table = read_parquet_file(path)
        except pa.ArrowInvalid as e:
            raise CorruptMetadataError(
                f"Truncated or corrupt Delta checkpoint {path!r}: {e}") from e
        metadata = DeltaMetadata()
        active: Dict[str, AddFile] = {}
        tombstones: Dict[str, RemoveFile] = {}
        for row in table.to_pylist():
            self._apply({k: v for k, v in row.items() if v is not None},
                        active, metadata, tombstones)
        return metadata, active, tombstones

    # -- writing ------------------------------------------------------------
    def write_commit(self, version: int, actions: List[Dict[str, Any]]) -> str:
        """Create commit ``version`` atomically; raises if it already exists
        (the same create-if-absent optimistic concurrency as the index
        operation log, IndexLogManager.scala:149-165)."""
        os.makedirs(self.log_path, exist_ok=True)
        path = self._commit_path(version)
        body = "\n".join(json.dumps(a, separators=(",", ":")) for a in actions)
        # 'x' = exclusive create: two writers racing on the same version —
        # exactly one wins, matching the Delta protocol's commit rule.
        with open(path, "x", encoding="utf-8") as f:
            f.write(body + "\n")
        return path
