"""Minimal Delta table writer: append / overwrite commits.

Produces protocol-compliant tables (Parquet part files + JSON commits) that
both this engine and standard Delta readers understand.  Exists because the
TPU engine owns its IO path end to end — the reference leans on delta-core's
writer; our tests and users need a native way to fabricate and mutate Delta
tables (the role ``spark.write.format("delta")`` plays in
HybridScanForDeltaLakeTest / DeltaLakeIntegrationTest).
"""

from __future__ import annotations

import json
import time
import uuid
from typing import List

import pyarrow as pa
import pyarrow.parquet as pq

from hyperspace_tpu.io.schemas import arrow_schema_from_spark, spark_schema_string
from hyperspace_tpu.sources.delta.log import DeltaLog

__all__ = ["write_delta", "delete_where_file", "upsert_delta",
           "delete_rows_delta", "spark_schema_string",
           "arrow_schema_from_spark"]


def write_delta(table: pa.Table, path: str, mode: str = "append") -> int:
    """Write ``table`` to the Delta table at ``path``; returns the committed
    version.  ``mode``: "append" adds files; "overwrite" removes every active
    file and adds the new ones.  Tables are unpartitioned (hive-partitioned
    Delta writes are not supported yet)."""
    if mode not in ("append", "overwrite"):
        raise ValueError(f"Unknown write mode {mode!r}")
    log = DeltaLog(path)
    now_ms = int(time.time() * 1000)
    exists = log.exists()
    version = log.latest_version() + 1 if exists else 0
    if exists:
        # Commit timestamps must be strictly monotonic for timestampAsOf to
        # resolve unambiguously (Spark's writer adjusts the same way).
        prev_ts = log._commit_timestamp(version - 1)
        if prev_ts is not None and now_ms <= prev_ts:
            now_ms = prev_ts + 1

    actions: List[dict] = []
    if version == 0:
        actions.append({"protocol": {"minReaderVersion": 1,
                                     "minWriterVersion": 2}})
        actions.append({"metaData": {
            "id": uuid.uuid4().hex,
            "format": {"provider": "parquet", "options": {}},
            "schemaString": spark_schema_string(table.schema),
            "partitionColumns": [],
            "configuration": {},
            "createdTime": now_ms,
        }})
    elif mode == "overwrite":
        snapshot = log.snapshot()
        for f in snapshot.files:
            rel = _relativize(f.path, log.table_path)
            actions.append({"remove": {"path": rel,
                                       "deletionTimestamp": now_ms,
                                       "dataChange": True}})
        # Overwrite may change the schema: commit a fresh metaData action
        # (keeping the stable table id) so readers don't resolve against the
        # replaced schema.
        new_schema = spark_schema_string(table.schema)
        if new_schema != snapshot.metadata.schema_string:
            actions.append({"metaData": {
                "id": snapshot.metadata.id or uuid.uuid4().hex,
                "format": {"provider": "parquet", "options": {}},
                "schemaString": new_schema,
                "partitionColumns": [],
                "configuration": dict(snapshot.metadata.configuration),
            }})

    name = f"part-00000-{uuid.uuid4().hex}-c000.snappy.parquet"
    data_path = f"{log.table_path}/{name}"
    import os

    os.makedirs(log.table_path, exist_ok=True)
    pq.write_table(table, data_path)

    actions.append({"add": {
        "path": name,
        "partitionValues": {},
        "size": os.stat(data_path).st_size,
        "modificationTime": now_ms,
        "dataChange": True,
    }})
    actions.append({"commitInfo": {"timestamp": now_ms,
                                   "operation": "WRITE",
                                   "operationParameters": {"mode": mode}}})
    log.write_commit(version, actions)
    _maybe_checkpoint(log, version)
    return version


CHECKPOINT_INTERVAL = 10  # delta-core's default checkpoint cadence


_CHECKPOINT_SCHEMA = pa.schema([
    ("protocol", pa.struct([("minReaderVersion", pa.int32()),
                            ("minWriterVersion", pa.int32())])),
    ("metaData", pa.struct([
        ("id", pa.string()),
        ("format", pa.struct([("provider", pa.string())])),
        ("schemaString", pa.string()),
        ("partitionColumns", pa.list_(pa.string())),
        ("configuration", pa.map_(pa.string(), pa.string())),
        ("createdTime", pa.int64()),
    ])),
    ("add", pa.struct([
        ("path", pa.string()),
        ("partitionValues", pa.map_(pa.string(), pa.string())),
        ("size", pa.int64()),
        ("modificationTime", pa.int64()),
        ("dataChange", pa.bool_()),
    ])),
    ("remove", pa.struct([
        ("path", pa.string()),
        ("deletionTimestamp", pa.int64()),
        ("dataChange", pa.bool_()),
    ])),
])

# delta-core's delta.deletedFileRetentionDuration default ("interval 1 week"):
# remove tombstones younger than this must survive checkpointing so readers
# of older versions can still resolve the files (VACUUM safety).
TOMBSTONE_RETENTION_MS = 7 * 24 * 3600 * 1000


def _maybe_checkpoint(log: DeltaLog, version: int) -> None:
    """Write ``version.checkpoint.parquet`` + ``_last_checkpoint`` every
    CHECKPOINT_INTERVAL commits (the delta protocol's log-compaction
    mechanism; our reader already replays from checkpoints, and writing
    them keeps snapshot() O(interval) instead of O(commits)).

    The table uses the protocol's EXPLICIT action schema (protocol row,
    metaData with format + map-typed configuration, add rows with
    partitionValues/dataChange) so standard Delta readers can consume it;
    both files land via temp + atomic rename, and any failure is swallowed
    — the commit already succeeded and a checkpoint is only an
    optimization."""
    if version == 0 or version % CHECKPOINT_INTERVAL != 0:
        return
    import os

    try:
        snap = log.snapshot(version)
        rows = [
            {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2},
             "metaData": None, "add": None, "remove": None},
            {"protocol": None, "add": None, "remove": None, "metaData": {
                "id": snap.metadata.id,
                "format": {"provider": "parquet"},
                "schemaString": snap.metadata.schema_string,
                "partitionColumns": snap.metadata.partition_columns,
                "configuration": list(snap.metadata.configuration.items()),
                "createdTime": None,
            }},
        ]
        # Checkpoint actions carry dataChange=false: they restate existing
        # state, and a streaming reader bootstrapping from the checkpoint
        # must not re-process them as new changes.
        for f in snap.files:
            rows.append({"protocol": None, "metaData": None, "remove": None,
                         "add": {
                             "path": _relativize(f.path, log.table_path),
                             "partitionValues": [],
                             "size": f.size,
                             "modificationTime": f.modification_time,
                             "dataChange": False,
                         }})
        # Unexpired remove tombstones ride along (delta-core checkpoint
        # schema): external readers pinned to an older version rely on them
        # within the retention window.
        # deletionTimestamp is optional in the protocol: an unknown age
        # (0) must be kept — dropping a possibly-fresh tombstone is the
        # unsafe direction.
        horizon = int(time.time() * 1000) - TOMBSTONE_RETENTION_MS
        for t in snap.tombstones:
            if t.deletion_timestamp >= horizon or t.deletion_timestamp == 0:
                rows.append({"protocol": None, "metaData": None, "add": None,
                             "remove": {
                                 "path": _relativize(t.path, log.table_path),
                                 "deletionTimestamp": t.deletion_timestamp,
                                 "dataChange": False,
                             }})
        cp_path = os.path.join(log.log_path,
                               f"{version:020d}.checkpoint.parquet")
        tmp = cp_path + f".tmp{os.getpid()}"
        pq.write_table(pa.Table.from_pylist(rows, schema=_CHECKPOINT_SCHEMA),
                       tmp)
        os.replace(tmp, cp_path)
        last = os.path.join(log.log_path, "_last_checkpoint")
        tmp2 = last + f".tmp{os.getpid()}"
        with open(tmp2, "w", encoding="utf-8") as f:
            json.dump({"version": version, "size": len(rows)}, f)
        os.replace(tmp2, last)
    except Exception:
        # Best-effort: a failed checkpoint must not fail the (already
        # durable) commit; the JSON log remains fully replayable.
        pass


def delete_where_file(path: str, file_path: str) -> int:
    """Commit a remove of one data file (simulates row deletion at file
    granularity — the unit HybridScan's deleted-files handling works at)."""
    log = DeltaLog(path)
    now_ms = int(time.time() * 1000)
    version = log.latest_version() + 1
    rel = _relativize(file_path, log.table_path)
    log.write_commit(version, [
        {"remove": {"path": rel, "deletionTimestamp": now_ms,
                    "dataChange": True}},
        {"commitInfo": {"timestamp": now_ms, "operation": "DELETE"}},
    ])
    _maybe_checkpoint(log, version)
    return version


def _relativize(path: str, root: str) -> str:
    import os

    if path.startswith(root.rstrip("/") + "/"):
        return path[len(root.rstrip("/")) + 1:]
    return path


# ---------------------------------------------------------------------------
# Row-level CDC commits (the shape MERGE INTO / DELETE WHERE leave behind)
# ---------------------------------------------------------------------------
def _rewrite_actions(log: DeltaLog, key: str, key_set: pa.Array,
                     now_ms: int) -> List[dict]:
    """Copy-on-write row rewrite: every active data file holding a row
    whose ``key`` is in ``key_set`` is tombstoned and its SURVIVING rows
    land in a fresh part file — remove(old)+add(rewritten) pairs, the
    file-level signature a real MERGE/DELETE commit leaves (and exactly
    what hybrid scan's deleted/appended overlay merges at read time)."""
    import os

    import pyarrow.compute as pc

    actions: List[dict] = []
    for f in log.snapshot().files:
        data = pq.read_table(f.path)
        if key not in data.column_names:
            raise ValueError(f"Key column {key!r} not in {f.path}")
        mask = pc.is_in(data.column(key),
                        value_set=key_set.cast(
                            data.schema.field(key).type))
        if not pc.any(mask).as_py():
            continue  # untouched files stay live
        actions.append({"remove": {
            "path": _relativize(f.path, log.table_path),
            "deletionTimestamp": now_ms, "dataChange": True}})
        survivors = data.filter(pc.invert(mask))
        if survivors.num_rows == 0:
            continue  # whole file matched: pure delete
        name = f"part-00000-{uuid.uuid4().hex}-c000.snappy.parquet"
        out = f"{log.table_path}/{name}"
        pq.write_table(survivors, out)
        actions.append({"add": {
            "path": name, "partitionValues": {},
            "size": os.stat(out).st_size,
            "modificationTime": now_ms, "dataChange": True}})
    return actions


def _next_commit_ts(log: DeltaLog, version: int) -> int:
    now_ms = int(time.time() * 1000)
    prev_ts = log._commit_timestamp(version - 1)
    if prev_ts is not None and now_ms <= prev_ts:
        now_ms = prev_ts + 1
    return now_ms


def upsert_delta(table: pa.Table, path: str, key: str) -> int:
    """MERGE ``table`` into the Delta table at ``path`` keyed on column
    ``key``: existing rows with a matching key are replaced, the rest
    are inserted — ONE commit carrying the remove/add pairs for every
    rewritten file plus one part file with the upserted rows (the
    copy-on-write merge-on-write shape; hyperspace absorbs it as
    merge-on-read debt via the quick refresh).  Returns the committed
    version; creates the table when it does not exist."""
    import os

    log = DeltaLog(path)
    if not log.exists():
        return write_delta(table, path, mode="append")
    version = log.latest_version() + 1
    now_ms = _next_commit_ts(log, version)
    actions = _rewrite_actions(log, key,
                               table.column(key).combine_chunks(), now_ms)
    name = f"part-00000-{uuid.uuid4().hex}-c000.snappy.parquet"
    out = f"{log.table_path}/{name}"
    pq.write_table(table, out)
    actions.append({"add": {
        "path": name, "partitionValues": {},
        "size": os.stat(out).st_size,
        "modificationTime": now_ms, "dataChange": True}})
    actions.append({"commitInfo": {
        "timestamp": now_ms, "operation": "MERGE",
        "operationParameters": {"matchedPredicates": key}}})
    log.write_commit(version, actions)
    _maybe_checkpoint(log, version)
    return version


def delete_rows_delta(path: str, key: str, values) -> int:
    """DELETE the rows of the Delta table at ``path`` whose ``key``
    column matches ``values`` — ONE commit tombstoning each touched
    file and re-adding its surviving rows.  Returns the committed
    version, or the current version unchanged when no row matched
    (delta-core's DELETE also skips the commit then)."""
    log = DeltaLog(path)
    version = log.latest_version() + 1
    now_ms = _next_commit_ts(log, version)
    actions = _rewrite_actions(log, key, pa.array(list(values)), now_ms)
    if not actions:
        return version - 1
    actions.append({"commitInfo": {
        "timestamp": now_ms, "operation": "DELETE",
        "operationParameters": {"predicate": key}}})
    log.write_commit(version, actions)
    _maybe_checkpoint(log, version)
    return version
