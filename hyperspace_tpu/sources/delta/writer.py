"""Minimal Delta table writer: append / overwrite commits.

Produces protocol-compliant tables (Parquet part files + JSON commits) that
both this engine and standard Delta readers understand.  Exists because the
TPU engine owns its IO path end to end — the reference leans on delta-core's
writer; our tests and users need a native way to fabricate and mutate Delta
tables (the role ``spark.write.format("delta")`` plays in
HybridScanForDeltaLakeTest / DeltaLakeIntegrationTest).
"""

from __future__ import annotations

import json
import time
import uuid
from typing import List

import pyarrow as pa
import pyarrow.parquet as pq

from hyperspace_tpu.io.schemas import arrow_schema_from_spark, spark_schema_string
from hyperspace_tpu.sources.delta.log import DeltaLog

__all__ = ["write_delta", "delete_where_file", "spark_schema_string",
           "arrow_schema_from_spark"]


def write_delta(table: pa.Table, path: str, mode: str = "append") -> int:
    """Write ``table`` to the Delta table at ``path``; returns the committed
    version.  ``mode``: "append" adds files; "overwrite" removes every active
    file and adds the new ones.  Tables are unpartitioned (hive-partitioned
    Delta writes are not supported yet)."""
    if mode not in ("append", "overwrite"):
        raise ValueError(f"Unknown write mode {mode!r}")
    log = DeltaLog(path)
    now_ms = int(time.time() * 1000)
    exists = log.exists()
    version = log.latest_version() + 1 if exists else 0
    if exists:
        # Commit timestamps must be strictly monotonic for timestampAsOf to
        # resolve unambiguously (Spark's writer adjusts the same way).
        prev_ts = log._commit_timestamp(version - 1)
        if prev_ts is not None and now_ms <= prev_ts:
            now_ms = prev_ts + 1

    actions: List[dict] = []
    if version == 0:
        actions.append({"protocol": {"minReaderVersion": 1,
                                     "minWriterVersion": 2}})
        actions.append({"metaData": {
            "id": uuid.uuid4().hex,
            "format": {"provider": "parquet", "options": {}},
            "schemaString": spark_schema_string(table.schema),
            "partitionColumns": [],
            "configuration": {},
            "createdTime": now_ms,
        }})
    elif mode == "overwrite":
        snapshot = log.snapshot()
        for f in snapshot.files:
            rel = _relativize(f.path, log.table_path)
            actions.append({"remove": {"path": rel,
                                       "deletionTimestamp": now_ms,
                                       "dataChange": True}})
        # Overwrite may change the schema: commit a fresh metaData action
        # (keeping the stable table id) so readers don't resolve against the
        # replaced schema.
        new_schema = spark_schema_string(table.schema)
        if new_schema != snapshot.metadata.schema_string:
            actions.append({"metaData": {
                "id": snapshot.metadata.id or uuid.uuid4().hex,
                "format": {"provider": "parquet", "options": {}},
                "schemaString": new_schema,
                "partitionColumns": [],
                "configuration": dict(snapshot.metadata.configuration),
            }})

    name = f"part-00000-{uuid.uuid4().hex}-c000.snappy.parquet"
    data_path = f"{log.table_path}/{name}"
    import os

    os.makedirs(log.table_path, exist_ok=True)
    pq.write_table(table, data_path)

    actions.append({"add": {
        "path": name,
        "partitionValues": {},
        "size": os.stat(data_path).st_size,
        "modificationTime": now_ms,
        "dataChange": True,
    }})
    actions.append({"commitInfo": {"timestamp": now_ms,
                                   "operation": "WRITE",
                                   "operationParameters": {"mode": mode}}})
    log.write_commit(version, actions)
    _maybe_checkpoint(log, version)
    return version


CHECKPOINT_INTERVAL = 10  # delta-core's default checkpoint cadence


_CHECKPOINT_SCHEMA = pa.schema([
    ("protocol", pa.struct([("minReaderVersion", pa.int32()),
                            ("minWriterVersion", pa.int32())])),
    ("metaData", pa.struct([
        ("id", pa.string()),
        ("format", pa.struct([("provider", pa.string())])),
        ("schemaString", pa.string()),
        ("partitionColumns", pa.list_(pa.string())),
        ("configuration", pa.map_(pa.string(), pa.string())),
        ("createdTime", pa.int64()),
    ])),
    ("add", pa.struct([
        ("path", pa.string()),
        ("partitionValues", pa.map_(pa.string(), pa.string())),
        ("size", pa.int64()),
        ("modificationTime", pa.int64()),
        ("dataChange", pa.bool_()),
    ])),
    ("remove", pa.struct([
        ("path", pa.string()),
        ("deletionTimestamp", pa.int64()),
        ("dataChange", pa.bool_()),
    ])),
])

# delta-core's delta.deletedFileRetentionDuration default ("interval 1 week"):
# remove tombstones younger than this must survive checkpointing so readers
# of older versions can still resolve the files (VACUUM safety).
TOMBSTONE_RETENTION_MS = 7 * 24 * 3600 * 1000


def _maybe_checkpoint(log: DeltaLog, version: int) -> None:
    """Write ``version.checkpoint.parquet`` + ``_last_checkpoint`` every
    CHECKPOINT_INTERVAL commits (the delta protocol's log-compaction
    mechanism; our reader already replays from checkpoints, and writing
    them keeps snapshot() O(interval) instead of O(commits)).

    The table uses the protocol's EXPLICIT action schema (protocol row,
    metaData with format + map-typed configuration, add rows with
    partitionValues/dataChange) so standard Delta readers can consume it;
    both files land via temp + atomic rename, and any failure is swallowed
    — the commit already succeeded and a checkpoint is only an
    optimization."""
    if version == 0 or version % CHECKPOINT_INTERVAL != 0:
        return
    import os

    try:
        snap = log.snapshot(version)
        rows = [
            {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2},
             "metaData": None, "add": None, "remove": None},
            {"protocol": None, "add": None, "remove": None, "metaData": {
                "id": snap.metadata.id,
                "format": {"provider": "parquet"},
                "schemaString": snap.metadata.schema_string,
                "partitionColumns": snap.metadata.partition_columns,
                "configuration": list(snap.metadata.configuration.items()),
                "createdTime": None,
            }},
        ]
        # Checkpoint actions carry dataChange=false: they restate existing
        # state, and a streaming reader bootstrapping from the checkpoint
        # must not re-process them as new changes.
        for f in snap.files:
            rows.append({"protocol": None, "metaData": None, "remove": None,
                         "add": {
                             "path": _relativize(f.path, log.table_path),
                             "partitionValues": [],
                             "size": f.size,
                             "modificationTime": f.modification_time,
                             "dataChange": False,
                         }})
        # Unexpired remove tombstones ride along (delta-core checkpoint
        # schema): external readers pinned to an older version rely on them
        # within the retention window.
        # deletionTimestamp is optional in the protocol: an unknown age
        # (0) must be kept — dropping a possibly-fresh tombstone is the
        # unsafe direction.
        horizon = int(time.time() * 1000) - TOMBSTONE_RETENTION_MS
        for t in snap.tombstones:
            if t.deletion_timestamp >= horizon or t.deletion_timestamp == 0:
                rows.append({"protocol": None, "metaData": None, "add": None,
                             "remove": {
                                 "path": _relativize(t.path, log.table_path),
                                 "deletionTimestamp": t.deletion_timestamp,
                                 "dataChange": False,
                             }})
        cp_path = os.path.join(log.log_path,
                               f"{version:020d}.checkpoint.parquet")
        tmp = cp_path + f".tmp{os.getpid()}"
        pq.write_table(pa.Table.from_pylist(rows, schema=_CHECKPOINT_SCHEMA),
                       tmp)
        os.replace(tmp, cp_path)
        last = os.path.join(log.log_path, "_last_checkpoint")
        tmp2 = last + f".tmp{os.getpid()}"
        with open(tmp2, "w", encoding="utf-8") as f:
            json.dump({"version": version, "size": len(rows)}, f)
        os.replace(tmp2, last)
    except Exception:
        # Best-effort: a failed checkpoint must not fail the (already
        # durable) commit; the JSON log remains fully replayable.
        pass


def delete_where_file(path: str, file_path: str) -> int:
    """Commit a remove of one data file (simulates row deletion at file
    granularity — the unit HybridScan's deleted-files handling works at)."""
    log = DeltaLog(path)
    now_ms = int(time.time() * 1000)
    version = log.latest_version() + 1
    rel = _relativize(file_path, log.table_path)
    log.write_commit(version, [
        {"remove": {"path": rel, "deletionTimestamp": now_ms,
                    "dataChange": True}},
        {"commitInfo": {"timestamp": now_ms, "operation": "DELETE"}},
    ])
    _maybe_checkpoint(log, version)
    return version


def _relativize(path: str, root: str) -> str:
    import os

    if path.startswith(root.rstrip("/") + "/"):
        return path[len(root.rstrip("/")) + 1:]
    return path
