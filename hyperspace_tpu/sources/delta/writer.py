"""Minimal Delta table writer: append / overwrite commits.

Produces protocol-compliant tables (Parquet part files + JSON commits) that
both this engine and standard Delta readers understand.  Exists because the
TPU engine owns its IO path end to end — the reference leans on delta-core's
writer; our tests and users need a native way to fabricate and mutate Delta
tables (the role ``spark.write.format("delta")`` plays in
HybridScanForDeltaLakeTest / DeltaLakeIntegrationTest).
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Dict, List, Optional

import pyarrow as pa
import pyarrow.parquet as pq

from hyperspace_tpu.sources.delta.log import DeltaLog

_ARROW_TO_SPARK = {
    "int8": "byte",
    "int16": "short",
    "int32": "integer",
    "int64": "long",
    "float": "float",
    "double": "double",
    "bool": "boolean",
    "string": "string",
    "large_string": "string",
    "date32[day]": "date",
    "binary": "binary",
}

_SPARK_TO_ARROW = {v: k for k, v in _ARROW_TO_SPARK.items() if v != "string"}
_SPARK_TO_ARROW["string"] = "string"


def spark_schema_string(schema: pa.Schema) -> str:
    """Arrow schema → Spark StructType JSON (the metaData.schemaString
    format every Delta reader expects)."""
    fields = []
    for f in schema:
        t = _ARROW_TO_SPARK.get(str(f.type))
        if t is None:
            if str(f.type).startswith("timestamp"):
                t = "timestamp"
            elif str(f.type).startswith("decimal128"):
                import re

                m = re.match(r"decimal128\((\d+),\s*(\d+)\)", str(f.type))
                t = f"decimal({m.group(1)},{m.group(2)})" if m else "string"
            else:
                t = "string"
        fields.append({"name": f.name, "type": t, "nullable": True,
                       "metadata": {}})
    return json.dumps({"type": "struct", "fields": fields})


def arrow_schema_from_spark(schema_string: str) -> Dict[str, str]:
    """Spark StructType JSON → our name→arrow-type-string schema dict."""
    parsed = json.loads(schema_string)
    out: Dict[str, str] = {}
    for f in parsed.get("fields", []):
        t = f["type"]
        if isinstance(t, str):
            if t == "timestamp":
                arrow = "timestamp[us]"
            elif t.startswith("decimal"):
                import re

                m = re.match(r"decimal\((\d+),\s*(\d+)\)", t)
                arrow = f"decimal128({m.group(1)}, {m.group(2)})" if m \
                    else "string"
            else:
                arrow = _SPARK_TO_ARROW.get(t, "string")
        else:
            arrow = "string"  # nested types surface as strings for now
        out[f["name"]] = arrow
    return out


def write_delta(table: pa.Table, path: str, mode: str = "append") -> int:
    """Write ``table`` to the Delta table at ``path``; returns the committed
    version.  ``mode``: "append" adds files; "overwrite" removes every active
    file and adds the new ones.  Tables are unpartitioned (hive-partitioned
    Delta writes are not supported yet)."""
    if mode not in ("append", "overwrite"):
        raise ValueError(f"Unknown write mode {mode!r}")
    log = DeltaLog(path)
    now_ms = int(time.time() * 1000)
    exists = log.exists()
    version = log.latest_version() + 1 if exists else 0
    if exists:
        # Commit timestamps must be strictly monotonic for timestampAsOf to
        # resolve unambiguously (Spark's writer adjusts the same way).
        prev_ts = log._commit_timestamp(version - 1)
        if prev_ts is not None and now_ms <= prev_ts:
            now_ms = prev_ts + 1

    actions: List[dict] = []
    if version == 0:
        actions.append({"protocol": {"minReaderVersion": 1,
                                     "minWriterVersion": 2}})
        actions.append({"metaData": {
            "id": uuid.uuid4().hex,
            "format": {"provider": "parquet", "options": {}},
            "schemaString": spark_schema_string(table.schema),
            "partitionColumns": [],
            "configuration": {},
            "createdTime": now_ms,
        }})
    elif mode == "overwrite":
        for f in log.snapshot().files:
            rel = _relativize(f.path, log.table_path)
            actions.append({"remove": {"path": rel,
                                       "deletionTimestamp": now_ms,
                                       "dataChange": True}})

    name = f"part-00000-{uuid.uuid4().hex}-c000.snappy.parquet"
    data_path = f"{log.table_path}/{name}"
    import os

    os.makedirs(log.table_path, exist_ok=True)
    pq.write_table(table, data_path)

    actions.append({"add": {
        "path": name,
        "partitionValues": {},
        "size": os.stat(data_path).st_size,
        "modificationTime": now_ms,
        "dataChange": True,
    }})
    actions.append({"commitInfo": {"timestamp": now_ms,
                                   "operation": "WRITE",
                                   "operationParameters": {"mode": mode}}})
    log.write_commit(version, actions)
    return version


def delete_where_file(path: str, file_path: str) -> int:
    """Commit a remove of one data file (simulates row deletion at file
    granularity — the unit HybridScan's deleted-files handling works at)."""
    log = DeltaLog(path)
    now_ms = int(time.time() * 1000)
    version = log.latest_version() + 1
    rel = _relativize(file_path, log.table_path)
    log.write_commit(version, [
        {"remove": {"path": rel, "deletionTimestamp": now_ms,
                    "dataChange": True}},
        {"commitInfo": {"timestamp": now_ms, "operation": "DELETE"}},
    ])
    return version


def _relativize(path: str, root: str) -> str:
    import os

    if path.startswith(root.rstrip("/") + "/"):
        return path[len(root.rstrip("/")) + 1:]
    return path
