from hyperspace_tpu.sources.delta.log import DeltaLog
from hyperspace_tpu.sources.delta.provider import DeltaLakeRelation, DeltaLakeSource
from hyperspace_tpu.sources.delta.writer import write_delta

__all__ = ["DeltaLog", "DeltaLakeRelation", "DeltaLakeSource", "write_delta"]
