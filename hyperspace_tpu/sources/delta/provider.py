"""Delta Lake source provider: versioned-table indexing with time travel.

Reference contract: sources/delta/DeltaLakeFileBasedSource.scala:40-123 and
sources/delta/DeltaLakeRelation.scala:33-243 —
  - supports relations whose format is "delta"; data files come from the
    transaction-log snapshot, never a directory listing (:47-56);
  - signature = table version + path (:39-42) so index validity is a version
    check, not an O(files) walk;
  - ``create_relation_metadata`` pins ``versionAsOf`` so refresh/rules know
    which version the index covers (:73-112);
  - ``refresh_relation_metadata`` drops time-travel options so refresh sees
    the latest data (DeltaLakeFileBasedSource.scala:49-55);
  - ``enrich_index_properties`` appends "indexVersion:deltaVersion" pairs to
    the ``deltaVersions`` history property (:107-123);
  - ``closest_index`` picks, for a time-traveled read, the index log version
    whose delta version is nearest — exact match, floor, or the diff-bytes
    tie-break between floor and ceiling (DeltaLakeRelation.scala:186-243).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.index.log_entry import (
    Content,
    FileIdTracker,
    FileInfo,
    IndexLogEntry,
    Relation,
)
from hyperspace_tpu.plan.nodes import Scan
from hyperspace_tpu.sources.delta.log import DeltaLog, Snapshot
from hyperspace_tpu.sources.interfaces import FileBasedRelation, FileBasedSourceProvider

DELTA_FORMAT = "delta"
DELTA_VERSION_HISTORY_PROPERTY = "deltaVersions"
INDEX_LOG_VERSION_PROPERTY = "indexLogVersion"


def _timestamp_ms(value: str) -> int:
    """``timestampAsOf`` accepts epoch milliseconds or a timestamp string
    (Spark accepts "yyyy-MM-dd[ HH:mm:ss]" and ISO forms)."""
    try:
        return int(value)
    except ValueError:
        pass
    from datetime import datetime, timezone

    text = value.strip().replace(" ", "T")
    try:
        dt = datetime.fromisoformat(text)
    except ValueError:
        raise ValueError(
            f"Cannot parse timestampAsOf value {value!r}: expected epoch "
            f"milliseconds or an ISO timestamp") from None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return int(dt.timestamp() * 1000)


class DeltaLakeRelation(FileBasedRelation):
    def __init__(self, scan: Scan, conf: HyperspaceConf, session=None) -> None:
        super().__init__(scan)
        self._conf = conf
        self._session = session
        if len(self.root_paths) != 1:
            raise ValueError("A Delta relation has exactly one table path")
        self._log = DeltaLog(self.root_paths[0])
        self._snapshot_cache: Optional[Snapshot] = None

    # -- snapshot resolution ------------------------------------------------
    @property
    def table_version(self) -> int:
        return self._snapshot().version

    def _snapshot(self) -> Snapshot:
        if self._snapshot_cache is None:
            opts = self.options
            version: Optional[int] = None
            if "versionAsOf" in opts:
                version = int(opts["versionAsOf"])
            elif "timestampAsOf" in opts:
                version = self._log.version_for_timestamp(
                    _timestamp_ms(opts["timestampAsOf"]))
            self._snapshot_cache = self._log.snapshot(version)
        return self._snapshot_cache

    # -- FileBasedRelation --------------------------------------------------
    def all_files(self, tracker: Optional[FileIdTracker] = None) -> List[FileInfo]:
        """Files from the snapshot, not a directory walk
        (DeltaLakeRelation.scala:47-56): overwritten/removed files still
        exist on disk but are NOT part of the table."""
        out = []
        for f in self._snapshot().files:
            fid = tracker.add_file(f.path, f.size, f.modification_time) \
                if tracker is not None else -1
            out.append(FileInfo(f.path, f.size, f.modification_time, fid))
        return out

    def schema(self) -> Dict[str, str]:
        meta = self._snapshot().metadata
        if meta.schema_string:
            from hyperspace_tpu.sources.delta.writer import arrow_schema_from_spark

            return arrow_schema_from_spark(meta.schema_string)
        files = self.all_files()
        if not files:
            raise FileNotFoundError(
                f"Delta table {self.root_paths[0]} has no schema and no files")
        from hyperspace_tpu.io.parquet import read_schema

        return read_schema(files[0].name, "parquet")

    def signature(self) -> str:
        """Table version + path — O(1), no file walk
        (DeltaLakeRelation.scala:39-42)."""
        return f"{self.table_version}{self._log.table_path}"

    def create_relation_metadata(self, tracker: FileIdTracker) -> Relation:
        files = self.all_files(tracker)
        # Pin the indexed version; drop any path-ish options
        # (DeltaLakeRelation.scala:93-105).
        opts = {k: v for k, v in self.options.items()
                if k not in ("path", "timestampAsOf")}
        opts["versionAsOf"] = str(self.table_version)
        return Relation(
            root_paths=[self._log.table_path],
            content=Content.from_leaf_files(files)
            or Content.from_directory(self._log.table_path, tracker),
            schema=self.schema(),
            file_format=DELTA_FORMAT,
            options=opts,
        )

    # -- multi-version index selection (DeltaLakeRelation.scala:155-243) ----
    def _version_history(self, entry: IndexLogEntry) -> List[tuple]:
        """[(index log version, delta version)] ascending; when several index
        versions map to one delta version (optimize), keep the highest."""
        raw = entry.properties.get(DELTA_VERSION_HISTORY_PROPERTY, "")
        if not raw:
            return []
        by_delta: Dict[int, int] = {}
        for pair in raw.split(","):
            index_v, delta_v = (int(x) for x in pair.split(":"))
            by_delta[delta_v] = max(index_v, by_delta.get(delta_v, -1))
        return sorted(((iv, dv) for dv, iv in by_delta.items()),
                      key=lambda t: t[1])

    def closest_index(self, entry: IndexLogEntry) -> IndexLogEntry:
        """DeltaLakeRelation.scala:186-243; the algorithm lives in the
        shared FileBasedRelation helper (positions = delta versions)."""
        return self._select_closest_version(
            entry, self._session, self._version_history(entry),
            self.table_version)


class DeltaLakeSource(FileBasedSourceProvider):
    name = "delta"

    def __init__(self, conf: HyperspaceConf) -> None:
        self._conf = conf
        self._session = None

    def bind_session(self, session) -> None:
        """Gives relations access to the index manager for closest_index
        (the Hyperspace.getContext(spark) lookup,
        DeltaLakeRelation.scala:193-199)."""
        self._session = session

    def is_supported_relation(self, scan: Scan) -> Optional[bool]:
        return True if scan.relation.file_format.lower() == DELTA_FORMAT else None

    def get_relation(self, scan: Scan) -> Optional[FileBasedRelation]:
        if not self.is_supported_relation(scan):
            return None
        return DeltaLakeRelation(scan, self._conf, self._session)

    def internal_file_format_name(self, relation: Relation) -> Optional[str]:
        return "parquet" if relation.file_format == DELTA_FORMAT else None

    def refresh_relation_metadata(self, relation: Relation) -> Optional[Relation]:
        if relation.file_format != DELTA_FORMAT:
            return None
        import dataclasses as dc

        opts = {k: v for k, v in relation.options.items()
                if k not in ("versionAsOf", "timestampAsOf")}
        return dc.replace(relation, options=opts)

    def enrich_index_properties(self, relation: Relation,
                                properties: Dict[str, str]) -> Optional[Dict[str, str]]:
        if relation.file_format != DELTA_FORMAT:
            return None
        out = dict(properties)
        index_version = properties.get(INDEX_LOG_VERSION_PROPERTY)
        delta_version = relation.options.get("versionAsOf")
        if index_version is not None and delta_version is not None:
            pair = f"{index_version}:{delta_version}"
            history = properties.get(DELTA_VERSION_HISTORY_PROPERTY)
            out[DELTA_VERSION_HISTORY_PROPERTY] = \
                f"{history},{pair}" if history else pair
        return out
