"""Default file-based source: plain parquet/csv/json directories.

Reference contract: sources/default/DefaultFileBasedSource.scala:37-148 and
DefaultFileBasedRelation — supports any allow-listed format
(HyperspaceConf.scala:93-98), signature = md5 fold over file metadata,
listing via recursive walk.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.index.log_entry import Content, FileIdTracker, FileInfo, Relation
from hyperspace_tpu.io.files import list_data_files
from hyperspace_tpu.io.parquet import read_schema
from hyperspace_tpu.plan.nodes import Scan
from hyperspace_tpu.sources.interfaces import FileBasedRelation, FileBasedSourceProvider
from hyperspace_tpu.utils.hashing import fold_md5


class DefaultFileBasedRelation(FileBasedRelation):
    def __init__(self, scan: Scan, conf: HyperspaceConf) -> None:
        super().__init__(scan)
        self._conf = conf
        self._files_cache: Optional[List[FileInfo]] = None
        self._schema_cache: Optional[Dict[str, str]] = None

    def all_files(self, tracker: Optional[FileIdTracker] = None) -> List[FileInfo]:
        # List once per relation object; registering with a tracker reuses
        # the cached (name, size, mtime) triples instead of re-walking.
        if self._files_cache is None:
            self._files_cache = list_data_files(self.root_paths, None)
        if tracker is None:
            return self._files_cache
        return [FileInfo(f.name, f.size, f.mtime,
                         tracker.add_file(f.name, f.size, f.mtime))
                for f in self._files_cache]

    def schema(self) -> Dict[str, str]:
        if self._schema_cache is None:
            files = self.all_files()
            if not files:
                raise FileNotFoundError(
                    f"No data files under {self.root_paths!r}")
            schema = read_schema(files[0].name, self.file_format, self.options)
            # Hive partition columns live in the paths, not the files
            # (partitionSchema, DefaultFileBasedRelation.scala:73-86).
            from hyperspace_tpu.io.partitions import partition_spec_for_roots

            for k, t in partition_spec_for_roots(self.root_paths).items():
                schema.setdefault(k, t)
            self._schema_cache = schema
        return self._schema_cache

    def signature(self) -> str:
        """md5 fold over (size, mtime, name) of all files
        (DefaultFileBasedRelation.scala:45-52)."""
        return fold_md5(f"{f.size}{f.mtime}{f.name}" for f in self.all_files())

    def create_relation_metadata(self, tracker: FileIdTracker) -> Relation:
        files = self.all_files(tracker)
        return Relation(
            root_paths=self._logged_root_paths(),
            content=Content.from_leaf_files(files) or Content.from_directory(
                self.root_paths[0], tracker),
            schema=self.schema(),
            file_format=self.file_format,
            options=self.options,
        )

    def _logged_root_paths(self) -> List[str]:
        """Root paths to record in the log entry.  When the globbing-pattern
        conf is set, validate the pattern covers every scanned root and
        record the PATTERN instead, so refresh re-expands it and picks up
        directories that appear later
        (DefaultFileBasedSource.scala:118-180's pattern validation)."""
        pattern = (self._conf.globbing_pattern or "").strip()
        if not pattern:
            return list(self.root_paths)
        from hyperspace_tpu.exceptions import HyperspaceError
        from hyperspace_tpu.io.files import expand_globs
        from hyperspace_tpu.utils.paths import normalize_path

        patterns = [p.strip() for p in pattern.split(",") if p.strip()]
        expanded = {normalize_path(p) for p in expand_globs(patterns)}
        # A root that IS one of the patterns (a refresh reconstructing a
        # pattern-rooted relation) trivially matches.
        unmatched = [r for r in self.root_paths
                     if r not in patterns and normalize_path(r) not in expanded]
        if unmatched:
            raise HyperspaceError(
                f"Some root paths of the relation do not match the globbing "
                f"pattern {pattern!r}: {unmatched}")
        return patterns


class DefaultFileBasedSource(FileBasedSourceProvider):
    name = "default"

    def __init__(self, conf: HyperspaceConf) -> None:
        self._conf = conf

    def _supported_formats(self) -> List[str]:
        return [f.strip().lower() for f in self._conf.supported_file_formats.split(",")]

    def is_supported_relation(self, scan: Scan) -> Optional[bool]:
        # Index scans are "supported" too: rules re-derive signatures over
        # rewritten plans (DefaultFileBasedSource.scala:55-68).
        return scan.relation.file_format.lower() in self._supported_formats()

    def get_relation(self, scan: Scan) -> Optional[FileBasedRelation]:
        if not self.is_supported_relation(scan):
            return None
        return DefaultFileBasedRelation(scan, self._conf)

    def internal_file_format_name(self, relation: Relation) -> Optional[str]:
        if relation.file_format.lower() in self._supported_formats():
            return relation.file_format.lower()
        return None

    def refresh_relation_metadata(self, relation: Relation) -> Optional[Relation]:
        if relation.file_format.lower() not in self._supported_formats():
            return None
        return relation  # no snapshot-pinning options for plain files

    def enrich_index_properties(self, relation: Relation,
                                properties: Dict[str, str]) -> Optional[Dict[str, str]]:
        if relation.file_format.lower() not in self._supported_formats():
            return None  # another provider owns this relation
        return properties
