"""Iceberg source provider: snapshot-based table indexing with time travel.

Reference contract: sources/iceberg/IcebergFileBasedSource.scala:35-110 and
sources/iceberg/IcebergRelation.scala:44-243 —
  - supports relations whose format is "iceberg"; data files come from
    manifest scan planning, never a directory listing (:60-63);
  - signature = snapshot id + table location (:50-55) so index validity is an
    O(1) metadata check, not an O(files) walk;
  - ``create_relation_metadata`` pins ``snapshot-id`` + ``as-of-timestamp``
    of the current snapshot (:85-113);
  - ``refresh_relation_metadata`` drops both pins so refresh sees the latest
    snapshot (IcebergFileBasedSource.scala:45-52);
  - ``enrich_index_properties`` appends "indexLogVersion:snapshotId" pairs
    to the ``icebergSnapshots`` history (the reference passes through here,
    :99-107 — the history powers the beyond-parity multi-version selection
    below);
  - data files are always Parquet (:118-121).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.index.log_entry import (
    Content,
    FileIdTracker,
    FileInfo,
    Relation,
)
from hyperspace_tpu.plan.nodes import Scan
from hyperspace_tpu.io.schemas import arrow_schema_from_iceberg
from hyperspace_tpu.sources.iceberg.metadata import (
    IcebergSnapshot,
    IcebergTable,
    TableMetadata,
)
from hyperspace_tpu.sources.interfaces import FileBasedRelation, FileBasedSourceProvider

ICEBERG_FORMAT = "iceberg"
ICEBERG_VERSION_HISTORY_PROPERTY = "icebergSnapshots"
INDEX_LOG_VERSION_PROPERTY = "indexLogVersion"


class IcebergRelation(FileBasedRelation):
    def __init__(self, scan: Scan, conf: HyperspaceConf, session=None) -> None:
        super().__init__(scan)
        self._conf = conf
        self._session = session
        if len(self.root_paths) != 1:
            raise ValueError("An Iceberg relation has exactly one table path")
        self._table = IcebergTable(self.root_paths[0])
        self._metadata_cache: Optional[TableMetadata] = None
        self._snapshot_cache: Optional[IcebergSnapshot] = None
        self._files_cache: Optional[List[FileInfo]] = None

    # -- snapshot resolution ------------------------------------------------
    def _metadata(self) -> TableMetadata:
        if self._metadata_cache is None:
            self._metadata_cache = self._table.load_metadata()
        return self._metadata_cache

    def _snapshot(self) -> Optional[IcebergSnapshot]:
        """Resolve time travel: ``snapshot-id`` wins, then
        ``as-of-timestamp`` (epoch ms), else the current snapshot
        (IcebergRelation.scala:50-55's option handling)."""
        if self._snapshot_cache is None:
            opts = self.options
            md = self._metadata()
            if "snapshot-id" in opts:
                self._snapshot_cache = md.snapshot_by_id(int(opts["snapshot-id"]))
            elif "as-of-timestamp" in opts:
                self._snapshot_cache = md.snapshot_for_timestamp(
                    int(opts["as-of-timestamp"]))
            else:
                self._snapshot_cache = md.current_snapshot()
        return self._snapshot_cache

    @property
    def snapshot_id(self) -> Optional[int]:
        snap = self._snapshot()
        return snap.snapshot_id if snap else None

    # -- FileBasedRelation --------------------------------------------------
    def all_files(self, tracker: Optional[FileIdTracker] = None) -> List[FileInfo]:
        """Files from manifest scan planning, not a directory walk
        (IcebergRelation.scala:60-63): replaced/deleted files still exist on
        disk but are NOT part of the snapshot.  The planned list is cached on
        the relation (a refresh calls this several times; re-parsing the Avro
        manifests and re-stat'ing every data file per call would multiply the
        metadata IO by file count)."""
        if self._files_cache is None:
            self._files_cache = []
            for f in self._table.plan_files(self._snapshot(), self._metadata()):
                mtime = int(os.stat(f.path).st_mtime * 1000) \
                    if os.path.isfile(f.path) else 0
                self._files_cache.append(FileInfo(f.path, f.size, mtime, -1))
        if tracker is None:
            return list(self._files_cache)
        return [FileInfo(f.name, f.size, f.mtime,
                         tracker.add_file(f.name, f.size, f.mtime))
                for f in self._files_cache]

    def schema(self) -> Dict[str, str]:
        if self._metadata().schema.get("fields"):
            return arrow_schema_from_iceberg(self._metadata().schema)
        files = self.all_files()
        if not files:
            raise FileNotFoundError(
                f"Iceberg table {self.root_paths[0]} has no schema and no files")
        from hyperspace_tpu.io.parquet import read_schema

        return read_schema(files[0].name, "parquet")

    def signature(self) -> str:
        """Snapshot id + location — O(1), no file walk
        (IcebergRelation.scala:50-55)."""
        return f"{self.snapshot_id}{self._metadata().location}"

    def create_relation_metadata(self, tracker: FileIdTracker) -> Relation:
        files = self.all_files(tracker)
        snap = self._snapshot()
        # Pin the indexed snapshot; drop any path-ish options
        # (IcebergRelation.scala:100-105).
        opts = {k: v for k, v in self.options.items() if k != "path"}
        if snap is not None:
            opts["snapshot-id"] = str(snap.snapshot_id)
            opts["as-of-timestamp"] = str(snap.timestamp_ms)
        return Relation(
            root_paths=[self._table.table_path],
            content=Content.from_leaf_files(files)
            or Content.from_directory(self._table.table_path, tracker),
            schema=self.schema(),
            file_format=ICEBERG_FORMAT,
            options=opts,
        )

    # -- multi-version index selection (beyond reference: the Delta-only
    # closestIndex, DeltaLakeRelation.scala:186-243, extended to Iceberg's
    # snapshot timeline) -----------------------------------------------------
    def _snapshot_order(self):
        """snapshot_id -> position on the timestamp-ordered timeline."""
        return {s.snapshot_id: i for i, s in enumerate(
            sorted(self._metadata().snapshots,
                   key=lambda s: s.timestamp_ms))}

    def _version_history(self, entry, order):
        """[(index log version, snapshot position)] ascending; when several
        index versions map to one snapshot (optimize), keep the highest."""
        raw = entry.properties.get(ICEBERG_VERSION_HISTORY_PROPERTY, "")
        if not raw:
            return []
        by_pos = {}
        for pair in raw.split(","):
            index_v, snap_id = (int(x) for x in pair.split(":"))
            pos = order.get(snap_id)
            if pos is None:
                continue  # expired snapshot: its index version can't anchor
            by_pos[pos] = max(index_v, by_pos.get(pos, -1))
        return sorted(((iv, pos) for pos, iv in by_pos.items()),
                      key=lambda t: t[1])

    def closest_index(self, entry):
        """The Delta closestIndex algorithm over Iceberg's snapshot
        timeline (shared FileBasedRelation helper)."""
        snap = self._snapshot()
        if snap is None:
            return entry
        order = self._snapshot_order()
        return self._select_closest_version(
            entry, self._session, self._version_history(entry, order),
            order.get(snap.snapshot_id))


class IcebergSource(FileBasedSourceProvider):
    name = "iceberg"

    def __init__(self, conf: HyperspaceConf) -> None:
        self._conf = conf
        self._session = None

    def bind_session(self, session) -> None:
        """Index-manager access for closest_index (as DeltaLakeSource)."""
        self._session = session

    def is_supported_relation(self, scan: Scan) -> Optional[bool]:
        return True if scan.relation.file_format.lower() == ICEBERG_FORMAT \
            else None

    def get_relation(self, scan: Scan) -> Optional[FileBasedRelation]:
        if not self.is_supported_relation(scan):
            return None
        return IcebergRelation(scan, self._conf, self._session)

    def internal_file_format_name(self, relation: Relation) -> Optional[str]:
        return "parquet" if relation.file_format == ICEBERG_FORMAT else None

    def refresh_relation_metadata(self, relation: Relation) -> Optional[Relation]:
        """Drop the snapshot pins so refresh sees the latest data
        (IcebergFileBasedSource.scala:45-52)."""
        if relation.file_format != ICEBERG_FORMAT:
            return None
        import dataclasses as dc

        opts = {k: v for k, v in relation.options.items()
                if k not in ("snapshot-id", "as-of-timestamp")}
        return dc.replace(relation, options=opts)

    def enrich_index_properties(self, relation: Relation,
                                properties: Dict[str, str]) -> Optional[Dict[str, str]]:
        """Append "indexLogVersion:snapshotId" to the snapshot history so
        time-traveled reads can pick the closest index version (the
        reference passes through here, IcebergFileBasedSource.scala:99-107
        — this history is the beyond-parity Delta symmetry)."""
        if relation.file_format != ICEBERG_FORMAT:
            return None
        out = dict(properties)
        index_version = properties.get(INDEX_LOG_VERSION_PROPERTY)
        snap_id = relation.options.get("snapshot-id")
        if index_version is not None and snap_id is not None:
            pair = f"{index_version}:{snap_id}"
            history = properties.get(ICEBERG_VERSION_HISTORY_PROPERTY)
            out[ICEBERG_VERSION_HISTORY_PROPERTY] = \
                f"{history},{pair}" if history else pair
        return out
