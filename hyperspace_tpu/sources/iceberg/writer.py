"""Minimal Iceberg table writer: append / overwrite / file-delete commits.

Produces spec-shaped HadoopTables-style tables (Parquet data files, Avro
manifest lists + manifests, ``v<N>.metadata.json`` + ``version-hint.text``)
that our reader understands.  Exists because the TPU engine owns its IO path
end to end — the reference leans on the iceberg-spark-runtime writer; our
tests and users need a native way to fabricate and mutate Iceberg tables
(the role ``df.write.format("iceberg")`` plays in IcebergIntegrationTest /
HybridScanForIcebergTest).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List

import pyarrow as pa
import pyarrow.parquet as pq

from hyperspace_tpu.io import avro
from hyperspace_tpu.io.schemas import iceberg_schema
from hyperspace_tpu.sources.iceberg.metadata import (
    MANIFEST_ENTRY_SCHEMA,
    MANIFEST_LIST_SCHEMA,
    METADATA_DIR,
    STATUS_ADDED,
    STATUS_DELETED,
    STATUS_EXISTING,
    VERSION_HINT,
    DataFile,
    IcebergTable,
    TableMetadata,
)

def _new_snapshot_id() -> int:
    return uuid.uuid4().int & ((1 << 62) - 1)


def _evolve_schema(metadata: TableMetadata, arrow_schema: pa.Schema) -> Dict:
    """Schema for an overwrite with possibly-changed columns.  The spec
    requires field ids to be unique across table HISTORY: a surviving column
    (same name + type) keeps its id; anything else takes a fresh id above
    last-column-id — reusing a dropped column's id would bind its historical
    data to the new column in field-id-based readers."""
    fresh = iceberg_schema(arrow_schema)
    old_by_name = {f["name"]: f for f in metadata.schema.get("fields", [])}
    next_id = max(metadata.last_column_id,
                  max((f["id"] for f in old_by_name.values()), default=0))
    fields = []
    for f in fresh["fields"]:
        old = old_by_name.get(f["name"])
        if old is not None and old.get("type") == f["type"]:
            fields.append({**f, "id": old["id"]})
        else:
            next_id += 1
            fields.append({**f, "id": next_id})
    return {"type": "struct", "schema-id": 0, "fields": fields}


def _check_append_schema(metadata: TableMetadata, arrow_schema: pa.Schema,
                         path: str) -> None:
    """Appends pin the table schema, so a mismatched table would commit
    silently and only surface later as null columns at read time; fail the
    commit instead (Iceberg writers validate the same way).  Omitting
    optional table columns is legal (all our fields are optional; readers
    null-fill), but unknown columns or changed types are not."""
    fresh = {f["name"]: f["type"] for f in iceberg_schema(arrow_schema)["fields"]}
    existing = {f["name"]: f["type"]
                for f in metadata.schema.get("fields", [])}
    problems = [f"unknown column {n!r} ({t})" for n, t in sorted(fresh.items())
                if n not in existing]
    problems += [f"column {n!r} is {t}, table has {existing[n]}"
                 for n, t in sorted(fresh.items())
                 if n in existing and t != existing[n]]
    if problems:
        raise ValueError(
            f"Appended data schema does not match Iceberg table {path}: "
            f"{'; '.join(problems)}; use mode='overwrite' to change the "
            f"schema")


def _write_manifest(table_path: str, entries: List[Dict],
                    snapshot_id: int) -> Dict:
    name = f"{uuid.uuid4().hex}-m0.avro"
    path = os.path.join(table_path, METADATA_DIR, name)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    avro.write_container(path, MANIFEST_ENTRY_SCHEMA, entries,
                         metadata={"schema": json.dumps(MANIFEST_ENTRY_SCHEMA),
                                   "format-version": "1"})
    added = sum(1 for e in entries if e["status"] == STATUS_ADDED)
    existing = sum(1 for e in entries if e["status"] == STATUS_EXISTING)
    deleted = sum(1 for e in entries if e["status"] == STATUS_DELETED)
    return {
        "manifest_path": path,
        "manifest_length": os.stat(path).st_size,
        "partition_spec_id": 0,
        "added_snapshot_id": snapshot_id,
        "added_data_files_count": added,
        "existing_data_files_count": existing,
        "deleted_data_files_count": deleted,
    }


def _commit(table: IcebergTable, metadata: TableMetadata,
            manifest_files: List[Dict], snapshot_id: int, now_ms: int,
            schema: Dict, properties: Dict[str, str],
            operation: str, table_uuid: str) -> int:
    """Write the manifest list + next metadata version (create-if-absent on
    the metadata file = the optimistic commit point, as in HadoopTables)."""
    md_dir = os.path.join(table.table_path, METADATA_DIR)
    os.makedirs(md_dir, exist_ok=True)
    list_path = os.path.join(
        md_dir, f"snap-{snapshot_id}-1-{uuid.uuid4().hex}.avro")
    avro.write_container(list_path, MANIFEST_LIST_SCHEMA, manifest_files,
                         metadata={"format-version": "1"})

    snapshots = [
        {"snapshot-id": s.snapshot_id, "timestamp-ms": s.timestamp_ms,
         "manifest-list": s.manifest_list, "summary": s.summary}
        for s in (metadata.snapshots if metadata else [])
    ]
    snapshots.append({
        "snapshot-id": snapshot_id,
        "timestamp-ms": now_ms,
        "manifest-list": list_path,
        "summary": {"operation": operation},
    })
    version = (metadata.metadata_version + 1) if metadata else 1
    doc = {
        "format-version": 1,
        "table-uuid": table_uuid,
        "location": table.table_path,
        "last-updated-ms": now_ms,
        # Monotonic across history even if the highest-id column was dropped.
        "last-column-id": max(
            [f["id"] for f in schema["fields"]]
            + [metadata.last_column_id if metadata else 0]),
        "schema": schema,
        "partition-spec": [],
        "properties": properties,
        "current-snapshot-id": snapshot_id,
        "snapshots": snapshots,
    }
    md_path = os.path.join(md_dir, f"v{version}.metadata.json")
    # 'x' = exclusive create: racing writers on the same version — one wins.
    with open(md_path, "x", encoding="utf-8") as f:
        json.dump(doc, f, indent=2)
    with open(os.path.join(md_dir, VERSION_HINT), "w", encoding="utf-8") as f:
        f.write(str(version))
    return version


def _entry(status: int, snapshot_id: int, f: DataFile) -> Dict:
    return {"status": status, "snapshot_id": snapshot_id,
            "data_file": {"file_path": f.path, "file_format": "PARQUET",
                          "record_count": f.record_count,
                          "file_size_in_bytes": f.size}}


def write_iceberg(data: pa.Table, path: str, mode: str = "append") -> int:
    """Write ``data`` to the Iceberg table at ``path``; returns the new
    snapshot id.  ``mode``: "append" adds files; "overwrite" replaces the
    live file set.  Tables are unpartitioned."""
    if mode not in ("append", "overwrite"):
        raise ValueError(f"Unknown write mode {mode!r}")
    table = IcebergTable(path)
    now_ms = int(time.time() * 1000)
    exists = table.exists()
    metadata = table.load_metadata() if exists else None
    if metadata and metadata.snapshots:
        latest_ts = max(s.timestamp_ms for s in metadata.snapshots)
        if now_ms <= latest_ts:  # keep as-of-timestamp resolution unambiguous
            now_ms = latest_ts + 1
    # Overwrite may change the schema (appends must conform to the table's);
    # stale schema metadata would make readers resolve the wrong column set.
    if metadata and mode == "append":
        _check_append_schema(metadata, data.schema, path)
        schema = metadata.schema
    elif metadata:
        schema = _evolve_schema(metadata, data.schema)
    else:
        schema = iceberg_schema(data.schema)
    table_uuid = metadata.table_uuid if metadata else str(uuid.uuid4())
    properties = metadata.properties if metadata else {}

    data_dir = os.path.join(table.table_path, "data")
    os.makedirs(data_dir, exist_ok=True)
    file_path = os.path.join(
        data_dir, f"{uuid.uuid4().hex}-00000.parquet")
    pq.write_table(data, file_path)
    new_file = DataFile(file_path, os.stat(file_path).st_size, data.num_rows)

    snapshot_id = _new_snapshot_id()
    carried: List[DataFile] = []
    if exists and mode == "append":
        carried = table.plan_files(metadata=metadata)
    entries = [_entry(STATUS_EXISTING, snapshot_id, f) for f in carried]
    entries.append(_entry(STATUS_ADDED, snapshot_id, new_file))
    manifest = _write_manifest(table.table_path, entries, snapshot_id)
    _commit(table, metadata, [manifest], snapshot_id, now_ms, schema,
            properties, mode, table_uuid)
    return snapshot_id


def delete_file_iceberg(path: str, file_path: str) -> int:
    """Commit a snapshot that drops one data file (simulates row deletion at
    file granularity — the unit Hybrid Scan's deleted-files handling works
    at)."""
    table = IcebergTable(path)
    metadata = table.load_metadata()
    now_ms = int(time.time() * 1000)
    if metadata.snapshots:
        latest_ts = max(s.timestamp_ms for s in metadata.snapshots)
        if now_ms <= latest_ts:
            now_ms = latest_ts + 1
    live = table.plan_files(metadata=metadata)
    target = os.path.abspath(file_path)
    if not any(f.path == target for f in live):
        raise FileNotFoundError(f"{file_path} is not a live file of {path}")
    snapshot_id = _new_snapshot_id()
    entries = [_entry(STATUS_EXISTING, snapshot_id, f)
               for f in live if f.path != target]
    entries.extend(_entry(STATUS_DELETED, snapshot_id, f)
                   for f in live if f.path == target)
    manifest = _write_manifest(table.table_path, entries, snapshot_id)
    _commit(table, metadata, [manifest], snapshot_id, now_ms, metadata.schema,
            metadata.properties, "delete", metadata.table_uuid)
    return snapshot_id


# ---------------------------------------------------------------------------
# Row-level CDC commits (the shape MERGE INTO / DELETE WHERE leave behind)
# ---------------------------------------------------------------------------
def _next_ts(metadata: TableMetadata) -> int:
    now_ms = int(time.time() * 1000)
    if metadata.snapshots:
        latest_ts = max(s.timestamp_ms for s in metadata.snapshots)
        if now_ms <= latest_ts:
            now_ms = latest_ts + 1
    return now_ms


def _write_data_file(table: IcebergTable, data: pa.Table) -> DataFile:
    data_dir = os.path.join(table.table_path, "data")
    os.makedirs(data_dir, exist_ok=True)
    file_path = os.path.join(data_dir, f"{uuid.uuid4().hex}-00000.parquet")
    pq.write_table(data, file_path)
    return DataFile(file_path, os.stat(file_path).st_size, data.num_rows)


def _rewrite_entries(table: IcebergTable, live: List[DataFile], key: str,
                     key_set: pa.Array, snapshot_id: int) -> List[Dict]:
    """Copy-on-write row rewrite: live files holding a matching ``key``
    become STATUS_DELETED and their surviving rows land in fresh
    STATUS_ADDED files; untouched files ride along STATUS_EXISTING —
    the single-snapshot file-level signature a real MERGE/DELETE leaves
    (and what hybrid scan's deleted/appended overlay merges at read
    time)."""
    import pyarrow.compute as pc

    entries: List[Dict] = []
    for f in live:
        data = pq.read_table(f.path)
        if key not in data.column_names:
            raise ValueError(f"Key column {key!r} not in {f.path}")
        mask = pc.is_in(data.column(key),
                        value_set=key_set.cast(
                            data.schema.field(key).type))
        if not pc.any(mask).as_py():
            entries.append(_entry(STATUS_EXISTING, snapshot_id, f))
            continue
        entries.append(_entry(STATUS_DELETED, snapshot_id, f))
        survivors = data.filter(pc.invert(mask))
        if survivors.num_rows:
            entries.append(_entry(STATUS_ADDED, snapshot_id,
                                  _write_data_file(table, survivors)))
    return entries


def upsert_iceberg(data: pa.Table, path: str, key: str) -> int:
    """MERGE ``data`` into the Iceberg table at ``path`` keyed on column
    ``key``: existing rows with a matching key are replaced, the rest
    are inserted — ONE snapshot carrying the deleted/rewritten entries
    for every touched file plus one data file with the upserted rows
    (format-v1 copy-on-write; hyperspace absorbs it as merge-on-read
    debt via the quick refresh).  Returns the new snapshot id; creates
    the table when it does not exist."""
    table = IcebergTable(path)
    if not table.exists():
        return write_iceberg(data, path, mode="append")
    metadata = table.load_metadata()
    _check_append_schema(metadata, data.schema, path)
    now_ms = _next_ts(metadata)
    snapshot_id = _new_snapshot_id()
    live = table.plan_files(metadata=metadata)
    entries = _rewrite_entries(table, live, key,
                               data.column(key).combine_chunks(),
                               snapshot_id)
    entries.append(_entry(STATUS_ADDED, snapshot_id,
                          _write_data_file(table, data)))
    manifest = _write_manifest(table.table_path, entries, snapshot_id)
    _commit(table, metadata, [manifest], snapshot_id, now_ms,
            metadata.schema, metadata.properties, "overwrite",
            metadata.table_uuid)
    return snapshot_id


def delete_rows_iceberg(path: str, key: str, values) -> int:
    """DELETE the rows of the Iceberg table at ``path`` whose ``key``
    column matches ``values`` — ONE snapshot marking each touched file
    deleted and adding its surviving rows back.  Returns the new
    snapshot id, or the current one unchanged when no row matched."""
    table = IcebergTable(path)
    metadata = table.load_metadata()
    now_ms = _next_ts(metadata)
    snapshot_id = _new_snapshot_id()
    live = table.plan_files(metadata=metadata)
    entries = _rewrite_entries(table, live, key, pa.array(list(values)),
                               snapshot_id)
    if all(e["status"] == STATUS_EXISTING for e in entries):
        return metadata.current_snapshot_id  # nothing matched: no commit
    manifest = _write_manifest(table.table_path, entries, snapshot_id)
    _commit(table, metadata, [manifest], snapshot_id, now_ms,
            metadata.schema, metadata.properties, "delete",
            metadata.table_uuid)
    return snapshot_id
