"""Iceberg table metadata: spec-shaped reader for HadoopTables-style tables.

An Iceberg table directory holds ``metadata/`` (numbered
``v<N>.metadata.json`` files plus a ``version-hint.text`` pointer) and
``data/`` Parquet files.  Each snapshot points at a **manifest list** (Avro)
whose entries point at **manifests** (Avro) whose entries are the data files.
Planning a scan = read current metadata -> resolve snapshot -> read its
manifest list -> read live entries from each manifest.

Reference parity: this replaces what the reference obtains from the
``iceberg-spark-runtime`` jar — ``HadoopTables.load`` + ``table.newScan()
.planFiles()`` (sources/iceberg/IcebergRelation.scala:60-63,205-219) and
snapshot/time-travel resolution — re-implemented natively because the TPU
engine owns its IO path (the Avro substrate is hyperspace_tpu/io/avro.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Dict, List, Optional

from hyperspace_tpu.exceptions import CorruptMetadataError
from hyperspace_tpu.io import avro

METADATA_DIR = "metadata"
VERSION_HINT = "version-hint.text"
_METADATA_RE = re.compile(r"^v(\d+)\.metadata\.json$")

# Manifest-list entry schema (Iceberg spec, format v1 required fields).
MANIFEST_LIST_SCHEMA: Dict[str, Any] = {
    "type": "record",
    "name": "manifest_file",
    "fields": [
        {"name": "manifest_path", "type": "string", "field-id": 500},
        {"name": "manifest_length", "type": "long", "field-id": 501},
        {"name": "partition_spec_id", "type": "int", "field-id": 502},
        {"name": "added_snapshot_id", "type": ["null", "long"], "default": None,
         "field-id": 503},
        {"name": "added_data_files_count", "type": ["null", "int"],
         "default": None, "field-id": 504},
        {"name": "existing_data_files_count", "type": ["null", "int"],
         "default": None, "field-id": 505},
        {"name": "deleted_data_files_count", "type": ["null", "int"],
         "default": None, "field-id": 506},
    ],
}

# Manifest entry schema (status + nested data_file record).
MANIFEST_ENTRY_SCHEMA: Dict[str, Any] = {
    "type": "record",
    "name": "manifest_entry",
    "fields": [
        {"name": "status", "type": "int", "field-id": 0},
        {"name": "snapshot_id", "type": ["null", "long"], "default": None,
         "field-id": 1},
        {"name": "data_file", "field-id": 2, "type": {
            "type": "record",
            "name": "r2",
            "fields": [
                {"name": "file_path", "type": "string", "field-id": 100},
                {"name": "file_format", "type": "string", "field-id": 101},
                {"name": "record_count", "type": "long", "field-id": 103},
                {"name": "file_size_in_bytes", "type": "long", "field-id": 104},
            ],
        }},
    ],
}

STATUS_EXISTING = 0
STATUS_ADDED = 1
STATUS_DELETED = 2


@dataclasses.dataclass(frozen=True)
class DataFile:
    """One live data file of a snapshot (absolute path)."""

    path: str
    size: int
    record_count: int


@dataclasses.dataclass
class IcebergSnapshot:
    snapshot_id: int
    timestamp_ms: int
    manifest_list: str
    summary: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class TableMetadata:
    location: str
    table_uuid: str
    current_snapshot_id: Optional[int]
    snapshots: List[IcebergSnapshot]
    schema: Dict[str, Any]          # Iceberg schema JSON (fields w/ ids)
    partition_spec: List[Dict[str, Any]]
    properties: Dict[str, str]
    last_column_id: int
    metadata_version: int

    def snapshot_by_id(self, snapshot_id: int) -> IcebergSnapshot:
        for s in self.snapshots:
            if s.snapshot_id == snapshot_id:
                return s
        raise ValueError(f"Snapshot {snapshot_id} not found in {self.location}")

    def current_snapshot(self) -> Optional[IcebergSnapshot]:
        if self.current_snapshot_id is None:
            return None
        return self.snapshot_by_id(self.current_snapshot_id)

    def snapshot_for_timestamp(self, timestamp_ms: int) -> IcebergSnapshot:
        """Latest snapshot committed at or before ``timestamp_ms``
        (``as-of-timestamp`` resolution)."""
        best: Optional[IcebergSnapshot] = None
        for s in sorted(self.snapshots, key=lambda s: s.timestamp_ms):
            if s.timestamp_ms <= timestamp_ms:
                best = s
        if best is None:
            raise ValueError(
                f"No snapshot at or before timestamp {timestamp_ms} in "
                f"{self.location}")
        return best


class IcebergTable:
    """Reader for one HadoopTables-style Iceberg table."""

    def __init__(self, table_path: str) -> None:
        self.table_path = os.path.abspath(table_path)
        self.metadata_path = os.path.join(self.table_path, METADATA_DIR)

    # -- discovery ----------------------------------------------------------
    def exists(self) -> bool:
        return bool(self.metadata_versions())

    def metadata_versions(self) -> List[int]:
        if not os.path.isdir(self.metadata_path):
            return []
        out = []
        for name in os.listdir(self.metadata_path):
            m = _METADATA_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_metadata_version(self) -> int:
        hint = os.path.join(self.metadata_path, VERSION_HINT)
        if os.path.isfile(hint):
            with open(hint, "r", encoding="utf-8") as f:
                try:
                    return int(f.read().strip())
                except ValueError:
                    pass
        versions = self.metadata_versions()
        if not versions:
            raise FileNotFoundError(f"Not an Iceberg table: {self.table_path}")
        return versions[-1]

    # -- metadata -----------------------------------------------------------
    def load_metadata(self, version: Optional[int] = None) -> TableMetadata:
        if version is None:
            version = self.latest_metadata_version()
        path = os.path.join(self.metadata_path, f"v{version}.metadata.json")
        with open(path, "r", encoding="utf-8") as f:
            try:
                raw = json.load(f)
            except ValueError as e:
                # A truncated metadata JSON (torn upload, partial copy)
                # must name the bad file, not surface a bare decode error.
                raise CorruptMetadataError(
                    f"Truncated or corrupt Iceberg metadata {path!r}: "
                    f"{e}") from e
        snapshots = [
            IcebergSnapshot(
                snapshot_id=int(s["snapshot-id"]),
                timestamp_ms=int(s["timestamp-ms"]),
                manifest_list=self._absolute(s["manifest-list"]),
                summary={k: str(v) for k, v in s.get("summary", {}).items()},
            )
            for s in raw.get("snapshots", [])
        ]
        schema = raw.get("schema")
        if schema is None:
            schemas = raw.get("schemas", [])
            current = raw.get("current-schema-id", 0)
            schema = next((s for s in schemas if s.get("schema-id") == current),
                          schemas[0] if schemas else {"type": "struct",
                                                      "fields": []})
        spec = raw.get("partition-spec")
        if spec is None:
            specs = raw.get("partition-specs", [])
            default = raw.get("default-spec-id", 0)
            spec_obj = next((s for s in specs if s.get("spec-id") == default),
                            None)
            spec = spec_obj.get("fields", []) if spec_obj else []
        return TableMetadata(
            location=raw.get("location", self.table_path),
            table_uuid=raw.get("table-uuid", ""),
            current_snapshot_id=raw.get("current-snapshot-id")
            if raw.get("current-snapshot-id", -1) != -1 else None,
            snapshots=snapshots,
            schema=schema,
            partition_spec=spec,
            properties={k: str(v) for k, v in raw.get("properties", {}).items()},
            last_column_id=int(raw.get("last-column-id", 0)),
            metadata_version=version,
        )

    # -- scan planning ------------------------------------------------------
    def plan_files(self, snapshot: Optional[IcebergSnapshot] = None,
                   metadata: Optional[TableMetadata] = None) -> List[DataFile]:
        """Live data files of ``snapshot`` (default: current) — the native
        ``table.newScan().planFiles()``."""
        metadata = metadata or self.load_metadata()
        snapshot = snapshot or metadata.current_snapshot()
        if snapshot is None:
            return []
        out: List[DataFile] = []
        for mf in self._read_manifest_avro(snapshot.manifest_list,
                                           "manifest list"):
            manifest_path = self._absolute(mf["manifest_path"])
            for entry in self._read_manifest_avro(manifest_path, "manifest"):
                if entry["status"] == STATUS_DELETED:
                    continue
                df = entry["data_file"]
                out.append(DataFile(self._absolute(df["file_path"]),
                                    int(df["file_size_in_bytes"]),
                                    int(df["record_count"])))
        return sorted(out, key=lambda f: f.path)

    @staticmethod
    def _read_manifest_avro(path: str, kind: str):
        """Avro container read with a torn-file diagnostic: a truncated
        manifest (the io/avro reader raises EOFError/ValueError/KeyError
        mid-decode) names the file and its role instead of surfacing a
        low-level decode error."""
        try:
            return avro.read_container(path)
        except (ValueError, KeyError, EOFError, IndexError, TypeError) as e:
            raise CorruptMetadataError(
                f"Truncated or corrupt Iceberg {kind} {path!r}: {e}") from e

    def _absolute(self, path: str) -> str:
        if os.path.isabs(path):
            return path
        # Spec paths are absolute URIs; tolerate relative and file: URIs.
        if path.startswith("file:"):
            return re.sub(r"^file:/{0,2}(/)", r"\1", path)
        return os.path.join(self.table_path, path)


def iceberg_schema_fields(schema: Dict[str, Any]) -> List[Dict[str, Any]]:
    return list(schema.get("fields", []))
