from hyperspace_tpu.sources.iceberg.metadata import IcebergTable
from hyperspace_tpu.sources.iceberg.provider import IcebergRelation, IcebergSource
from hyperspace_tpu.sources.iceberg.writer import delete_file_iceberg, write_iceberg

__all__ = ["IcebergTable", "IcebergRelation", "IcebergSource",
           "write_iceberg", "delete_file_iceberg"]
