"""Source provider manager: dispatches each API to exactly one provider.

Reference contract: sources/FileBasedSourceProviderManager.scala:38-183 —
providers come from conf (comma-separated names); each call must be answered
by exactly one provider (error on 0 or >1, :117-155).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, TypeVar

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.log_entry import Relation
from hyperspace_tpu.plan.nodes import Scan
from hyperspace_tpu.sources.interfaces import FileBasedRelation, FileBasedSourceProvider

T = TypeVar("T")

# Name → factory registry; lake providers register themselves on import
# (the conf-class-name reflection of FileBasedSourceProviderManager.scala:166-182).
PROVIDER_REGISTRY: Dict[str, Callable[[HyperspaceConf], FileBasedSourceProvider]] = {}


def register_provider(name: str,
                      factory: Callable[[HyperspaceConf], FileBasedSourceProvider]) -> None:
    PROVIDER_REGISTRY[name] = factory


def _builtin_providers() -> None:
    if "default" not in PROVIDER_REGISTRY:
        from hyperspace_tpu.sources.default.provider import DefaultFileBasedSource

        register_provider("default", DefaultFileBasedSource)
    if "delta" not in PROVIDER_REGISTRY:
        from hyperspace_tpu.sources.delta.provider import DeltaLakeSource

        register_provider("delta", DeltaLakeSource)
    if "iceberg" not in PROVIDER_REGISTRY:
        from hyperspace_tpu.sources.iceberg.provider import IcebergSource

        register_provider("iceberg", IcebergSource)


class FileBasedSourceProviderManager:
    def __init__(self, conf: HyperspaceConf, session=None) -> None:
        _builtin_providers()
        self._conf = conf
        names = [n.strip() for n in conf.source_providers.split(",") if n.strip()]
        unknown = [n for n in names if n not in PROVIDER_REGISTRY]
        if unknown:
            raise HyperspaceError(f"Unknown source providers: {unknown}")
        self._providers: List[FileBasedSourceProvider] = [
            PROVIDER_REGISTRY[n](conf) for n in names]
        if session is not None:
            # Providers that need session context (index-manager lookups for
            # closest_index) opt in via bind_session.
            for p in self._providers:
                if hasattr(p, "bind_session"):
                    p.bind_session(session)

    def _run(self, api: str, fn: Callable[[FileBasedSourceProvider], Optional[T]]) -> T:
        """Exactly-one-provider dispatch
        (FileBasedSourceProviderManager.scala:117-155)."""
        answers = [(p, r) for p in self._providers if (r := fn(p)) is not None]
        if len(answers) == 0:
            raise HyperspaceError(f"No source provider answered {api}")
        if len(answers) > 1:
            names = [p.name for p, _ in answers]
            raise HyperspaceError(f"Multiple source providers answered {api}: {names}")
        return answers[0][1]

    def is_supported_relation(self, scan: Scan) -> bool:
        try:
            return self._run("is_supported_relation",
                             lambda p: p.is_supported_relation(scan) or None)
        except HyperspaceError:
            return False

    def get_relation(self, scan: Scan) -> FileBasedRelation:
        return self._run("get_relation", lambda p: p.get_relation(scan))

    def internal_file_format_name(self, relation: Relation) -> str:
        return self._run("internal_file_format_name",
                         lambda p: p.internal_file_format_name(relation))

    def refresh_relation_metadata(self, relation: Relation) -> Relation:
        return self._run("refresh_relation_metadata",
                         lambda p: p.refresh_relation_metadata(relation))

    def enrich_index_properties(self, relation: Relation,
                                properties: Dict[str, str]) -> Dict[str, str]:
        return self._run("enrich_index_properties",
                         lambda p: p.enrich_index_properties(relation, properties))
