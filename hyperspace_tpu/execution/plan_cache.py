"""Optimize-result cache for the serving layer: pay optimize once per
query shape, serve repeats straight to the executor.

The serving workload is repeat-heavy (ROADMAP item 2: many clients, a
mixed filter/join/agg template set), and each repeat pays the full
optimizer pass — subquery rewrite, pushdown, pruning, rule matching over
every ACTIVE index — before executing.  This cache keys the OPTIMIZED
plan by:

  - the PR 5 advisor's STRUCTURAL plan fingerprint
    (``advisor/workload.fingerprint``: per-relation filter/join/group
    columns, never literal values) — the coarse bucket, shared with the
    workload-capture subsystem so one fingerprint walk feeds both;
  - a digest of the full plan tree INCLUDING literals
    (``plan.tree_string()``): two queries that share a shape but pin
    different values optimize to different plans (bucket pruning prunes
    different buckets), so literals must be part of the key;
  - the session's hyperspace-enabled switch (same plan, rules on vs off,
    different result).

Entries are invalidated three ways:

  - **generation**: every committed index action (create/refresh/vacuum/
    optimize/delete — actions/base.py) bumps a process-global generation;
    entries carry the generation they were built under and a stale
    generation is a miss.  This is what makes "build an index while the
    server runs" safe: the very next request re-optimizes and picks the
    new index up.
  - **TTL**: source data can drift without any index action (files
    appended under a scanned root).  Entries expire after ``ttl_s`` —
    the serving layer passes ``hyperspace.index.cache.expiryDurationInSeconds``,
    the same staleness window the index-listing cache already accepts.
  - **explicit**: the serving layer drops an entry whose plan failed at
    execution before running the degraded/containment machinery, so a
    cached plan over quarantined files cannot fail twice.

Eviction is the byte-budget LRU shared with the HBM column cache
(:class:`~hyperspace_tpu.execution.device_cache.ByteBudgetLRU`), entry
size estimated from the rendered tree plus the index scans' materialized
file lists (the dominant cost of a cached plan).  Metrics land under
``serve.plan_cache.*`` (hits/misses/evictions counters, bytes gauge).
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Optional, Tuple

from hyperspace_tpu.execution.device_cache import ByteBudgetLRU
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan

# Process-global plan generation: bumped by every committed index action.
# Process-global (not per-session) because sessions share the on-disk
# index state — an action through ANY session invalidates every cache.
_generation = 0
_generation_lock = threading.Lock()


def bump_generation() -> None:
    global _generation
    with _generation_lock:
        _generation += 1


def current_generation() -> int:
    with _generation_lock:
        return _generation


def _plan_bytes_estimate(rendered: str, plan: LogicalPlan) -> int:
    """Approximate retained size of a cached plan: the rendered tree plus
    the per-scan file lists (index scans materialize every file path)."""
    total = len(rendered)
    for scan in plan.leaf_relations():
        if isinstance(scan, Scan) and scan.relation.file_paths:
            total += sum(len(p) for p in scan.relation.file_paths)
    return total + 256  # node-object overhead floor


class PlanCache:
    """Thread-safe optimize-result cache (one per serving endpoint)."""

    def __init__(self, budget_bytes: int = 64 << 20,
                 ttl_s: float = 300.0) -> None:
        self.budget_bytes = int(budget_bytes)
        self.ttl_s = float(ttl_s)
        self._lru = ByteBudgetLRU(metric_prefix="serve.plan_cache")

    # -- keying -------------------------------------------------------------
    def key_for(self, session, plan: LogicalPlan) -> Optional[str]:
        """Cache key for the USER plan, or None when the plan is not
        cacheable (no source relations to fingerprint, or fingerprinting
        itself fails — a cache must never fail a query)."""
        try:
            from hyperspace_tpu.advisor import workload

            fp = workload.fingerprint(session, plan)
            if fp is None:
                return None
            structural = workload.fingerprint_key(fp)
            literal = hashlib.sha1(
                plan.tree_string().encode("utf-8")).hexdigest()[:16]
            enabled = "1" if session.is_hyperspace_enabled() else "0"
            return f"{structural}:{literal}:{enabled}"
        except Exception:  # noqa: BLE001 — uncacheable, never fatal
            return None

    # -- lookup / store -----------------------------------------------------
    def get(self, key: str) -> Optional[LogicalPlan]:
        entry: Optional[Tuple[LogicalPlan, int, float]] = self._lru.peek(key)
        if entry is not None:
            plan, generation, stored_at = entry
            if generation == current_generation() \
                    and time.monotonic() - stored_at <= self.ttl_s:
                self._lru.get(key)  # hit accounting + recency bump
                return plan
            # Stale: an index action landed since, or the TTL passed.
            # Dropped BEFORE the counting lookup so the hit-rate the
            # bench reports means "served from cache", nothing else.
            self._lru.pop(key)
            from hyperspace_tpu.telemetry import metrics

            metrics.inc("serve.plan_cache.stale")
        self._lru.get(key)  # registers the miss
        return None

    def put(self, key: str, plan: LogicalPlan) -> None:
        try:
            rendered = plan.tree_string()
        except Exception:  # noqa: BLE001 — unrenderable = uncacheable
            return
        self._lru.put(key, (plan, current_generation(), time.monotonic()),
                      _plan_bytes_estimate(rendered, plan),
                      self.budget_bytes)

    def invalidate(self, key: str) -> None:
        self._lru.pop(key)

    def clear(self) -> None:
        self._lru.clear()

    def stats(self):
        return self._lru.stats()
