"""Physical execution: walk the logical plan, produce an arrow Table.

This plays the role of Spark's physical planning + execution for the tiny
operator set the rules target (§1 L2: FileSourceScanExec, SMJ,
BucketUnionExec).  The data plane routes to TPU kernels where the data is
numeric (predicates: ops/filter.py; equi-joins: ops/join.py) and falls back
to arrow/pandas host compute for variable-length data — mirroring how the
reference delegates string-heavy work to the JVM while we keep the MXU/VPU
fed with columnar numerics.

Scan semantics:
  - ``relation.file_paths`` overrides root-path listing (index scans and
    hybrid-scan subsets, RuleUtils.scala:255-286).
  - ``relation.prune_to_buckets`` drops index files whose bucket id (from
    the file name) is not needed — the bucket-pruning read
    (FilterIndexRule.scala:62-68).
"""

from __future__ import annotations

import os

from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from hyperspace_tpu.execution import sync_guard
from hyperspace_tpu.io import columnar
from hyperspace_tpu.telemetry import timeline
from hyperspace_tpu.utils import deadline as _deadline
from hyperspace_tpu.utils.compat import enable_x64 as _enable_x64
from hyperspace_tpu.io.files import list_data_files
from hyperspace_tpu.io.parquet import bucket_id_of_file, read_table
from hyperspace_tpu.plan.expr import (
    And,
    Arith,
    BinOp,
    Case,
    BucketIn,
    Cast,
    Col,
    Expr,
    Extract,
    IsIn,
    IsNull,
    Lit,
    Neg,
    Not,
    Or,
    StringFn,
    StringMatch,
)
from hyperspace_tpu.plan.nodes import (
    Aggregate,
    BucketUnion,
    Compute,
    Distinct,
    Filter,
    InMemory,
    Join,
    Limit,
    LogicalPlan,
    Project,
    Scan,
    SetOp,
    Sort,
    Union,
    Window,
    WithColumns,
)


from hyperspace_tpu.sources.interfaces import LAKE_DATA_FORMATS, physical_read_format


class Executor:
    def __init__(self, session) -> None:
        self.session = session
        # Physical execution stats (PhysicalOperatorAnalyzer.scala:30-58
        # intent): per-join strategy, per-scan file counts.  Read back via
        # session.last_execution_stats after Dataset.collect().
        self.stats: Dict[str, list] = {"joins": [], "scans": []}
        # File-identity provenance of scan outputs within THIS query, for
        # the HBM-resident column cache (execution/device_cache.py):
        # id(table) -> (fingerprint, cacheable column names, table ref —
        # kept so the id can't be recycled mid-query).
        self._scan_fp: Dict[int, Tuple[str, frozenset, pa.Table]] = {}
        # Engine mesh, resolved once per executor (= per collect):
        # ``hyperspace.parallel.mesh.enabled`` gates every sharded
        # dispatch below; None keeps the bit-equal single-device paths.
        self._mesh_cache: Tuple[bool, object] = (False, None)

    def _active_mesh(self):
        probed, mesh = self._mesh_cache
        if not probed:
            from hyperspace_tpu.parallel.mesh import active_mesh

            mesh = active_mesh(self.session.conf)
            self._mesh_cache = (True, mesh)
        return mesh

    # -- HBM-resident column cache ------------------------------------------
    def _register_scan_identity(self, table: pa.Table, paths) -> None:
        conf = self.session.conf
        if conf.device_cache_policy == "off" or conf.device_cache_bytes <= 0:
            return
        from hyperspace_tpu.execution.device_cache import files_fingerprint

        fp = files_fingerprint(paths)
        if fp:
            self._scan_fp[id(table)] = (
                fp, frozenset(table.column_names), table)

    def _scan_identity(self, table: pa.Table) -> Optional[Tuple[str, frozenset]]:
        entry = self._scan_fp.get(id(table))
        return (entry[0], entry[1]) if entry is not None else None

    def _register_derived_identity(self, out: pa.Table, parent_identity,
                                   transform: str) -> None:
        """Content identity for a table DERIVED from an identified scan by
        a deterministic transform (a filter predicate): the derived
        fingerprint hashes the parent fingerprint with the transform's
        stable repr, so a warm repeat of the same query over the same
        files addresses the same cached device arrays — the bridge that
        lets filtered join inputs go HBM-resident.  A different predicate
        or a changed file set changes the fingerprint; stale serving is
        impossible."""
        if parent_identity is None or out is None:
            return
        import hashlib

        fp, cacheable = parent_identity
        derived = hashlib.md5(
            f"{fp}|{transform}".encode()).hexdigest()
        self._scan_fp[id(out)] = (
            derived, cacheable & frozenset(out.column_names), out)

    def _propagate_identity(self, out: pa.Table, parent: pa.Table) -> None:
        """Row-preserving transforms (column selection) keep the parent's
        fingerprint: the surviving columns are the same arrays, so cache
        entries stay addressable under the same keys."""
        entry = self._scan_fp.get(id(parent))
        if entry is None or out is None:
            return
        fp, cacheable, _ref = entry
        self._scan_fp[id(out)] = (
            fp, cacheable & frozenset(out.column_names), out)

    def _cache_key(self, identity, column: str, kind: str):
        if identity is None:
            return None
        fp, cacheable = identity
        return (fp, column, kind) if column in cacheable else None

    def _all_resident(self, identity, pairs) -> bool:
        """True when every (column, kind) pair is already cached for this
        scan identity."""
        from hyperspace_tpu.execution.device_cache import global_cache

        cache = global_cache()
        keys = [self._cache_key(identity, c, k) for c, k in pairs]
        return bool(keys) and all(k is not None and cache.contains(k)
                                  for k in keys)

    def _device_column(self, table: pa.Table, column: str, identity,
                       kind: str):
        """The column in its device domain — from the resident cache when
        this scan's file identity is known (hit: zero transfer; miss:
        convert, place on device, and cache), host numpy otherwise."""
        key = self._cache_key(identity, column, kind)
        convert = (columnar.to_order_words if kind == "order"
                   else columnar.to_device_numeric)
        if key is None:
            return convert(table.column(column))
        from hyperspace_tpu.execution.device_cache import global_cache

        cache = global_cache()
        counters = self.stats.setdefault(
            "device_cache", {"hits": 0, "misses": 0})
        arr = cache.get(key)
        if arr is not None:
            counters["hits"] += 1
            return arr
        import jax

        host = convert(table.column(column))
        with _enable_x64():  # int64 columns must keep full width
            dev = jax.device_put(np.asarray(host))
        timeline.record_transfer("h2d", int(getattr(dev, "nbytes", 0)))
        cache.put(key, dev, self.session.conf.device_cache_bytes)
        counters["misses"] += 1
        return dev

    def _cache_aware_min_rows(self, identity, pairs, kind: str) -> int:
        """The effective routing threshold: the cold-transfer break-even
        normally, the latency-only resident break-even when every input
        (column, kind) pair is already cached for this scan (or will be
        under the 'eager' populate policy)."""
        conf = self.session.conf
        min_rows = conf.device_min_rows(kind)
        if identity is None:
            return min_rows
        # Eager lowers the threshold only when every input is CACHEABLE
        # (computed hidden columns never are, and neither are columns the
        # cache already rejected for exceeding the byte budget —
        # re-shipping them per query would pay the transfer forever, not
        # once).
        from hyperspace_tpu.execution.device_cache import global_cache

        cache = global_cache()
        keys = [self._cache_key(identity, c, k) for c, k in pairs]
        eager_all_cacheable = (
            conf.device_cache_policy == "eager"
            and all(k is not None and not cache.was_rejected(k)
                    for k in keys))
        if eager_all_cacheable or self._all_resident(identity, pairs):
            return min(min_rows, conf.resident_min_rows(kind))
        return min_rows

    def finalize_stats(self) -> None:
        """Close out one query's stats: sample the lightweight memory
        gauges (one getrusage call; live device-buffer bytes only when a
        device cache/kernel actually ran this query — walking live arrays
        is not free) into ``stats["memory"]`` and the process registry
        (``mem.host.peak_rss_mb`` / ``mem.device.live_bytes``).  Called
        once per collect(), never per operator."""
        from hyperspace_tpu.telemetry import metrics

        mem: Dict[str, float] = {}
        try:
            import resource

            mem["peak_rss_mb"] = round(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                / 1024.0, 1)
            metrics.set_gauge("mem.host.peak_rss_mb", mem["peak_rss_mb"])
        except Exception:  # noqa: BLE001 — non-POSIX platform
            pass
        touched_device = bool(
            self.stats.get("device_cache")
            or any(j.get("strategy") == "device"
                   for j in self.stats.get("join_kernels", []))
            or any(a.get("strategy", "").startswith("device")
                   for a in self.stats.get("aggregates", [])))
        if touched_device:
            import sys

            jax = sys.modules.get("jax")
            if jax is not None:
                try:
                    live = int(sum(int(getattr(a, "nbytes", 0))
                                   for a in jax.live_arrays()))
                    mem["device_live_bytes"] = live
                    metrics.set_gauge("mem.device.live_bytes", live)
                except Exception:  # noqa: BLE001
                    pass
        if mem:
            self.stats["memory"] = mem

    def execute(self, plan: LogicalPlan) -> pa.Table:
        # Per-request deadline (utils/deadline.py): operator ENTRY and
        # EXIT are both phase boundaries.  Entry alone is not enough —
        # the recursion checks every node on the way DOWN (all within
        # microseconds of each other), so a deadline that expires inside
        # a long scan would never abort the aggregation/sort/join work
        # stacked above it.  The exit check fires right after the child
        # work that consumed the budget, before the parent spends more.
        # One contextvar read each when no deadline is set.
        # Timeline (telemetry/timeline.py, conf-gated): each operator
        # dispatch lands as one interval on the "exec" lane, so the
        # Perfetto export shows operator time against the device and
        # build lanes.  Disabled cost: one bool check.
        t0 = timeline.op_begin()
        out = self._execute_node(plan)
        timeline.op_end("exec", type(plan).__name__, t0)
        _deadline.check(type(plan).__name__)
        return out

    def _execute_node(self, plan: LogicalPlan) -> pa.Table:
        _deadline.check(type(plan).__name__)
        if isinstance(plan, InMemory):
            return plan.table
        if isinstance(plan, Scan):
            return self._scan(plan)
        if isinstance(plan, Filter):
            return self._filter(plan)
        if isinstance(plan, Project):
            if isinstance(plan.child, Scan):
                # Scan pushdown: read only the projected columns from disk
                # (the payoff of plan/pruning.py).
                return self._scan(plan.child, columns=plan.columns)
            table = self.execute(plan.child)
            out = table.select(plan.columns)
            # Selection keeps rows (same arrays): identity carries over.
            self._propagate_identity(out, table)
            return out
        if isinstance(plan, Compute):
            table = self.execute(plan.child)
            data = {name: _eval_column(e, table) for name, e in plan.exprs}
            return pa.table(data)
        if isinstance(plan, WithColumns):
            table = self.execute(plan.child)
            for name, e in plan.exprs:
                arr = _eval_column(e, table)
                if name in table.column_names:
                    table = table.set_column(
                        table.column_names.index(name), name, arr)
                else:
                    table = table.append_column(name, arr)
            return table
        if isinstance(plan, Join):
            return self._join(plan)
        if isinstance(plan, Window):
            table = self.execute(plan.child)
            dev = self._try_device_window(table, plan)
            out = dev if dev is not None else _window(table, plan)
            # Appending an analytic column preserves rows and the source
            # arrays: identity carries so a SECOND window (or any
            # downstream op) still routes by residency.
            self._propagate_identity(out, table)
            return out
        if isinstance(plan, Aggregate):
            return self._aggregate(plan)
        if isinstance(plan, Distinct):
            table = self.execute(plan.child)
            names = table.column_names
            if len(set(names)) != len(names):
                raise ValueError(
                    f"distinct() needs unique column names, got {names}; "
                    f"project/rename the duplicates first")
            if table.num_rows == 0:
                return table
            return table.group_by(names).aggregate([]).select(names)
        if isinstance(plan, Sort):
            table = self.execute(plan.child)
            return _sorted_table(table, plan.keys)
        if isinstance(plan, Limit):
            if (isinstance(plan.child, Sort) and plan.n > 0
                    and isinstance(plan.child.child, Aggregate)):
                fused = self._topn_join_aggregate(
                    plan.child.child, plan.child, plan.n)
                if fused is not None:
                    return fused
            if isinstance(plan.child, Sort) and plan.n > 0:
                # Top-N fusion: O(n log k) partial selection instead of a
                # full sort + slice.  "Unstable" only affects tie order,
                # which LIMIT over ORDER BY leaves unspecified anyway.
                # select_k has no null-placement control, so null-bearing
                # keys take the full sort (Spark null order preserved).
                sort = plan.child
                table = self.execute(sort.child)
                if table.num_rows == 0:
                    return table  # select_k rejects zero-row input
                if any(table.column(c).null_count > 0 for c, _ in sort.keys):
                    return _sorted_table(table, sort.keys).slice(0, plan.n)
                idx = pc.select_k_unstable(
                    table, k=min(plan.n, table.num_rows),
                    sort_keys=[(c, "ascending" if asc else "descending")
                               for c, asc in sort.keys])
                return table.take(idx)
            table = self.execute(plan.child)
            return table.slice(0, plan.n)
        if isinstance(plan, SetOp):
            return self._set_op(plan)
        if isinstance(plan, (BucketUnion, Union)):
            tables = [self.execute(c) for c in plan.children]
            # Public Union: "permissive" widens same-named numeric columns
            # of different widths (int32 ∪ int64 -> int64, int ∪ float ->
            # double) like Spark's unionByName.  BucketUnion merges an
            # INDEX with its own source's appended rows — a width mismatch
            # there is index/source schema drift that must stay LOUD (a
            # silent int64 ∪ float64 -> double promotion would corrupt
            # >2^53 keys), so it keeps strict-by-name promotion.
            promote = "permissive" if isinstance(plan, Union) \
                and not plan.strict else "default"
            return pa.concat_tables(tables, promote_options=promote)
        raise ValueError(f"Unknown plan node: {type(plan).__name__}")

    # -- set operations -----------------------------------------------------
    def _set_op(self, plan: SetOp) -> pa.Table:
        """INTERSECT/EXCEPT with SQL null-safe row equality: both sides
        stack into one promoted table, every row gets a dense null-safe
        group code (the window engine's encoder), and membership is one
        vectorized isin — no hashing of Python tuples, no join-predicate
        null semantics leaking in."""
        from hyperspace_tpu.ops.window import partition_codes

        left = self.execute(plan.left)
        right = self.execute(plan.right)
        if len(left.column_names) != len(right.column_names):
            raise ValueError(
                f"{plan.kind.upper()} needs equal column counts: "
                f"{left.column_names} vs {right.column_names}")
        r_renamed = right.rename_columns(left.column_names)
        stacked = pa.concat_tables([left, r_renamed],
                                   promote_options="permissive")
        if stacked.num_rows == 0:
            return stacked
        codes = partition_codes(stacked, stacked.column_names)
        ca = codes[:left.num_rows]
        cb = codes[left.num_rows:]
        in_b = np.isin(ca, cb)
        keep = in_b if plan.kind == "intersect" else ~in_b
        kept_rows = np.flatnonzero(keep)
        if kept_rows.size == 0:
            return stacked.slice(0, 0)
        # Distinct: first occurrence per code, in left-row order.
        _uniq, first = np.unique(ca[kept_rows], return_index=True)
        rows = np.sort(kept_rows[first])
        return stacked.take(pa.array(rows))

    # -- aggregate ----------------------------------------------------------
    def _aggregate(self, plan: Aggregate) -> pa.Table:
        from hyperspace_tpu.telemetry.trace import span

        with span("exec.aggregate", groups=len(plan.group_by)) as sp:
            attempt = self._try_join_aggregate(plan)
            if attempt is not None:
                kind, payload = attempt
                if kind == "done":
                    sp.set(strategy="fused_join_agg", rows=payload.num_rows)
                    return payload
                # Sides were materialized for the attempt; joined on host.
                out = self._aggregate_on_table(plan, payload)
            else:
                out = self._aggregate_on_table(plan, self.execute(plan.child))
            sp.set(rows=out.num_rows)
            return out

    def _aggregate_on_table(self, plan: Aggregate,
                            table: pa.Table) -> pa.Table:
        # Scan provenance survives the hidden-column appends below (the
        # appended table is a new object); only the ORIGINAL columns stay
        # cacheable — computed inputs are query-specific.
        identity = self._scan_identity(table)
        # Expression inputs (sum(price * (1 - discount))) materialize as
        # hidden columns first; the reduction then sees plain columns.
        agg_inputs: List = []
        for i, (func, agg_in, _out) in enumerate(plan.aggs):
            if isinstance(agg_in, Expr) and not isinstance(agg_in, Col):
                name = f"__agg_in_{i}"
                while name in table.column_names:
                    name += "_"
                table = table.append_column(name, _eval_column(agg_in, table))
                agg_inputs.append(name)
            elif isinstance(agg_in, Col):
                agg_inputs.append(agg_in.name)
            else:
                agg_inputs.append(agg_in)
        specs = [([] if func == "count_all" else agg_inputs[i], func)
                 for i, (func, _in, _out) in enumerate(plan.aggs)]
        if plan.group_by:
            device = self._try_device_aggregate(table, plan, agg_inputs,
                                                identity)
            if device is not None:
                return device
            keys = list(plan.group_by)
            out = table.group_by(keys).aggregate(specs)
            # Map output columns POSITIONALLY from arrow's documented
            # layout: the key block is contiguous at the front (pyarrow
            # >= 8) or the back (older), in group_by order; the other
            # positions are the agg results in spec order.  First-name
            # matching would silently swap a key with an auto-named agg
            # column (e.g. key 'v_sum' vs agg output 'v_sum').
            names = out.column_names
            nk = len(keys)
            if names[:nk] == keys:
                key_idx, agg_idx = list(range(nk)), list(range(nk, len(names)))
            elif names[-nk:] == keys:
                key_idx = list(range(len(names) - nk, len(names)))
                agg_idx = list(range(len(names) - nk))
            else:
                raise AssertionError(
                    f"Unrecognized group-by output layout {names} for keys "
                    f"{keys}")
            assert len(agg_idx) == len(plan.aggs)
            data = {k: out.column(i) for k, i in zip(keys, key_idx)}
            for (_f, _c, out_name), i in zip(plan.aggs, agg_idx):
                data[out_name] = out.column(i)
            return pa.table(data)
        # Global aggregation: one row, computed per spec.
        cols, vals = [], []
        for i, (func, _in, out_name) in enumerate(plan.aggs):
            if func == "count_all":
                value = table.num_rows
            elif func == "count":
                column = table.column(agg_inputs[i])
                value = table.num_rows - column.null_count
            else:
                value = getattr(pc, func)(table.column(agg_inputs[i])).as_py()
            cols.append(out_name)
            vals.append(value)
        return pa.table({n: [v] for n, v in zip(cols, vals)})

    def _try_device_aggregate(self, table: pa.Table, plan: Aggregate,
                              agg_inputs: List[str],
                              identity=None) -> Optional[pa.Table]:
        """Route an eligible GROUP BY through the device segment-reduction
        kernel (ops/aggregate.py).  Eligible: enough rows (conf
        device_agg_min_rows, or the resident threshold when the inputs are
        HBM-cached), integer/bool group keys (float keys would split
        arrow's single NaN group by bit pattern), null-free numeric
        inputs, and only sum/min/max/mean/count/count_all.  Output rows
        come back in ascending key order — GROUP BY output order is
        unspecified, as on the host path."""
        from hyperspace_tpu.ops.aggregate import AGG_OPS

        conf = self.session.conf
        if table.num_rows == 0:
            return None
        pairs = [(k, "order") for k in plan.group_by] + [
            (agg_inputs[i], "num")
            for i, (func, _in, _out) in enumerate(plan.aggs)
            if func not in ("count", "count_all")]
        min_rows = self._cache_aware_min_rows(identity, pairs, "agg")
        # An active mesh opens the sharded aggregate at its own
        # threshold, like the filter/join dispatches.
        mesh = self._active_mesh()
        if mesh is not None:
            min_rows = min(min_rows, conf.mesh_agg_min_rows)
        if table.num_rows < min_rows:
            return None
        if any(func not in AGG_OPS for func, _i, _o in plan.aggs):
            return None
        for k in plan.group_by:
            t = table.schema.field(k).type
            # uint64 excluded: the device domain is int64 (a >= 2^63 value
            # would fail the safe cast; smaller ones would flip the output
            # type with row count).  Narrower unsigned types fit int64.
            if not (pa.types.is_integer(t) or pa.types.is_boolean(t)) \
                    or pa.types.is_uint64(t):
                return None
            if table.column(k).null_count > 0:
                return None
        for i, (func, _in, _out) in enumerate(plan.aggs):
            if func == "count_all":
                continue
            if func == "count":
                # count == group row count only when the input has no
                # nulls; any TYPE is fine since no value is reduced.
                if table.column(agg_inputs[i]).null_count > 0:
                    return None
                continue
            t = table.schema.field(agg_inputs[i]).type
            # Strictly int/float (uint64 excluded — device domain is
            # int64): temporal columns would crash min/max at the cast
            # back (and "sum" over dates must raise, as the host path
            # does); bool sums promote to uint64 on host but int64 on
            # device — excluded rather than special-cased.
            if not (pa.types.is_integer(t) or pa.types.is_floating(t)) \
                    or pa.types.is_uint64(t) \
                    or table.column(agg_inputs[i]).null_count > 0:
                return None

        from hyperspace_tpu.ops.aggregate import (
            grouped_aggregate,
            grouped_aggregate_mesh,
        )

        use_mesh = (mesh is not None
                    and table.num_rows >= conf.mesh_agg_min_rows)
        if use_mesh:
            # Sharded path: rows partitioned by group-key bucket
            # ownership — a group is reduced whole on one device, so
            # every op is exact and no merge pass exists.  Host arrays
            # only (sharded placement is its own layout — the
            # single-device resident cache is bypassed).
            key_words = [np.asarray(columnar.to_order_words(
                table.column(k))) for k in plan.group_by]
            value_cols = [
                np.asarray(columnar.to_device_numeric(
                    table.column(agg_inputs[i])))
                for i, (func, _in, _out) in enumerate(plan.aggs)
                if func not in ("count", "count_all")]
            first_rows, counts, results = grouped_aggregate_mesh(
                key_words, value_cols, [f for f, _i, _o in plan.aggs],
                mesh, pad_to=conf.device_batch_rows)
            resident = False
        else:
            resident = self._all_resident(identity, pairs)
            key_words = [self._device_column(table, k, identity, "order")
                         for k in plan.group_by]
            # One array per NON-count aggregate; counts ship nothing (a
            # dummy column would be ~8 B/row of pointless transfer).
            value_cols = [
                self._device_column(table, agg_inputs[i], identity, "num")
                for i, (func, _in, _out) in enumerate(plan.aggs)
                if func not in ("count", "count_all")]
            first_rows, counts, results = grouped_aggregate(
                key_words, value_cols, [f for f, _i, _o in plan.aggs],
                pad_to=conf.device_batch_rows)
        self.stats.setdefault("aggregates", []).append({
            "strategy": "mesh-segment" if use_mesh else "device-segment",
            "groups": int(len(first_rows)),
            "rows": table.num_rows,
            "resident": resident,
        })
        # Gather only the key columns (the full-width table would random-
        # gather every unused value column too).
        taken = table.select(list(plan.group_by)).take(pa.array(first_rows))
        data = {k: taken.column(k) for k in plan.group_by}
        for (func, _in, out_name), res, i in zip(
                plan.aggs, results, range(len(results))):
            if func in ("count", "count_all"):
                data[out_name] = pa.array(counts.astype(np.int64))
            elif func in ("min", "max"):
                # Reductions return existing values: restore the input type
                # (the device ran float64/int64).
                src_type = table.schema.field(agg_inputs[i]).type
                data[out_name] = pc.cast(pa.array(res), src_type)
            elif func == "mean":
                data[out_name] = pa.array(res.astype(np.float64))
            else:  # sum: int stays int64, float stays float64 — arrow's
                # own promotion for sums.
                data[out_name] = pa.array(res)
        return pa.table(data)

    # -- device windows (whole-partition aggregates over resident data) -----
    def _try_device_window(self, table: pa.Table,
                           plan: Window) -> Optional[pa.Table]:
        """Whole-partition window aggregates (``sum(x) OVER (PARTITION
        BY k)``) over HBM-resident columns: the reduction runs on the
        segment kernel (ops/aggregate.py — the round-4 verdict's ask),
        only per-GROUP results return, and the broadcast back to rows is
        one host searchsorted.  Scope: single int/bool partition key,
        null-free numeric value, no ORDER BY/frame (running frames are
        the vectorized host engine's job); routing by the resident
        'agg' threshold, like grouped aggregation."""
        conf = self.session.conf
        if (plan.frame is not None or plan.order_by
                or len(plan.partition_by) != 1
                or plan.func not in ("sum", "min", "max", "mean",
                                     "count")
                or table.num_rows == 0):
            return None
        key = plan.partition_by[0]
        kt = table.schema.field(key).type
        if not (pa.types.is_integer(kt) or pa.types.is_boolean(kt)) \
                or pa.types.is_uint64(kt) \
                or table.column(key).null_count > 0:
            return None
        pairs = [(key, "order")]
        src_type = None
        if plan.func == "count":
            # count over a null-free value equals the group row count:
            # nothing ships beyond the key, and the value column must
            # not enter `pairs` (it is never cached, so it would pin
            # _all_resident to False forever).
            if plan.value is not None \
                    and table.column(plan.value).null_count > 0:
                return None
        else:
            if plan.value is None:
                return None
            src_type = table.schema.field(plan.value).type
            if not (pa.types.is_integer(src_type)
                    or pa.types.is_floating(src_type)) \
                    or pa.types.is_uint64(src_type) \
                    or table.column(plan.value).null_count > 0:
                return None
            pairs.append((plan.value, "num"))
        identity = self._scan_identity(table)
        if table.num_rows < self._cache_aware_min_rows(identity, pairs,
                                                       "agg"):
            return None
        from hyperspace_tpu.ops.aggregate import grouped_aggregate

        resident = self._all_resident(identity, pairs)
        key_words = [self._device_column(table, key, identity, "order")]
        value_cols = [] if plan.func == "count" else [
            self._device_column(table, plan.value, identity, "num")]
        first_rows, counts, results = grouped_aggregate(
            key_words, value_cols, [plan.func if plan.func != "count"
                                    else "count_all"],
            pad_to=conf.device_batch_rows)
        group_keys = table.column(key).take(pa.array(first_rows))
        gk = np.asarray(columnar.to_device_numeric(group_keys))
        rows = np.asarray(
            columnar.to_device_numeric(table.column(key)))
        idx = np.searchsorted(gk, rows)  # groups ascend by key
        res = results[0]
        if plan.func == "count":
            out = pa.array(counts.astype(np.int64)).take(pa.array(idx))
        elif plan.func in ("min", "max"):
            out = pc.cast(pa.array(res), src_type).take(pa.array(idx))
        elif plan.func == "mean":
            out = pa.array(res.astype(np.float64)).take(pa.array(idx))
        else:  # sum: int64 / float64 by the device result dtype
            out = pa.array(res).take(pa.array(idx))
        self.stats.setdefault("windows", []).append({
            "strategy": "device-segment", "rows": table.num_rows,
            "groups": int(len(counts)), "resident": resident})
        if plan.name in table.column_names:
            return table.set_column(
                table.column_names.index(plan.name), plan.name, out)
        return table.append_column(plan.name, out)

    # -- fused join+aggregate (the whole Q3/Q10 hot path on device) ---------
    _JOIN_AGG_OPS = ("sum", "min", "max", "mean", "count", "count_all")

    def _topn_join_aggregate(self, agg: Aggregate, sort: Sort,
                             n: int) -> Optional[pa.Table]:
        """ORDER BY <aggregate output> LIMIT n over a fused join+agg:
        the ranking runs on device too, so only n groups come home —
        the full Q3/Q10 pipeline (filter ⨝ index → group → top-N) with
        O(n) host traffic.  None = not applicable, take the normal
        path."""
        if len(sort.keys) != 1:
            return None
        key, asc = sort.keys[0]
        agg_index = next((i for i, (_f, _in, out) in enumerate(agg.aggs)
                          if out == key), None)
        if agg_index is None:  # ordering by a group column: no device win
            return None
        attempt = self._try_join_aggregate(
            agg, topn=(agg_index, bool(asc), int(n)))
        if attempt is None:
            return None
        kind, payload = attempt
        if kind == "done":
            table = payload  # k rows already — exact re-sort is cheap
        else:
            table = self._aggregate_on_table(agg, payload)
        return _sorted_table(table, sort.keys).slice(0, n)

    def _static_column_type(self, node, name: str):
        """Arrow type of ``name`` in ``node``'s output when derivable
        WITHOUT executing anything (Filter/Project/Sort/Limit chains
        over Scan/InMemory — the shapes join sides actually take);
        None when unknown."""
        while True:
            if isinstance(node, (Filter, Sort, Limit)):
                node = node.child
                continue
            if isinstance(node, Project):
                if name not in node.columns:
                    return None
                node = node.child
                continue
            if isinstance(node, InMemory):
                if name not in node.table.column_names:
                    return None
                return node.table.schema.field(name).type
            if isinstance(node, Scan):
                try:
                    from hyperspace_tpu.io.parquet import schema_to_arrow

                    m = {k.lower(): v for k, v in
                         self.session.schema_map_of(node).items()}
                    t = m.get(name.lower())
                    return schema_to_arrow({"c": t}).field(0).type \
                        if t is not None else None
                except Exception:
                    return None
            return None

    def _plan_row_upper_bound(self, node) -> Optional[int]:
        """Row UPPER BOUND for a join side without executing it: parquet
        footer counts under Filter/Project chains (filters only shrink).
        None when the shape or format doesn't allow a cheap answer."""
        while isinstance(node, (Filter, Project, Sort, Limit)):
            node = node.child
        if isinstance(node, InMemory):
            return node.table.num_rows
        if not isinstance(node, Scan):
            return None
        rel = node.relation
        try:
            import pyarrow.parquet as pq

            if rel.file_paths is not None:
                paths = list(rel.file_paths)
            else:
                paths = [f.name for f in list_data_files(rel.root_paths)]
            return sum(pq.ParquetFile(p).metadata.num_rows
                       for p in paths)
        except Exception:
            return None

    def _join_agg_static_pregate(self, plan: Aggregate,
                                 child: Join) -> bool:
        """False when the fused path is KNOWABLY ineligible before any
        execution — ambiguous/missing columns, or statically resolvable
        types outside the kernel's domain.  An early False preserves
        the normal path (with its bucketed join) at zero cost; unknowns
        stay True and the data-dependent checks decide later."""
        try:
            l_cols = set(child.left.output_columns(self.session.schema_of))
            r_cols = set(child.right.output_columns(self.session.schema_of))
        except Exception:
            return True  # unresolvable statically: decide after exec
        refs = set(plan.group_by)
        for _func, agg_in, _out in plan.aggs:
            if isinstance(agg_in, Col):
                refs.add(agg_in.name)
            elif isinstance(agg_in, str):
                if agg_in:
                    refs.add(agg_in)
            elif isinstance(agg_in, Expr):
                refs |= set(agg_in.referenced_columns())
        for name in refs:
            in_l, in_r = name in l_cols, name in r_cols
            if in_l == in_r:  # missing or ambiguous
                return False
            side = child.left if in_l else child.right
            t = self._static_column_type(side, name)
            if t is None:
                continue  # unknown: the late check decides
            if name in plan.group_by:
                if not (pa.types.is_integer(t) or pa.types.is_boolean(t)
                        or pa.types.is_temporal(t)) \
                        or pa.types.is_uint64(t):
                    return False
            elif not (pa.types.is_integer(t) or pa.types.is_floating(t)) \
                    or pa.types.is_uint64(t):
                return False
        return True

    def _try_join_aggregate(self, plan: Aggregate, topn=None):
        """Route ``aggregate(inner equi-join)`` through the fused device
        pipeline (ops/join_agg.py): join match, gather, expression
        evaluation, and segment reduction all happen in HBM; only
        per-group results return.  The north-star shapes
        (BASELINE.md Q3/Q10) are exactly this pattern — executed
        separately, the full joined row set would cross the attachment.

        Returns None to leave the plan alone (structural mismatch, or
        the device isn't plausibly profitable); ("done", table) with the
        fused result; or ("joined", table) when the sides were
        materialized for the attempt but eligibility failed — the
        caller aggregates the host-joined table without re-executing.
        """
        conf = self.session.conf
        if not plan.group_by:
            return None
        child = plan.child
        if not isinstance(child, Join) or child.how != "inner" \
                or child.residual is not None:
            return None
        # Plausibility gate BEFORE touching anything: the eager populate
        # policy (pay the transfer once, serve repeats from HBM), or a
        # genuinely LOW calibrated cold threshold (locally attached
        # chips, where cold device joins win outright).  Anything else —
        # including the conservative static defaults — leaves the
        # regular path, bucketed host join included, untouched.
        if conf.device_cache_policy != "eager" \
                and conf.device_min_rows("join_agg") > (1 << 22):
            return None
        if any(func not in self._JOIN_AGG_OPS
               for func, _i, _o in plan.aggs):
            return None
        # min/max need a plain column (their result restores its type):
        # statically decidable, so decide it BEFORE materializing sides.
        for func, agg_in, _out in plan.aggs:
            if func in ("min", "max") and not isinstance(agg_in,
                                                         (Col, str)):
                return None
        from hyperspace_tpu.plan.expr import as_equi_join_pairs

        pairs = as_equi_join_pairs(child.condition)
        if pairs is None or len(pairs) != 1:
            return None
        if not self._join_agg_static_pregate(plan, child):
            # Statically ineligible: leave the plan alone so the normal
            # path (bucketed host join included) runs untouched.
            return None
        # Row pre-gate from parquet FOOTERS: when even the upper bound
        # cannot clear the lowest applicable threshold, the device can
        # never win — bail before materializing anything so small joins
        # keep their bucketed host path.
        lo_thresh = min(conf.device_min_rows("join_agg"),
                        conf.resident_min_rows("join_agg"))
        est_l = self._plan_row_upper_bound(child.left)
        est_r = self._plan_row_upper_bound(child.right)
        if est_l is not None and est_r is not None \
                and max(est_l, est_r) < lo_thresh:
            return None

        left = self.execute(child.left)
        right = self.execute(child.right)

        def fallback():
            self.stats["joins"].append(
                {"strategy": "plain", "how": "inner"})
            return ("joined", self._host_join_tables(
                left, right, child.condition, "inner"))

        a, b = pairs[0]
        if a in left.column_names and b in right.column_names:
            lk_name, rk_name = a, b
        elif b in left.column_names and a in right.column_names:
            lk_name, rk_name = b, a
        else:
            return fallback()
        if (lk_name == rk_name or lk_name in right.column_names
                or rk_name in left.column_names):
            # Name present on both sides: the flat column index below
            # couldn't tell them apart.
            return fallback()
        if not (columnar.is_numeric_type(
                    left.schema.field(lk_name).type)
                and columnar.is_numeric_type(
                    right.schema.field(rk_name).type)):
            return fallback()
        # Inner join: null keys never match — drop them up front (with
        # derived identity so residency carries across repeats).
        lv, rv = left, right
        if left.column(lk_name).null_count > 0:
            lv = left.filter(pc.is_valid(left.column(lk_name)))
            self._register_derived_identity(
                lv, self._scan_identity(left), f"dropnull:{lk_name}")
        if right.column(rk_name).null_count > 0:
            rv = right.filter(pc.is_valid(right.column(rk_name)))
            self._register_derived_identity(
                rv, self._scan_identity(right), f"dropnull:{rk_name}")
        if lv.num_rows == 0 or rv.num_rows == 0:
            return fallback()

        def side_of(name: str) -> Optional[str]:
            in_l = name in lv.column_names
            in_r = name in rv.column_names
            if in_l == in_r:  # missing or ambiguous
                return None
            return "l" if in_l else "r"

        def table_of(side: str) -> pa.Table:
            return lv if side == "l" else rv

        # Group keys: int/bool/temporal (int64 device domain), null-free.
        for k in plan.group_by:
            side = side_of(k)
            if side is None:
                return fallback()
            t = table_of(side).schema.field(k).type
            if not (pa.types.is_integer(t) or pa.types.is_boolean(t)
                    or pa.types.is_temporal(t)) or pa.types.is_uint64(t):
                return fallback()
            if table_of(side).column(k).null_count > 0:
                return fallback()
        # Aggregate inputs: strictly int/float null-free references;
        # min/max need a plain column (the result restores its type).
        from hyperspace_tpu.ops.filter import build_value_fn

        agg_ref_names: List[str] = []
        for func, agg_in, _out in plan.aggs:
            if func == "count_all":
                continue
            if func == "count" and isinstance(agg_in, Expr) \
                    and not isinstance(agg_in, Col):
                # count(expr): the kernel counts group rows, which only
                # equals count(non-null expr) when the expression can
                # never produce null from null-free inputs — true for
                # the device arithmetic subset (+ - * neg), NOT for
                # division (x/0 -> null).  Validate through the same
                # compiler; ineligible shapes take the host path.
                try:
                    build_value_fn(agg_in, sorted(agg_in.referenced_columns()))
                except ValueError:
                    return fallback()
            refs = [agg_in.name] if isinstance(agg_in, Col) else (
                [agg_in] if isinstance(agg_in, str)
                else list(agg_in.referenced_columns()))
            if func in ("min", "max") and not (
                    isinstance(agg_in, (Col, str))):
                return fallback()
            for r in refs:
                side = side_of(r)
                if side is None:
                    return fallback()
                t = table_of(side).schema.field(r).type
                if not (pa.types.is_integer(t)
                        or pa.types.is_floating(t)) \
                        or pa.types.is_uint64(t):
                    return fallback()
                if table_of(side).column(r).null_count > 0:
                    return fallback()
                agg_ref_names.append(r)

        # Routing: cold-transfer break-even, or the resident/eager
        # threshold when every referenced column of a side is cached
        # (or will be) for that side's — possibly filter-derived —
        # identity.
        id_l = self._scan_identity(lv)
        id_r = self._scan_identity(rv)
        need_l = sorted({lk_name} | {
            c for c in set(plan.group_by) | set(agg_ref_names)
            if side_of(c) == "l"})
        need_r = sorted({rk_name} | {
            c for c in set(plan.group_by) | set(agg_ref_names)
            if side_of(c) == "r"})
        pl = [(c, "num") for c in need_l]
        pr = [(c, "num") for c in need_r]
        max_rows = max(lv.num_rows, rv.num_rows)
        cold = conf.device_min_rows("join_agg")
        # The sharded pipeline opens at its own threshold (topn fusion
        # and HBM residency keep the single-device kernel — the mesh
        # path re-partitions between stages, which only pays off when
        # the data is big enough to scale with the devices).
        mesh = self._active_mesh()
        use_mesh = (mesh is not None and topn is None
                    and max_rows >= conf.mesh_join_min_rows)
        use_device = max_rows >= cold
        if not use_device:
            eff = max(self._cache_aware_min_rows(id_l, pl, "join_agg"),
                      self._cache_aware_min_rows(id_r, pr, "join_agg"))
            use_device = eff < cold and max_rows >= eff
        if not use_device and not use_mesh:
            return fallback()
        resident = self._all_resident(id_l, pl) \
            and self._all_resident(id_r, pr)
        use_mesh = use_mesh and not resident

        # Device arrays for every referenced column (cache-aware); the
        # mesh path takes HOST arrays instead — sharded placement is its
        # own layout, so the single-device resident cache is bypassed.
        ref_order: List[Tuple[str, str]] = \
            [("l", c) for c in need_l] + [("r", c) for c in need_r]
        col_ix = {c: i for i, (_s, c) in enumerate(ref_order)}
        if use_mesh:
            columns = [np.asarray(columnar.to_device_numeric(
                table_of(s).column(c))) for s, c in ref_order]
        else:
            columns = [self._device_column(
                table_of(s), c, id_l if s == "l" else id_r, "num")
                for s, c in ref_order]
        sides = [s for s, _c in ref_order]
        group_ix = [col_ix[k] for k in plan.group_by]
        value_fns, lits_list, agg_ops = [], [], []
        for func, agg_in, _out in plan.aggs:
            agg_ops.append(func)
            if func in ("count", "count_all"):
                continue
            expr = Col(agg_in) if isinstance(agg_in, str) else agg_in
            try:
                fn, lits = build_value_fn(
                    expr, [c for _s, c in ref_order])
            except ValueError:
                return fallback()
            value_fns.append(fn)
            lits_list.append(lits)

        from hyperspace_tpu.ops.join_agg import (
            join_group_aggregate,
            join_group_aggregate_mesh,
        )

        if use_mesh:
            li_first, ri_first, counts, results = \
                join_group_aggregate_mesh(
                    columns[col_ix[lk_name]], columns[col_ix[rk_name]],
                    columns, sides, group_ix, agg_ops, value_fns,
                    lits_list, mesh, pad_to=conf.device_batch_rows)
        else:
            li_first, ri_first, counts, results = join_group_aggregate(
                columns[col_ix[lk_name]], columns[col_ix[rk_name]],
                columns, sides, group_ix, agg_ops, value_fns, lits_list,
                topn=topn)
        self.stats["joins"].append({
            "strategy": "mesh-fused-agg" if use_mesh
            else "device-fused-agg", "how": "inner",
            "resident": resident})
        self.stats.setdefault("aggregates", []).append({
            "strategy": "mesh-join-agg" if use_mesh
            else "device-join-agg", "groups": int(len(counts)),
            "rows": int(max_rows), "resident": resident,
            "topn": None if topn is None else int(topn[2])})
        data = {}
        for k in plan.group_by:
            if side_of(k) == "l":
                data[k] = lv.column(k).take(pa.array(li_first))
            else:
                data[k] = rv.column(k).take(pa.array(ri_first))
        # `results` is aligned with plan.aggs: the segment kernel emits
        # one output per op (count slots carry the group counts).
        for (func, agg_in, out_name), res in zip(plan.aggs, results):
            if func in ("count", "count_all"):
                data[out_name] = pa.array(counts.astype(np.int64))
                continue
            if func in ("min", "max"):
                name = agg_in.name if isinstance(agg_in, Col) else agg_in
                src_type = table_of(side_of(name)).schema.field(name).type
                data[out_name] = pc.cast(pa.array(res), src_type)
            elif func == "mean":
                data[out_name] = pa.array(res.astype(np.float64))
            else:  # sum: dtype carried by the device result
                data[out_name] = pa.array(res)
        return ("done", pa.table(data))

    # -- scan ---------------------------------------------------------------
    def _scan(self, plan: Scan, columns: Optional[List[str]] = None) -> pa.Table:
        from hyperspace_tpu.telemetry.trace import span

        with span("exec.scan") as sp:
            out = self._scan_inner(plan, columns, sp)
            sp.set(rows=out.num_rows)
            return out

    def _scan_inner(self, plan: Scan, columns, sp) -> pa.Table:
        rel = plan.relation
        if rel.hypothetical:
            # A what-if plan leaked past the advisor (advisor/hypothetical
            # .py): hypothetical index scans have zero data files and MUST
            # never execute — answering from one would silently return an
            # empty table for a query that has rows.
            from hyperspace_tpu.exceptions import HyperspaceError

            raise HyperspaceError(
                f"Plan contains a hypothetical index scan "
                f"({rel.index_scan_of!r}); what-if plans are for analysis "
                f"only and can never execute (docs/17-advisor.md)")
        read_format = physical_read_format(rel.file_format)
        lake_relation = None
        if rel.file_paths is not None:
            paths = list(rel.file_paths)
        elif rel.file_format.lower() in LAKE_DATA_FORMATS:
            # Lake formats resolve files through the provider's snapshot —
            # a directory walk would see removed/overwritten files too.
            lake_relation = self.session.source_provider_manager.get_relation(plan)
            paths = [f.name for f in lake_relation.all_files()]
            read_format = lake_relation.read_format
        else:
            paths = [f.name for f in list_data_files(rel.root_paths)]
        all_paths = paths
        if rel.prune_to_buckets is not None:
            wanted = set(rel.prune_to_buckets)
            paths = [p for p in paths
                     if (b := bucket_id_of_file(p)) is None or b in wanted]
        # Bytes are measured by stat (the files are about to be read, so
        # the inodes are hot); a vanished file surfaces in read_table with
        # a better error than here.
        bytes_read = 0
        for p in paths:
            try:
                bytes_read += os.path.getsize(p)
            except OSError:
                pass
        scan_record = {
            "relation": rel.index_scan_of or ",".join(rel.root_paths),
            "is_index": bool(rel.index_scan_of),
            "files_read": len(paths),
            "files_listed": len(all_paths),
            "bytes_read": bytes_read,
        }
        self.stats["scans"].append(scan_record)
        sp.set(relation=rel.index_scan_of or ",".join(rel.root_paths),
               is_index=bool(rel.index_scan_of), files_read=len(paths),
               files_listed=len(all_paths), bytes_read=bytes_read)
        # The run report carries per-scan IO too: it is what the advisor's
        # workload capture consumes (bytes actually scanned per relation)
        # and what "why was my query slow" reads (telemetry/report.py).
        from hyperspace_tpu.telemetry import report as run_report

        run_report.record("scan", **scan_record)
        if not paths:
            # Bucket pruning removed every file (key hashes to an empty
            # bucket): the result is empty but MUST keep the scan schema so
            # downstream Project/Filter still resolve.
            from hyperspace_tpu.io.parquet import read_schema, schema_to_arrow

            if all_paths:
                schema = schema_to_arrow(read_schema(
                    all_paths[0], read_format, rel.options_dict))
                empty = schema.empty_table()
            elif lake_relation is not None:
                # A lake table whose active file set is empty still has a
                # schema in its metadata — downstream Project/Filter must
                # resolve against it, not against a column-less table.
                empty = schema_to_arrow(lake_relation.schema()).empty_table()
            else:
                empty = pa.table({})
            return empty.select(columns) if columns else empty
        # Source scans materialize hive partition columns from paths; index
        # data reads (index_scan_of) never do — v__=N is not a partition.
        roots = rel.root_paths if rel.index_scan_of is None else None
        out = read_table(paths, read_format, columns, rel.options_dict,
                         partition_roots=roots)
        if columns:
            out = out.select(columns)
        scan_record["rows"] = out.num_rows
        self._register_scan_identity(out, paths)
        return out

    # -- filter -------------------------------------------------------------
    def _filter(self, plan: Filter) -> pa.Table:
        table = self.execute(plan.child)
        if table.num_rows == 0:
            return table
        mask = self._eval_predicate(plan.condition, table)
        out = table.filter(pa.array(mask))
        # The filtered rows are a pure function of (scan files, predicate):
        # give the output a derived identity so repeats of the same query
        # can serve its columns from the HBM cache (the resident join's
        # filtered sides depend on this).
        self._register_derived_identity(
            out, self._scan_identity(table),
            f"filter:{plan.condition!r}")
        return out

    def _eval_predicate(self, expr: Expr, table: pa.Table) -> np.ndarray:
        cols = expr.referenced_columns()
        # Device path requires at least one column and all referenced columns
        # numeric and null-free; everything else (strings, nullables,
        # constant predicates) takes the arrow path, which owns SQL
        # three-valued-logic semantics.
        # Small batches stay on host: the device round trip's fixed latency
        # dwarfs a vectorized arrow pass (conf device_filter_min_rows).
        # With an active mesh the MESH threshold also opens the device
        # path — otherwise raising device_filter_min_rows above
        # mesh_filter_min_rows would make the sharded path unreachable
        # in between.  ``hyperspace.parallel.mesh.enabled=off`` pins
        # every dispatch below to the bit-equal single-device path.
        identity = self._scan_identity(table)
        pairs = [(c, "num") for c in cols]
        min_rows = self._cache_aware_min_rows(identity, pairs, "filter")
        mesh = self._active_mesh()
        if mesh is not None:
            min_rows = min(min_rows, self.session.conf.mesh_filter_min_rows)
        numeric = bool(cols) \
            and table.num_rows >= min_rows \
            and all(
                columnar.is_numeric_type(table.schema.field(c).type)
                and table.column(c).null_count == 0
                for c in cols
            ) and self._device_compatible(expr, table)
        if numeric:
            # The mesh branch bypasses the single-device resident cache
            # (sharded placement is its own layout) — its stats must not
            # claim a zero-transfer resident run.
            use_mesh = (mesh is not None and table.num_rows
                        >= self.session.conf.mesh_filter_min_rows)
            resident = not use_mesh and self._all_resident(identity, pairs)
            mask = self._eval_device(expr, table, identity,
                                     mesh=mesh if use_mesh else None)
            self.stats.setdefault("filters", []).append({
                "strategy": "device-mesh" if use_mesh else "device",
                "rows": table.num_rows, "resident": resident})
            return mask
        self.stats.setdefault("filters", []).append({
            "strategy": "host", "rows": table.num_rows})
        return self._eval_arrow(expr, table)

    def _device_compatible(self, expr: Expr, table: pa.Table) -> bool:
        if isinstance(expr, BinOp):
            sides = (expr.left, expr.right)
            if not all(isinstance(s, (Col, Lit)) for s in sides):
                # Compound operands: every leaf must be a column or a
                # plainly numeric literal under + - * / neg arithmetic (no
                # temporal normalization inside arithmetic; division is
                # host-only for x/0 -> null 3VL; CASE/string nodes are
                # host-only entirely).  Column leaves must be strictly
                # int/float: temporal/bool pass the outer is_numeric_type
                # gate but arithmetic over their int64 normalization is
                # unit-dependent (and the host mirror raises), so routing
                # must not depend on row count.
                return all(_arith_device_ok(s, table) for s in sides)
            cols_in_cmp = [s for s in sides if isinstance(s, Col)]
            if not cols_in_cmp:
                # Lit-vs-Lit: constant predicates are host-only (the arrow
                # path owns their 3VL), and there is no column type to
                # normalize a temporal literal against.
                return False
            col_types = [table.schema.field(c.name).type for c in cols_in_cmp]
            if len(col_types) == 2 and (pa.types.is_boolean(col_types[0])
                                        != pa.types.is_boolean(col_types[1])):
                # bool-vs-numeric column pair: arrow has no mixed kernel,
                # so the host path raises — the device 0/1 view must not
                # silently answer instead.
                return False
            if any(pa.types.is_temporal(t) for t in col_types):
                # Temporal columns compare on device only against a
                # temporal-typed literal (normalized below) or a column of
                # the SAME temporal type (same epoch unit).  A raw numeric
                # literal or a mixed-type column pair must route to host —
                # comparing epoch int64s against plain numbers would give a
                # silently different answer above the row threshold than
                # the host path's loud error below it.
                if len(col_types) == 2 and (
                        not all(pa.types.is_temporal(t) for t in col_types)
                        or col_types[0] != col_types[1]):
                    return False
                if any(isinstance(s, Lit)
                       and isinstance(s.value, (int, float, bool,
                                                np.integer, np.floating,
                                                np.bool_))
                       for s in sides):
                    return False
            for side in sides:
                if not isinstance(side, Lit):
                    continue
                v = side.value
                bool_lit = isinstance(v, (bool, np.bool_))
                if bool_lit != pa.types.is_boolean(col_types[0]) and (
                        bool_lit or isinstance(v, (int, float, np.integer,
                                                   np.floating))):
                    # bool-vs-numeric in either direction: arrow has no
                    # mixed (int64, bool) comparison kernel, so the host
                    # path raises — the device path must not silently
                    # answer instead.
                    return False
                if not isinstance(v, (int, float, bool)):
                    # Temporal/string literals: host path normalizes them.
                    if columnar.literal_to_numeric(v, col_types[0]) is None:
                        return False
            return True
        if isinstance(expr, (And, Or)):
            return (self._device_compatible(expr.left, table)
                    and self._device_compatible(expr.right, table))
        if isinstance(expr, Not):
            return self._device_compatible(expr.child, table)
        if isinstance(expr, IsIn):
            # The child must be strictly-numeric (temporal/bool columns
            # would be compared as raw epoch int64s against the plain
            # numeric value set — the host path raises instead).
            return (_arith_device_ok(expr.child, table)
                    and all(isinstance(v, (int, float, bool))
                            for v in expr.values))
        return False

    def _eval_device(self, expr: Expr, table: pa.Table,
                     identity=None, mesh=None) -> np.ndarray:
        from hyperspace_tpu.ops.filter import compile_predicate

        order = sorted(expr.referenced_columns())
        norm = self._normalize_literals(expr, table)
        fn, literals = compile_predicate(norm, order)
        # Scoped x64 so int64 columns keep full width on device (global x64
        # would leak dtype defaults into the embedding application's JAX).
        if mesh is not None:
            # Large scan + a mesh: shard the columns row-wise over every
            # mesh device (the batch is host-resident; other hosts'
            # devices are not addressable from here); the elementwise
            # program partitions with zero collectives (parallel/filter.py,
            # which scopes x64 itself).  The single-device resident cache
            # is bypassed — sharded placement is its own layout.
            from hyperspace_tpu.parallel.filter import eval_predicate_on_mesh

            device_cols = [columnar.to_device_numeric(table.column(c))
                           for c in order]
            return eval_predicate_on_mesh(fn, device_cols, literals,
                                          mesh=mesh)
        device_cols = [self._device_column(table, c, identity, "num")
                       for c in order]
        t0 = timeline.kernel_begin()
        with _enable_x64():
            mask = fn(device_cols, literals)
        timeline.kernel_end("filter", t0, mask)
        return sync_guard.pull(mask, "filter.mask")

    def _normalize_literals(self, expr: Expr, table: pa.Table) -> Expr:
        """Rewrite temporal/bool literals to their int64 device domain."""
        if isinstance(expr, BinOp):
            left, right = expr.left, expr.right
            if isinstance(left, Col) and isinstance(right, Lit):
                t = table.schema.field(left.name).type
                v = columnar.literal_to_numeric(right.value, t)
                return BinOp(expr.op, left, Lit(v))
            if isinstance(right, Col) and isinstance(left, Lit):
                t = table.schema.field(right.name).type
                v = columnar.literal_to_numeric(left.value, t)
                return BinOp(expr.op, Lit(v), right)
            return expr
        if isinstance(expr, And):
            return And(self._normalize_literals(expr.left, table),
                       self._normalize_literals(expr.right, table))
        if isinstance(expr, Or):
            return Or(self._normalize_literals(expr.left, table),
                      self._normalize_literals(expr.right, table))
        if isinstance(expr, Not):
            return Not(self._normalize_literals(expr.child, table))
        return expr

    def _eval_arrow(self, expr: Expr, table: pa.Table) -> np.ndarray:
        """Host fallback: arrow compute (reference semantics for strings)."""
        result = _arrow_eval(expr, table)
        if isinstance(result, pa.Scalar):
            # Constant predicate: broadcast (null ⇒ no rows, SQL semantics).
            value = result.as_py()
            return np.full(table.num_rows, bool(value) if value is not None else False)
        mask = np.asarray(result.to_numpy(zero_copy_only=False))
        if mask.dtype != np.bool_:
            # Kleene nulls surface as None in an object array: null ⇒ False.
            mask = np.array([bool(v) if v is not None else False for v in mask])
        return mask

    # -- join ---------------------------------------------------------------
    def _join(self, plan: Join, _record: bool = True) -> pa.Table:
        from hyperspace_tpu.telemetry.trace import span

        with span("exec.join", how=plan.how) as sp:
            joins_mark = len(self.stats["joins"])
            bucketed = self._try_bucketed_join(plan)
            if bucketed is not None:
                if len(self.stats["joins"]) > joins_mark:
                    sp.set(strategy=self.stats["joins"][joins_mark]
                           .get("strategy"))
                sp.set(rows=bucketed.num_rows)
                return bucketed
            if _record:
                self.stats["joins"].append({"strategy": "plain",
                                            "how": plan.how})
            sp.set(strategy="plain")
            left = self.execute(plan.left)
            right = self.execute(plan.right)
            out = self._host_join_tables(left, right, plan.condition,
                                         plan.how, residual=plan.residual)
            sp.set(rows=out.num_rows)
            return out

    def _host_join_tables(self, left: pa.Table, right: pa.Table,
                          condition: Expr, how: str,
                          residual: Optional[Expr] = None) -> pa.Table:
        """Join two materialized tables.  Match pairs come from the inner
        equi-join kernels over the VALID-key rows (SQL: null keys never
        match); the join type then shapes the output from those pairs —
        null-extension via arrow's null-index take, existence joins by
        membership over the matched left rows."""
        from hyperspace_tpu.plan.expr import as_equi_join_pairs

        pairs = as_equi_join_pairs(condition)
        if pairs is None:
            raise ValueError(f"Non-equi join condition: {condition!r}")
        # Resolve which side each column belongs to.
        l_keys, r_keys = [], []
        for a, b in pairs:
            if a in left.column_names and b in right.column_names:
                l_keys.append(a)
                r_keys.append(b)
            elif b in left.column_names and a in right.column_names:
                l_keys.append(b)
                r_keys.append(a)
            else:
                raise ValueError(f"Join columns {a!r}/{b!r} not found")
        # Null keys never match, but outer/anti joins still EMIT those rows
        # — so track original positions instead of dropping rows outright.
        l_map = _valid_key_positions(left, l_keys)
        r_map = _valid_key_positions(right, r_keys)
        lv = left if len(l_map) == left.num_rows else left.take(pa.array(l_map))
        rv = right if len(r_map) == right.num_rows else right.take(pa.array(r_map))
        # Null-key drops are a pure function of (files, key columns):
        # identity derives through so the resident join still addresses
        # the cache when an identified side has nullable keys.
        if lv is not left:
            self._register_derived_identity(
                lv, self._scan_identity(left), f"dropnull:{l_keys}")
        if rv is not right:
            self._register_derived_identity(
                rv, self._scan_identity(right), f"dropnull:{r_keys}")
        li, ri = self._inner_match_pairs(lv, rv, l_keys, r_keys)
        li = l_map[li] if len(l_map) != left.num_rows else li
        ri = r_map[ri] if len(r_map) != right.num_rows else ri
        if residual is not None and len(li):
            # Inequality correlations etc.: the residual predicate
            # filters the MATCHED pairs before the join type shapes the
            # output (NULL => no match, like any join predicate) — so an
            # anti join keeps exactly the left rows with no SURVIVING
            # match, the literal NOT EXISTS semantics.
            combined = _concat_horizontal(left.take(pa.array(li)),
                                          right.take(pa.array(ri)))
            mask = self._eval_arrow(residual, combined)
            li, ri = li[mask], ri[mask]

        if how == "inner":
            return _concat_horizontal(left.take(pa.array(li)),
                                      right.take(pa.array(ri)))
        if how == "semi":
            return left.take(pa.array(np.unique(li)))
        if how == "anti":
            mask = np.ones(left.num_rows, dtype=bool)
            mask[li] = False
            return left.filter(pa.array(mask))
        # Outer joins: matched pairs first, then each side's unmatched rows
        # null-extended (take with a null index yields a null row).
        l_parts = [li]
        r_parts = [ri]
        l_masks = [np.zeros(len(li), dtype=bool)]
        r_masks = [np.zeros(len(ri), dtype=bool)]
        if how in ("left", "full"):
            unmatched = np.setdiff1d(np.arange(left.num_rows), li)
            l_parts.append(unmatched)
            r_parts.append(np.zeros(len(unmatched), dtype=ri.dtype))
            l_masks.append(np.zeros(len(unmatched), dtype=bool))
            r_masks.append(np.ones(len(unmatched), dtype=bool))
        if how in ("right", "full"):
            unmatched = np.setdiff1d(np.arange(right.num_rows), ri)
            l_parts.append(np.zeros(len(unmatched), dtype=li.dtype))
            r_parts.append(unmatched)
            l_masks.append(np.ones(len(unmatched), dtype=bool))
            r_masks.append(np.zeros(len(unmatched), dtype=bool))
        l_idx = pa.array(np.concatenate(l_parts), mask=np.concatenate(l_masks))
        r_idx = pa.array(np.concatenate(r_parts), mask=np.concatenate(r_masks))
        return _concat_horizontal(left.take(l_idx), right.take(r_idx))

    def _inner_match_pairs(self, left: pa.Table, right: pa.Table,
                           l_keys: List[str], r_keys: List[str]):
        """(left_indices, right_indices) of the INNER matches between two
        null-free-key tables, as int64 numpy arrays."""
        single_numeric = (
            len(l_keys) == 1
            and columnar.is_numeric_type(left.schema.field(l_keys[0]).type)
            and columnar.is_numeric_type(right.schema.field(r_keys[0]).type))
        if single_numeric:
            from hyperspace_tpu.ops.join import (
                sorted_equi_join,
                sorted_equi_join_mesh,
                sorted_equi_join_np,
            )

            # Routing: the cold-transfer break-even normally; when BOTH
            # sides' key columns are HBM-resident for their (possibly
            # filter-derived) scan identities, only round-trip latency and
            # the match-index pull remain, so the much smaller resident
            # threshold applies (the contract the covering-index design
            # states: join kernels over HBM-resident batches,
            # JoinIndexRule.scala:36-50).
            max_rows = max(left.num_rows, right.num_rows)
            cold = self.session.conf.device_min_rows("join")
            id_l = self._scan_identity(left)
            id_r = self._scan_identity(right)
            pl = [(l_keys[0], "num")]
            pr = [(r_keys[0], "num")]
            use_device = max_rows >= cold
            if not use_device:
                eff = max(self._cache_aware_min_rows(id_l, pl, "join"),
                          self._cache_aware_min_rows(id_r, pr, "join"))
                use_device = eff < cold and max_rows >= eff
            resident = use_device and self._all_resident(id_l, pl) \
                and self._all_resident(id_r, pr)
            # An active mesh shards the key space over the devices at
            # its own threshold (host inputs only: resident arrays keep
            # the single-device kernel, whose HBM placement is its own
            # layout).  Same match set either way — the mesh changes
            # where the searchsorted runs, not what it finds.
            mesh = self._active_mesh()
            use_mesh = (mesh is not None and not resident
                        and max_rows
                        >= self.session.conf.mesh_join_min_rows)
            if use_mesh:
                lk = columnar.to_device_numeric(left.column(l_keys[0]))
                rk = columnar.to_device_numeric(right.column(r_keys[0]))
                li, ri = sorted_equi_join_mesh(lk, rk, mesh)
            elif use_device:
                lk = self._device_column(left, l_keys[0], id_l, "num")
                rk = self._device_column(right, r_keys[0], id_r, "num")
                li, ri = sorted_equi_join(lk, rk)
            else:
                lk = columnar.to_device_numeric(left.column(l_keys[0]))
                rk = columnar.to_device_numeric(right.column(r_keys[0]))
                li, ri = sorted_equi_join_np(lk, rk)
            self.stats.setdefault("join_kernels", []).append({
                "strategy": "mesh" if use_mesh
                else ("device" if use_device else "host"),
                "rows": int(max_rows), "resident": resident})
            return np.asarray(li, dtype=np.int64), np.asarray(ri, dtype=np.int64)
        # Composite/string keys: digest join on device (or its host
        # mirror below the size threshold) with exact verification —
        # pandas only for key pairs with no exact common domain.
        from hyperspace_tpu.ops.join import (
            UnsupportedJoinKeys,
            hashed_equi_join,
        )

        try:
            use_device = (max(left.num_rows, right.num_rows)
                          >= self.session.conf.device_min_rows("join"))
            li, ri = hashed_equi_join(left, right, l_keys, r_keys,
                                      device=use_device)
            return np.asarray(li, dtype=np.int64), np.asarray(ri, dtype=np.int64)
        except UnsupportedJoinKeys:
            import pandas as pd  # noqa: F401

            ldf = left.to_pandas()
            rdf = right.to_pandas()
            ldf["__li"] = np.arange(len(ldf))
            rdf["__ri"] = np.arange(len(rdf))
            merged = ldf.merge(rdf, left_on=l_keys, right_on=r_keys,
                               how="inner", suffixes=("", "__r"))
            return (merged["__li"].to_numpy(dtype=np.int64),
                    merged["__ri"].to_numpy(dtype=np.int64))

    # -- bucket-aligned join (the shuffle-free SMJ payoff on one chip) ------
    # Structural applicability lives in ``bucketed_join_precheck`` (module
    # level) so the explain physical analyzer predicts the same strategy
    # the executor takes, from one set of checks.
    def _try_bucketed_join(self, plan: Join) -> Optional[pa.Table]:
        """When both sides are (Project|Filter)* chains over index scans
        with MATCHING bucket specs on the join keys (what JoinIndexRule
        constructs), execute and join bucket by bucket: equal keys can only
        meet inside one bucket, so each per-bucket merge works on 1/B of the
        data — the single-chip analog of Spark's exchange-free SMJ over
        matching bucketSpecs (JoinIndexRule.scala:36-50).

        A side may also be a hybrid-scan ``BucketUnion(index, appended)``:
        the appended rows are routed through the build hash kernel into the
        index's bucket space and joined per bucket alongside the index
        files — the executed form of the reference's on-the-fly shuffle
        (RuleUtils.scala:511-570), keeping the index side exchange-free
        instead of degrading to a full-table merge."""
        if plan.residual is not None:
            # Residual joins (subquery inequality correlations) take the
            # plain path: they're semi/anti existence shapes, not the
            # bucketed-index fan-out this optimizes.
            return None
        precheck = bucketed_join_precheck(self.session, plan)
        if precheck is None:
            return None
        left_side, right_side, l_files, r_files = precheck
        scans_mark = len(self.stats["scans"])
        l_parts = self._side_bucket_parts(left_side, l_files)
        r_parts = None if l_parts is None \
            else self._side_bucket_parts(right_side, r_files)
        shared = [] if l_parts is None or r_parts is None \
            else sorted(set(l_parts) & set(r_parts))
        if not shared:
            # Decomposition failed (or zero overlapping buckets — the plain
            # path produces the correct result, including outer
            # null-extension, with the full joined schema): roll back
            # anything recorded while probing.
            del self.stats["scans"][scans_mark:]
            return None
        # One-sided buckets: for inner (and semi on the right / anti on the
        # right) they contribute nothing, but an outer/anti join must still
        # emit the unmatched rows of its preserved side.  Join those buckets
        # against a ZERO-ROW donor of the other side (schema from a shared
        # bucket) — the per-bucket join then null-extends/passes them
        # exactly like the plain path would.
        extra_left = sorted(set(l_parts) - set(r_parts)) \
            if plan.how in ("left", "full", "anti") else []
        extra_right = sorted(set(r_parts) - set(l_parts)) \
            if plan.how in ("right", "full") else []
        hybrid = bool(left_side.appended or right_side.appended)
        mesh_result = self._try_mesh_bucketed_join(
            plan, left_side, right_side, l_parts, r_parts, shared,
            extra_left, extra_right, hybrid, l_files, r_files)
        if mesh_result is not None:
            return mesh_result
        self.stats["joins"].append({
            "strategy": "bucketed",
            "how": plan.how,
            "buckets": len(shared) + len(extra_left) + len(extra_right),
            "hybrid": hybrid,
        })
        # Zero-row schema donors for one-sided buckets: executed ONCE —
        # the donor bucket's table is reused for its own join too, so its
        # files are not decoded (nor its scans recorded) twice.
        pre: Dict[int, Tuple[pa.Table, pa.Table]] = {}
        l_donor = r_donor = None
        if extra_left or extra_right:
            donor = shared[0]
            lt0 = self.execute(l_parts[donor]())
            rt0 = self.execute(r_parts[donor]())
            pre[donor] = (lt0, rt0)
            l_donor, r_donor = lt0.slice(0, 0), rt0.slice(0, 0)

        def join_bucket(bucket: int) -> pa.Table:
            if bucket in extra_left:
                sub = Join(l_parts[bucket](), InMemory(r_donor),
                           plan.condition, plan.how)
            elif bucket in extra_right:
                sub = Join(InMemory(l_donor), r_parts[bucket](),
                           plan.condition, plan.how)
            elif bucket in pre:
                lt, rt = pre[bucket]
                sub = Join(InMemory(lt), InMemory(rt),
                           plan.condition, plan.how)
            else:
                sub = Join(l_parts[bucket](), r_parts[bucket](),
                           plan.condition, plan.how)
            # Per-bucket plans carry no bucket_spec, so this recursion takes
            # the plain per-bucket join path — no re-entry.
            return self._join(sub, _record=False)

        from hyperspace_tpu.utils.parallel_map import parallel_map_ordered

        # Buckets are independent; parquet decode + numpy merge release the
        # GIL.  Each in-flight bucket holds both sides + output (~2/B of
        # the joined data), so 8 concurrent buckets stay memory-modest
        # while keeping every core decoding (nested per-file reads run
        # inline in the shared pool, so this cap IS the read concurrency).
        parts = parallel_map_ordered(join_bucket,
                                     sorted(shared + extra_left + extra_right),
                                     max_workers=8)
        return pa.concat_tables(parts, promote_options="default")

    # -- mesh dispatch of the bucket-aligned join ---------------------------
    def _try_mesh_bucketed_join(self, plan: Join, left_side, right_side,
                                l_parts, r_parts, shared,
                                extra_left, extra_right,
                                hybrid: bool, l_files, r_files):
        """Run the per-bucket joins over the device mesh instead of the
        host thread pool: buckets are range-partitioned over the shard
        axis and ``copartitioned_join_ragged`` joins every device's
        buckets with ZERO collectives (equal keys share a bucket, and a
        bucket lives on exactly one device) — the executed form of the
        reference's distributed exchange-free SMJ
        (BucketUnionExec.scala:52-81 + Spark SMJ over executors).

        Applies to INNER joins with a single numeric key when the engine
        mesh is active (``hyperspace.parallel.mesh.enabled``; off or
        1 device keeps the bit-equal host pool) and the data is large
        enough to amortize the transfer (conf mesh_join_min_rows —
        estimated from parquet FOOTERS before anything is materialized,
        so a below-threshold join never loses the host pool's
        8-concurrent-bucket memory bound); everything else keeps the
        host pool.  The mesh path itself holds all buckets resident by
        construction — that is what the threshold gates."""
        if plan.how != "inner" or extra_left or extra_right:
            return None
        mesh = self._active_mesh()
        if mesh is None:
            return None
        devices = list(mesh.devices.flat)
        from hyperspace_tpu.plan.expr import as_equi_join_pairs

        pairs = as_equi_join_pairs(plan.condition)
        if pairs is None or len(pairs) != 1:
            return None
        # Key columns are the (single) bucket columns — precheck guaranteed
        # the pairs map them — so eligibility is decidable from STORED
        # schemas before executing anything.
        lk_name = left_side.scan.relation.bucket_spec[1][0]
        rk_name = right_side.scan.relation.bucket_spec[1][0]
        try:
            from hyperspace_tpu.io.parquet import schema_to_arrow

            l_map = {k.lower(): v for k, v in
                     self.session.schema_map_of(left_side.scan).items()}
            r_map = {k.lower(): v for k, v in
                     self.session.schema_map_of(right_side.scan).items()}
            l_type = l_map[lk_name.lower()]
            r_type = r_map[rk_name.lower()]
            if not (columnar.is_numeric_type(
                        schema_to_arrow({"c": l_type}).field(0).type)
                    and columnar.is_numeric_type(
                        schema_to_arrow({"c": r_type}).field(0).type)):
                return None
        except Exception:
            return None
        # Row estimate from footers only (no decode): filters above the
        # scans can shrink actual rows, so this is an upper bound — the
        # threshold is a routing heuristic, not a correctness gate.
        est = _footer_row_estimate(l_files, shared) \
            + _footer_row_estimate(r_files, shared)
        if est < self.session.conf.mesh_join_min_rows:
            return None

        from hyperspace_tpu.utils.parallel_map import parallel_map_ordered

        l_tabs = parallel_map_ordered(
            lambda b: self.execute(l_parts[b]()), shared, max_workers=8)
        r_tabs = parallel_map_ordered(
            lambda b: self.execute(r_parts[b]()), shared, max_workers=8)
        # Resolve the executed tables' key column spellings (projections
        # preserve source case; the spec columns are case-insensitive).
        lk_name = _find_column(l_tabs[0], lk_name)
        rk_name = _find_column(r_tabs[0], rk_name)
        if lk_name is None or rk_name is None:
            # Shouldn't happen (the join condition references them), but a
            # wrong guess must degrade to an error-free fallback: run the
            # buckets through the host join path on the materialized pairs.
            return pa.concat_tables(
                [self._join(Join(InMemory(lt), InMemory(rt),
                                 plan.condition, plan.how), _record=True)
                 for lt, rt in zip(l_tabs, r_tabs)],
                promote_options="default")
        # Null keys never match an inner join: drop per bucket up front.

        def drop_nulls(tabs, key):
            out = []
            for t in tabs:
                if t.column(key).null_count > 0:
                    t = t.filter(pc.is_valid(t.column(key)))
                out.append(t)
            return out

        l_tabs = drop_nulls(l_tabs, lk_name)
        r_tabs = drop_nulls(r_tabs, rk_name)
        # MOD bucket ownership over the shard axis (device d owns bucket
        # b iff b % D == d — the same ownership the sharded build route
        # writes with, so index shards and query shards stay aligned);
        # one concatenated table + key shard per device.
        from hyperspace_tpu.parallel.join import copartitioned_join_ragged
        from hyperspace_tpu.telemetry import metrics

        D = len(devices)
        groups = [[i for i, b in enumerate(shared) if b % D == d]
                  for d in range(D)]
        l_dev_tabs, r_dev_tabs, l_shards, r_shards = [], [], [], []
        for g in groups:
            lt = pa.concat_tables([l_tabs[i] for i in g]) if len(g) \
                else l_tabs[0].slice(0, 0)
            rt = pa.concat_tables([r_tabs[i] for i in g]) if len(g) \
                else r_tabs[0].slice(0, 0)
            l_dev_tabs.append(lt)
            r_dev_tabs.append(rt)
            l_shards.append(np.asarray(
                columnar.to_device_numeric(lt.column(lk_name))))
            r_shards.append(np.asarray(
                columnar.to_device_numeric(rt.column(rk_name))))
        dev_ids, l_local, r_local = copartitioned_join_ragged(
            l_shards, r_shards, mesh)
        metrics.set_gauge("exec.mesh.devices", D)
        self.stats["joins"].append({
            "strategy": "bucketed-mesh",
            "how": plan.how,
            "buckets": len(shared),
            "devices": D,
            "hybrid": hybrid,
        })
        parts = []
        for d in range(D):
            sel = dev_ids == d
            if not sel.any():
                continue
            parts.append(_concat_horizontal(
                l_dev_tabs[d].take(pa.array(l_local[sel])),
                r_dev_tabs[d].take(pa.array(r_local[sel]))))
        if not parts:
            empty_l = l_dev_tabs[0].slice(0, 0)
            empty_r = r_dev_tabs[0].slice(0, 0)
            return _concat_horizontal(empty_l, empty_r)
        return pa.concat_tables(parts, promote_options="default")

    def _side_bucket_parts(self, side: "_BucketedSide", by_bucket):
        """bucket id -> zero-arg builder of that bucket's sub-plan for one
        join side, or None when the side can't be decomposed.  Index files
        group by the bucket id in their name (``by_bucket``, precomputed by
        the caller); appended rows (hybrid scan) are routed with the build
        hash kernel."""
        appended_by_bucket: Dict[int, pa.Table] = {}
        if side.appended is not None:
            table = self.execute(side.appended)
            num_buckets, cols, _sort = side.scan.relation.bucket_spec
            routed = self._route_to_buckets(table, cols, num_buckets, side.scan)
            if routed is None:
                return None
            appended_by_bucket = routed

        def make(bucket: int) -> LogicalPlan:
            parts: List[LogicalPlan] = []
            if bucket in by_bucket:
                parts.append(_rewrap(side.scan, side.inner, by_bucket[bucket]))
            if bucket in appended_by_bucket:
                parts.append(InMemory(appended_by_bucket[bucket]))
            # strict: index ∪ its own appended rows (see Union docstring).
            node = parts[0] if len(parts) == 1 else Union(parts, strict=True)
            for w in reversed(side.outer):
                node = w.with_children((node,))
            return node

        return {b: (lambda b=b: make(b))
                for b in set(by_bucket) | set(appended_by_bucket)}

    def _route_to_buckets(self, table: pa.Table, cols, num_buckets: int,
                          index_scan: Scan) -> Optional[Dict[int, pa.Table]]:
        """Partition ``table`` by the index's bucket assignment.  Uses the
        host mirror of the build kernel (bit-identical, parity-tested):
        hybrid-scan thresholds cap appended bytes at a fraction of the
        index, so these batches are small and a device round trip would be
        pure latency.  Key columns are cast to the index's STORED type
        first — the kernel hashes raw bits, so an int64 row hashed as
        float64 would land in the wrong bucket."""
        from hyperspace_tpu.io.columnar import to_hash_words
        from hyperspace_tpu.io.parquet import schema_to_arrow
        from hyperspace_tpu.ops.hash import bucket_ids_np

        if table.num_rows == 0:
            return {}
        by_lower = {c.lower(): c for c in table.column_names}
        stored = {k.lower(): v
                  for k, v in self.session.schema_map_of(index_scan).items()}
        word_cols = []
        for c in cols:
            name = by_lower.get(c.lower())
            if name is None:
                return None
            col = table.column(name)
            stored_type = stored.get(c.lower())
            if stored_type is not None and str(col.type) != stored_type:
                target = schema_to_arrow({"c": stored_type}).field(0).type
                try:
                    col = pc.cast(col, target)
                except (pa.ArrowInvalid, pa.ArrowNotImplementedError,
                        pa.ArrowTypeError):
                    return None
            word_cols.append(np.asarray(to_hash_words(col)))
        bucket_ids = bucket_ids_np(word_cols, num_buckets)
        return {int(b): table.filter(pa.array(bucket_ids == b))
                for b in np.unique(bucket_ids)}


def _footer_row_estimate(files_by_bucket, buckets) -> int:
    """Sum of parquet footer row counts for the given buckets' files —
    O(footer) per file, no column decode.  Non-parquet/unreadable files
    contribute 0 (the estimate is a routing heuristic only)."""
    import pyarrow.parquet as pq

    total = 0
    for b in buckets:
        for path in files_by_bucket.get(b, ()):
            try:
                total += pq.read_metadata(path).num_rows
            except Exception:
                pass
    return total


def _find_column(table: pa.Table, name: str) -> Optional[str]:
    """Case-insensitive column lookup, exact spelling preferred."""
    if name in table.column_names:
        return name
    lower = name.lower()
    for c in table.column_names:
        if c.lower() == lower:
            return c
    return None


def _valid_key_positions(table: pa.Table, keys: List[str]) -> np.ndarray:
    """Row positions whose join keys are ALL non-null (the rows that can
    participate in matching)."""
    valid = np.ones(table.num_rows, dtype=bool)
    for k in keys:
        col = table.column(k)
        if col.null_count > 0:
            valid &= np.asarray(
                pc.is_valid(col).to_numpy(zero_copy_only=False))
    return np.nonzero(valid)[0] if not valid.all() \
        else np.arange(table.num_rows)


def _arith_device_ok(e: Expr, table: pa.Table) -> bool:
    """Device-evaluable value expression: strictly-numeric columns, numeric
    literals, and + - * arithmetic over them (division is host-only: x/0
    must null; temporal/bool columns are host-only: their arithmetic over
    raw int64 normalization would be unit-dependent)."""
    if isinstance(e, Col):
        try:
            t = table.schema.field(e.name).type
        except KeyError:
            return False
        return pa.types.is_integer(t) or pa.types.is_floating(t)
    if isinstance(e, Lit):
        # bool is excluded despite being an int subclass: the host mirror
        # has no (int64, bool) arithmetic kernel, so admitting it would
        # key the outcome on row count.
        return (isinstance(e.value, (int, float))
                and not isinstance(e.value, bool))
    if isinstance(e, Arith):
        return (e.op != "/" and _arith_device_ok(e.left, table)
                and _arith_device_ok(e.right, table))
    if isinstance(e, Neg):
        return _arith_device_ok(e.child, table)
    return False


class _BucketedSide:
    """One join side decomposed for bucket-aligned execution: the bucketed
    index ``scan``, ``inner`` wrappers between the hybrid BucketUnion and
    the scan (empty when there is no union), ``outer`` wrappers above, and
    the ``appended`` subtree (None when the side is a pure index chain)."""

    def __init__(self, scan: Scan, inner, outer, appended) -> None:
        self.scan = scan
        self.inner = inner
        self.outer = outer
        self.appended = appended


def _is_bucketed_index_scan(node: LogicalPlan) -> bool:
    return (isinstance(node, Scan) and bool(node.relation.bucket_spec)
            and node.relation.file_paths is not None
            and bool(node.relation.index_scan_of))


def _unwrap_chain(node: LogicalPlan):
    wrappers: List[LogicalPlan] = []
    while isinstance(node, (Project, Filter)):
        wrappers.append(node)
        node = node.children[0]
    return wrappers, node


def _bucketed_side(node: LogicalPlan) -> Optional[_BucketedSide]:
    """Match ``(Project|Filter)*`` over either a bucketed index scan or a
    hybrid-scan ``BucketUnion(index chain, appended subtree)``."""
    outer, node = _unwrap_chain(node)
    if _is_bucketed_index_scan(node):
        return _BucketedSide(node, [], outer, None)
    if isinstance(node, BucketUnion) and len(node.children) == 2:
        # The rule constructs [index_side, appended_side]; identify the
        # index chain structurally rather than by position.
        for index_child, appended_child in (node.children,
                                            node.children[::-1]):
            inner, leaf = _unwrap_chain(index_child)
            if _is_bucketed_index_scan(leaf):
                return _BucketedSide(leaf, inner, outer, appended_child)
    return None


def bucketed_join_precheck(session, plan: Join):
    """Structural applicability of the bucket-aligned join — side-effect
    free, shared by the executor and the explain physical analyzer so the
    predicted strategy can never diverge from the executed one.  Returns
    (left_side, right_side, left_files_by_bucket, right_files_by_bucket)
    or None when the plain join path applies.

    Multi-column keys qualify when the join pairs map the two sides'
    bucket columns POSITION BY POSITION (the reference's compatible-order
    requirement, JoinIndexRule.scala:483-530) — same hash inputs in the
    same order means equal key tuples share a bucket id."""
    from hyperspace_tpu.plan.expr import as_equi_join_pairs

    pairs = as_equi_join_pairs(plan.condition)
    if not pairs:
        return None
    aligned = [_bucketed_side(side) for side in (plan.left, plan.right)]
    if any(a is None for a in aligned):
        return None
    left_side, right_side = aligned
    l_scan, r_scan = left_side.scan, right_side.scan
    l_spec, r_spec = l_scan.relation.bucket_spec, r_scan.relation.bucket_spec
    if l_spec[0] != r_spec[0]:
        return None
    l_cols = tuple(c.lower() for c in l_spec[1])
    r_cols = tuple(c.lower() for c in r_spec[1])
    if len(pairs) != len(l_cols) or len(l_cols) != len(r_cols):
        return None
    # Orient each pair to (left-side column, right-side column); a pair
    # whose columns don't belong to the two bucket specs disqualifies.
    l_to_r = {}
    for a, b in pairs:
        la, rb = a.lower(), b.lower()
        fwd = la in l_cols and rb in r_cols
        rev = rb in l_cols and la in r_cols
        if fwd and rev and la != rb:
            # Ambiguous orientation (both names exist on both specs): the
            # per-bucket sub-join resolves sides by TABLE columns and could
            # pick the other pairing — partitioning on one orientation and
            # joining on the other silently drops matches.  Plain path.
            return None
        if fwd:
            l_to_r[la] = rb
        elif rev:
            l_to_r[rb] = la
        else:
            return None
    if [l_to_r.get(c) for c in l_cols] != list(r_cols):
        return None
    # Bucket ids only align when both sides hashed the SAME bit patterns:
    # an int64 key on one side and float64 on the other put equal VALUES in
    # different buckets (to_hash_words hashes raw bits), while the plain
    # join path matches them by value — so a type mismatch must fall back,
    # or results silently change.
    for lc, rc in zip(l_spec[1], r_spec[1]):
        l_type = session.schema_map_of(l_scan).get(lc)
        r_type = session.schema_map_of(r_scan).get(rc)
        if l_type is None or r_type is None or l_type != r_type:
            return None
    # Cheap structural checks for BOTH sides before the executor runs any
    # appended subtree (a late failure would re-execute it on the plain
    # path).
    l_files = _files_by_bucket(left_side.scan)
    r_files = _files_by_bucket(right_side.scan)
    if l_files is None or r_files is None:
        return None
    return left_side, right_side, l_files, r_files


def _files_by_bucket(scan: Scan):
    """Bucket id -> files, honoring the scan's own bucket pruning (a
    filter under the join may have restricted the buckets already)."""
    allowed = None if scan.relation.prune_to_buckets is None \
        else set(scan.relation.prune_to_buckets)
    out: Dict[int, List[str]] = {}
    for p in scan.relation.file_paths:
        b = bucket_id_of_file(p)
        if b is None:
            return None
        if allowed is not None and b not in allowed:
            continue
        out.setdefault(b, []).append(p)
    return out


def _rewrap(scan: Scan, wrappers, files) -> LogicalPlan:
    import dataclasses as dc

    rel = dc.replace(scan.relation, file_paths=tuple(files),
                     bucket_spec=None, prune_to_buckets=None)
    node: LogicalPlan = Scan(rel)
    for w in reversed(wrappers):
        node = w.with_children((node,))
    return node


def _sorted_table(table: pa.Table, keys) -> pa.Table:
    """ORDER BY with Spark's null order: nulls sort as SMALLEST — first
    ascending, last descending (the reference's executor for ORDER BY is
    Spark SQL).  Arrow's null_placement is positional (one setting for all
    keys regardless of direction), so each null-bearing key gets a validity
    flag key in front: false < true puts nulls first under the key's own
    direction when ascending and last when descending, and within each flag
    group the real key orders rows."""
    if table.num_rows == 0:
        return table
    return table.take(_sort_indices(table, keys))


def _sort_indices(table: pa.Table, keys) -> pa.Array:
    """Sort permutation with Spark's null order (nulls first ascending,
    last descending) — the validity-flag technique of _sorted_table."""
    work = table
    sort_keys = []
    for c, asc in keys:
        direction = "ascending" if asc else "descending"
        if table.column(c).null_count > 0:
            flag = f"__valid__{c}"
            n = 1
            while flag in work.column_names:
                flag = f"__valid__{c}__{n}"
                n += 1
            work = work.append_column(flag, pc.is_valid(table.column(c)))
            sort_keys.append((flag, direction))
        sort_keys.append((c, direction))
    return pc.sort_indices(work, sort_keys=sort_keys)


def _window_empty_type(table: pa.Table, plan: Window):
    """Output type for a zero-row input — must match the rowful path so
    the schema doesn't depend on whether the input had rows."""
    out_type = {"row_number": pa.int32(), "rank": pa.int32(),
                "dense_rank": pa.int32(), "ntile": pa.int32(),
                "count": pa.int64(),
                "mean": pa.float64()}.get(plan.func)
    if out_type is None and plan.func in ("lag", "lead", "first_value",
                                          "last_value"):
        out_type = table.schema.field(plan.value).type
    if out_type is None and plan.func == "sum":
        src = table.schema.field(plan.value).type
        out_type = pa.int64() \
            if pa.types.is_integer(src) or pa.types.is_boolean(src) \
            else pa.float64()
    if out_type is None:  # min/max follow the input column
        out_type = table.schema.field(plan.value).type \
            if plan.value else pa.int64()
    return out_type


def _np_window_values(v_sorted: pa.Array):
    """(values, valid) numpy views of a sorted Arrow column for the
    frame kernels: temporals/bools lower to their integer repr so int
    arithmetic stays exact; nulls are filled with 0 and tracked in
    ``valid``.  Non-numeric types return (None, valid) — the caller
    decides whether that's an error or an Arrow-side path."""
    t = v_sorted.type
    valid = np.asarray(pc.is_valid(v_sorted)
                       .to_numpy(zero_copy_only=False))
    num = None
    if pa.types.is_boolean(t):
        num = v_sorted.cast(pa.int8())
    elif pa.types.is_date32(t) or pa.types.is_time32(t):
        num = v_sorted.cast(pa.int32())
    elif (pa.types.is_date64(t) or pa.types.is_time64(t)
            or pa.types.is_timestamp(t) or pa.types.is_duration(t)):
        num = v_sorted.cast(pa.int64())
    elif pa.types.is_integer(t) or pa.types.is_floating(t):
        num = v_sorted
    # Decimals deliberately return None: a float64 view would sum with
    # rounded increments and could argmin the WRONG row when two
    # decimals collide at float precision — the caller picks an exact
    # Arrow-side path or fails loudly.
    if num is None:
        return None, valid
    filled = pc.fill_null(num, pa.scalar(0, type=num.type)
                          if not pa.types.is_floating(num.type)
                          else pa.scalar(0.0, type=num.type))
    vals = filled.to_numpy(zero_copy_only=False)
    return vals, valid


def _whole_partition_agg_arrow(v_sorted: pa.Array, part: np.ndarray,
                               func: str) -> pa.Array:
    """Whole-partition aggregates for types the numpy kernels don't
    take (strings, binary, decimals): Arrow hash aggregation broadcast
    back by the dense partition code — exact in the value's own type."""
    t = pa.table({"__c": pa.array(part), "__v": v_sorted})
    agg = t.group_by("__c").aggregate([("__v", func)])
    agg = agg.sort_by("__c")
    by_code = agg.column(f"__v_{func}")
    if isinstance(by_code, pa.ChunkedArray):
        by_code = by_code.combine_chunks()
    return by_code.take(pa.array(part))


def _window(table: pa.Table, plan: Window) -> pa.Table:
    """One analytic column over ``table``: sort once by (partition,
    order keys), then evaluate with the vectorized segment kernels in
    :mod:`hyperspace_tpu.ops.window` — no per-partition Python/pandas
    loop.  Semantics in the Window node's docstring."""
    from hyperspace_tpu.ops import window as W

    n = table.num_rows
    if n == 0:
        return table.append_column(
            plan.name, pa.array([], type=_window_empty_type(table, plan)))

    part_orig = W.partition_codes(table, plan.partition_by)
    pname = "__part"
    suffix = 1
    while pname in table.column_names:  # user column collision guard
        pname = f"__part__{suffix}"
        suffix += 1
    work = table.append_column(pname, pa.array(part_orig))
    perm = _sort_indices(work, [(pname, True)] + list(plan.order_by))
    perm_np = np.asarray(perm)
    part = part_orig[perm_np]
    new_part = np.empty(n, dtype=bool)
    new_part[0] = True
    new_part[1:] = part[1:] != part[:-1]

    # Tie groups: partition change OR any order-key change (null-safe,
    # both-NaN equal — Spark normalizes NaN ordering ties).
    new_tie = new_part.copy()
    for c, _asc in plan.order_by:
        col_sorted = table.column(c).take(perm)
        valid = np.asarray(pc.is_valid(col_sorted)
                           .to_numpy(zero_copy_only=False))
        vals = col_sorted.to_numpy(zero_copy_only=False)
        with np.errstate(invalid="ignore"):
            eq = vals[1:] == vals[:-1]
        if vals.dtype.kind == "f":
            eq = eq | (np.isnan(vals[1:].astype(float))
                       & np.isnan(vals[:-1].astype(float)))
        same = (valid[1:] == valid[:-1]) & (~valid[1:] | eq)
        new_tie[1:] |= ~same.astype(bool)

    part_start, part_end = W.segment_bounds(new_part)
    func = plan.func
    if func in ("lag", "lead"):
        # Exact index shift within partitions on the sorted layout;
        # Arrow take preserves the value type bit-for-bit and
        # out-of-partition positions null via the validity mask.
        src_type = table.schema.field(plan.value).type
        v_sorted = table.column(plan.value).take(perm)
        if isinstance(v_sorted, pa.ChunkedArray):
            v_sorted = v_sorted.combine_chunks()
        shift = plan.offset if func == "lag" else -plan.offset
        idx = np.arange(n) - shift
        inb = (idx >= 0) & (idx < n)
        rows = np.nonzero(inb)[0]
        valid = np.zeros(n, dtype=bool)
        valid[rows] = part[idx[rows]] == part[rows]
        taken = v_sorted.take(pa.array(np.where(valid, idx, 0)))
        out = pc.if_else(pa.array(valid), taken,
                         pa.scalar(None, type=src_type))
    elif func == "row_number":
        out = pa.array(W.row_number(part_start))
    elif func == "rank":
        out = pa.array(W.rank_from_ties(part_start, new_tie))
    elif func == "dense_rank":
        out = pa.array(W.dense_rank_from_ties(new_part, new_tie))
    elif func == "ntile":
        out = pa.array(W.ntile(part_start, part_end, plan.offset))
    else:
        _, tie_end = W.segment_bounds(new_tie)
        lo, hi = W.frame_bounds(part_start, part_end, tie_end,
                                plan.frame, bool(plan.order_by))
        src_type = table.schema.field(plan.value).type if plan.value \
            else None
        v_sorted = None
        if plan.value is not None:
            v_sorted = table.column(plan.value).take(perm)
            if isinstance(v_sorted, pa.ChunkedArray):
                v_sorted = v_sorted.combine_chunks()
        if func in ("first_value", "last_value"):
            arg, nonempty = W.frame_first_last(lo, hi,
                                               func == "first_value")
            taken = v_sorted.take(pa.array(arg))
            out = pc.if_else(pa.array(nonempty), taken,
                             pa.scalar(None, type=src_type))
        elif func == "count" and plan.value is None:
            out = pa.array(W.frame_count(None, lo, hi))
        else:
            vals, valid = _np_window_values(v_sorted)
            if vals is None:
                # Strings/binary/decimals: exact Arrow hash-agg path for
                # whole-partition shapes, loud error for running frames
                # (parity with the round-4 engine; decimals additionally
                # avoid a lossy float64 round-trip).
                whole = plan.frame is None and not plan.order_by
                arrow_funcs = ("min", "max") \
                    if not pa.types.is_decimal(v_sorted.type) \
                    else ("min", "max", "sum", "mean")
                if func in arrow_funcs and whole:
                    out = _whole_partition_agg_arrow(v_sorted, part, func)
                    if func in ("sum", "mean"):
                        out = pc.cast(out, pa.float64())
                elif func == "count":
                    out = pa.array(W.frame_count(valid, lo, hi))
                else:
                    raise ValueError(
                        f"Running window {func}() over a "
                        f"{v_sorted.type} column is not supported; "
                        f"drop the ORDER BY for a whole-partition "
                        f"{func}, or cast the column to a "
                        f"numeric/temporal type")
            elif func == "count":
                out = pa.array(W.frame_count(valid, lo, hi))
            elif func == "sum":
                sums, cnt = W.frame_sum(vals, valid, lo, hi)
                if vals.dtype.kind == "u":
                    # uint64 sums computed in uint64; the int64 result
                    # column overflows loudly, never wraps.
                    if sums.size and sums.max() > np.iinfo(np.int64).max:
                        raise ValueError(
                            "window sum() over a uint64 column "
                            "overflows the int64 result type")
                    out = pa.array(sums.astype(np.int64),
                                   mask=(cnt == 0))
                elif vals.dtype.kind in "ib":
                    out = pa.array(sums.astype(np.int64),
                                   mask=(cnt == 0))
                else:
                    out = pa.array(sums.astype(np.float64),
                                   mask=(cnt == 0))
            elif func == "mean":
                means, cnt = W.frame_mean(vals, valid, lo, hi)
                out = pa.array(means, mask=(cnt == 0))
            else:  # min / max
                arg, cnt = W.frame_min_max(
                    vals, valid, lo, hi, part_start, part_end,
                    plan.frame, is_min=(func == "min"))
                taken = v_sorted.take(pa.array(arg))
                out = pc.if_else(pa.array(cnt > 0), taken,
                                 pa.scalar(None, type=src_type))
    # Scatter back to the original row order.
    inverse = np.empty(n, dtype=np.int64)
    inverse[perm_np] = np.arange(n)
    out = out.take(pa.array(inverse))
    if plan.name in table.column_names:
        return table.set_column(table.column_names.index(plan.name),
                                plan.name, out)
    return table.append_column(plan.name, out)


def _concat_horizontal(left: pa.Table, right: pa.Table) -> pa.Table:
    names = list(left.column_names)
    cols = list(left.columns)
    for name, col in zip(right.column_names, right.columns):
        out_name = name
        n = 1
        while out_name in names:
            out_name = f"{name}__{n}"
            n += 1
        names.append(out_name)
        cols.append(col)
    return pa.table(dict(zip(names, cols)))


def _eval_column(expr: Expr, table: pa.Table):
    """Evaluate ``expr`` as an output COLUMN (Compute/WithColumns/agg
    inputs): array results pass through, scalar results broadcast to the
    table's length (``lit(1)`` as a column)."""
    result = _arrow_eval(expr, table)
    if isinstance(result, pa.Scalar):
        return pa.array([result.as_py()] * table.num_rows,
                        type=result.type if result.is_valid else None)
    return result


def _coerce_numeric_strings(column) -> np.ndarray:
    """Vectorized null-on-failure numeric parse of a string column:
    float64 values with NaN where the string (or a null) didn't parse —
    the one shared home for the pd.to_numeric coerce idiom."""
    import pandas as pd

    arr = column.to_numpy(zero_copy_only=False)
    return pd.to_numeric(pd.Series(arr), errors="coerce") \
        .to_numpy(dtype=np.float64, na_value=np.nan)


def _parse_numeric(column, target_type) -> pa.Array:
    """Parse a string column as ``target_type``, null on failure — the
    Spark coercion for string-column vs numeric-literal comparisons
    ('05' == 5 and '5.0' == 5 match via the double promotion; 'abc'
    becomes null and the row drops)."""
    try:
        return pc.cast(column, target_type)
    except (pa.ArrowInvalid, pa.ArrowTypeError):
        # 'abc' -> NaN, which no comparison matches — same row-drop
        # effect as Spark's null.
        return pa.array(_coerce_numeric_strings(column), type=target_type)


def _arrow_eval(expr: Expr, table: pa.Table):
    if isinstance(expr, Col):
        return table.column(expr.name)
    if isinstance(expr, Lit):
        return pa.scalar(expr.value)
    if isinstance(expr, BinOp):
        left = _arrow_eval(expr.left, table)
        right = _arrow_eval(expr.right, table)
        ops = {"==": pc.equal, "<": pc.less, "<=": pc.less_equal,
               ">": pc.greater, ">=": pc.greater_equal}
        try:
            return ops[expr.op](left, right)
        except pa.ArrowNotImplementedError:
            # Spark-style coercion.  String column vs numeric literal: Spark
            # casts the STRING side to the numeric type ('05' == 5 matches),
            # so cast the column, not the literal.  Otherwise a scalar of a
            # different type is cast to the column's type (e.g. "2024" vs an
            # int64 partition column).  Uncastable values re-raise.
            def coerced(scalar, column):
                if (pa.types.is_string(column.type)
                        and (pa.types.is_integer(scalar.type)
                             or pa.types.is_floating(scalar.type))):
                    # Spark promotes string-vs-numeric to DOUBLE, so
                    # '5.0' == 5 and '5e0' == 5 both match.
                    target = pa.float64()
                    return pc.cast(scalar, target), \
                        _parse_numeric(column, target)
                # pc.cast parses, e.g. string "2024" -> int64 2024.
                return pc.cast(scalar, column.type), column

            try:
                if isinstance(left, pa.Scalar) and not isinstance(right, pa.Scalar):
                    lhs, rhs = coerced(left, right)
                    return ops[expr.op](lhs, rhs)
                if isinstance(right, pa.Scalar) and not isinstance(left, pa.Scalar):
                    rhs, lhs = coerced(right, left)
                    return ops[expr.op](lhs, rhs)
            except (pa.ArrowInvalid, pa.ArrowTypeError, ValueError, TypeError):
                pass
            raise
    if isinstance(expr, Arith):
        left = _arrow_eval(expr.left, table)
        right = _arrow_eval(expr.right, table)
        if expr.op == "/":
            # Spark non-ANSI division: result is DOUBLE; x / 0 is NULL
            # (arrow would give inf for floats and raise for ints).
            left = pc.cast(left, pa.float64())
            right = pc.cast(right, pa.float64())
            zero = pc.equal(right, pa.scalar(0.0))
            safe = pc.if_else(zero, pa.scalar(1.0), right)
            return pc.if_else(zero, pa.scalar(None, type=pa.float64()),
                              pc.divide(left, safe))
        fn = {"+": pc.add, "-": pc.subtract, "*": pc.multiply}[expr.op]
        return fn(left, right)
    if isinstance(expr, Neg):
        return pc.negate(_arrow_eval(expr.child, table))
    if isinstance(expr, And):
        return pc.and_kleene(_arrow_eval(expr.left, table), _arrow_eval(expr.right, table))
    if isinstance(expr, Or):
        return pc.or_kleene(_arrow_eval(expr.left, table), _arrow_eval(expr.right, table))
    if isinstance(expr, Not):
        return pc.invert(_arrow_eval(expr.child, table))
    if isinstance(expr, BucketIn):
        # Quarantine containment (rules/hybrid.py): membership of each
        # row's hash bucket — computed with the build kernel's own host
        # mirror, so "rows of bucket b" here can never disagree with
        # which rows the damaged index file actually held.  Nulls hash to
        # their deterministic sentinel bucket (same as the build): the
        # mask is null-free.
        from hyperspace_tpu.io.columnar import to_hash_words
        from hyperspace_tpu.ops.hash import bucket_ids_np

        word_cols = [np.asarray(to_hash_words(table.column(c)))
                     for c in expr.columns]
        row_buckets = bucket_ids_np(word_cols, expr.num_buckets)
        return pa.array(np.isin(
            row_buckets, np.asarray(expr.buckets, dtype=row_buckets.dtype)))
    if isinstance(expr, IsIn):
        child = _arrow_eval(expr.child, table)
        # Spark 3VL, which arrow's is_in does not implement:
        #   NULL IN (...)          -> NULL  (arrow: false)
        #   x IN (..no match.., NULL) -> NULL  (arrow: false)
        # Both matter under NOT — false would flip to TRUE and keep rows
        # SQL drops.
        values = [v for v in expr.values if v is not None]
        null_in_list = len(values) != len(expr.values)
        null_bool = pa.scalar(None, type=pa.bool_())
        result = pc.is_in(child, value_set=pa.array(values)) if values \
            else pa.scalar(False)
        if null_in_list:
            result = pc.if_else(result, pa.scalar(True), null_bool)
        if isinstance(child, pa.Scalar):
            if not child.is_valid:
                return null_bool
            return result
        return pc.if_else(pc.is_valid(child), result, null_bool)
    if isinstance(expr, IsNull):
        return pc.is_null(_arrow_eval(expr.child, table))
    if isinstance(expr, Cast):
        from hyperspace_tpu.io.parquet import _dtype_from_string

        child = _arrow_eval(expr.child, table)
        target = _dtype_from_string(expr.type_name)
        try:
            return pc.cast(child, target)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError,
                pa.ArrowTypeError):
            pass
        # Spark non-ANSI CAST: unconvertible values become null and
        # float->int truncates toward zero (out-of-range -> null), never
        # an error.  Vectorized try isn't available in arrow, so retry
        # element-wise only when the bulk safe cast fails.
        import math

        def int_bounds(t):
            bits = t.bit_width
            if pa.types.is_signed_integer(t):
                return -(1 << (bits - 1)), (1 << (bits - 1)) - 1
            return 0, (1 << bits) - 1

        def scalar_cast(v):
            if v is None:
                return None
            if isinstance(v, (float, str)) and pa.types.is_integer(target):
                # Spark parses numeric strings as decimal and truncates:
                # '3.5' AS INT is 3, not null.  Integer strings parse
                # EXACTLY (int64 strings must not round-trip via float64)
                # but only ASCII-digit forms — int()'s Python-only syntax
                # ('1_000', unicode digits) must null exactly like the
                # vectorized pd.to_numeric column path does.
                if isinstance(v, str):
                    import re

                    sv = v.strip()
                    if re.fullmatch(r"[+-]?[0-9]+", sv):
                        v = int(sv)
                    else:
                        f = _coerce_numeric_strings(pa.array([v]))[0]
                        if math.isnan(f):
                            return None
                        v = float(f)
                if isinstance(v, float):
                    if math.isnan(v) or math.isinf(v):
                        return None
                    v = int(v)  # truncation toward zero, like Spark
                lo, hi = int_bounds(target)
                return v if lo <= v <= hi else None
            try:
                return pc.cast(pa.array([v]), target)[0].as_py()
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError,
                    pa.ArrowTypeError, ValueError, OverflowError):
                return None

        if isinstance(child, pa.Scalar):
            return pa.scalar(scalar_cast(child.as_py()), type=target)
        ctype = child.type
        if (pa.types.is_integer(target) and target.bit_width <= 64
                and not (pa.types.is_unsigned_integer(target)
                         and target.bit_width == 64)
                and (pa.types.is_floating(ctype)
                     or pa.types.is_string(ctype)
                     or pa.types.is_large_string(ctype))):
            # The common fallback cases — float->int with fractional values,
            # string->int with any bad value — stay vectorized instead of
            # an O(n) Python loop.  Numeric strings parse as decimal first
            # ('3.5' AS INT is 3, like Spark), bad values null.
            valid = np.asarray(
                pc.is_valid(child).to_numpy(zero_copy_only=False))
            if pa.types.is_floating(ctype):
                arr = np.asarray(pc.fill_null(child, 0.0)
                                 .to_numpy(zero_copy_only=False),
                                 dtype=np.float64)
            else:
                arr = _coerce_numeric_strings(child)
                valid &= ~np.isnan(arr)
                arr = np.where(np.isnan(arr), 0.0, arr)
            finite = np.isfinite(arr)
            trunc = np.trunc(np.where(finite, arr, 0.0))
            lo, hi = int_bounds(target)
            hi_f = float(hi)
            ok = valid & finite & (trunc >= float(lo)) & (
                trunc <= hi_f if int(hi_f) == hi else trunc < hi_f)
            vals = np.where(ok, trunc, 0.0).astype(np.int64)
            if not pa.types.is_floating(ctype):
                # float64 is exact only below 2**53: integer strings in the
                # tail (int64-range ids) re-parse exactly, element-wise
                # over just those rows.  Only ASCII-integer forms can gain
                # precision — the vectorized regex keeps the loop empty for
                # float-form tails ('1e300' columns stay O(1) Python).
                big = np.nonzero(valid & (np.abs(trunc) >= 2.0**53))[0]
                if big.size:
                    intlike = np.asarray(pc.fill_null(
                        pc.match_substring_regex(
                            child, r"^\s*[+-]?[0-9]+\s*$"), False)
                        .to_numpy(zero_copy_only=False), dtype=bool)
                    big = big[intlike[big]]
                for i in big.tolist():
                    exact = scalar_cast(child[i].as_py())
                    if exact is None:
                        ok[i] = False
                    else:
                        vals[i] = exact
                        ok[i] = True
            out = pa.array(vals, mask=~ok)
            return pc.cast(out, target)
        return pa.array([scalar_cast(v) for v in child.to_pylist()],
                        type=target)
    if isinstance(expr, Extract):
        child = _arrow_eval(expr.child, table)
        fns = {"year": pc.year, "month": pc.month, "day": pc.day,
               "quarter": pc.quarter}
        out = fns[expr.field](child)
        # Spark's year()/month()/... return INT (32-bit); arrow yields
        # int64 — match Spark so downstream casts/joins see the same type.
        return pc.cast(out, pa.int32())
    if isinstance(expr, StringFn):
        args = [_arrow_eval(a, table) for a in expr.args]
        if expr.name == "upper":
            return pc.utf8_upper(args[0])
        if expr.name == "lower":
            return pc.utf8_lower(args[0])
        if expr.name == "length":
            return pc.cast(pc.utf8_length(args[0]), pa.int32())
        if expr.name == "trim":
            return pc.utf8_trim_whitespace(args[0])
        if expr.name == "ltrim":
            return pc.utf8_ltrim_whitespace(args[0])
        if expr.name == "rtrim":
            return pc.utf8_rtrim_whitespace(args[0])
        if expr.name == "substring":
            # SQL 1-based start (validated >= 1 at construction).
            begin = expr.args[1].value - 1
            if len(expr.args) == 2:
                return pc.utf8_slice_codeunits(args[0], begin)
            return pc.utf8_slice_codeunits(args[0], begin,
                                           begin + expr.args[2].value)
        # concat: Spark casts every part to string and nulls the WHOLE
        # result when any part is null.  Scalars stay scalars —
        # binary_join_element_wise broadcasts them without an O(rows)
        # literal array.
        def as_str(part):
            t = part.type
            if not (pa.types.is_string(t) or pa.types.is_large_string(t)):
                part = pc.cast(part, pa.string())
            return part

        parts = [as_str(a) for a in args]
        return pc.binary_join_element_wise(
            *parts, "", null_handling="emit_null")
    if isinstance(expr, StringMatch):
        child = _arrow_eval(expr.child, table)
        if expr.kind == "like":
            return pc.match_like(child, expr.pattern)
        if expr.kind == "startswith":
            return pc.starts_with(child, expr.pattern)
        if expr.kind == "endswith":
            return pc.ends_with(child, expr.pattern)
        return pc.match_substring(child, expr.pattern)
    if isinstance(expr, Case):
        # Spark CASE: branches in order, null condition = branch NOT taken
        # (arrow's if_else would propagate the null instead), no ELSE =
        # null.  Built right-to-left so earlier branches win.
        result = _arrow_eval(expr.otherwise, table)
        if isinstance(result, pa.Scalar) and not result.is_valid \
                and result.type == pa.null():
            # Untyped null ELSE: let if_else infer the branch type.
            result = None
        for cond, value in reversed(expr.branches):
            mask = _arrow_eval(cond, table)
            if isinstance(mask, pa.Scalar):
                mask = pa.scalar(bool(mask.as_py())
                                 if mask.is_valid else False)
            else:
                mask = pc.fill_null(mask, False)
            val = _arrow_eval(value, table)
            if result is None:
                # First (innermost) branch with a null ELSE: null of the
                # branch value's type.
                result = pa.scalar(None, type=val.type)
            result = pc.if_else(mask, val, result)
        return result
    raise ValueError(f"Unsupported expression: {expr!r}")
