"""Strict-mode runtime device→host sync guard
(``hyperspace.system.deviceGuard.enabled``, default off).

The device-discipline lint rule proves the CHECKED-IN hot path pulls
from device only through attributed seams; this module enforces the same
contract at RUNTIME, where the static pass cannot see — a monkeypatched
kernel, a REPL experiment, a dependency upgrade that starts calling
``.item()`` on our arrays.  Armed, it turns PR 11's ``exec.transfer.*``
metrics from an observation into a contract tests and bench can assert.

Two halves:

  - **attributed seams** — :func:`pull` (array) and :func:`scalar`
    (0-d/dynamic-shape sync point) are the sanctioned device→host
    conversions.  Each runs inside an allowance window, counts
    ``guard.sync.attributed``, and feeds ``exec.transfer.d2h.bytes``
    through the PR 11 timeline seam.  They are cheap pass-throughs for
    host inputs and when the guard is off.
  - **the guard** — when a collect runs with the conf key on,
    :func:`arm` patches the concrete jax array type's scalar-conversion
    surface (``item``/``tolist``/``__float__``/``__int__``/``__bool__``/
    ``__index__``/``__array__``) to RAISE :class:`DeviceSyncError` (and
    count ``guard.sync.violations``) on any conversion outside an
    allowance window.  The patch is process-global, installed lazily on
    first arming — with the conf off (the default) nothing is patched
    and jax is untouched.

CPU-backend caveat (documented in docs/18): on the CPU backend numpy can
reach a jax array's buffer zero-copy, so a raw ``np.asarray`` is not
interceptable there — but ``.item()``/``float()``/``bool()``/``int()``
(the scalar syncs that dominate the 196-site audit) always route through
the patched surface, and the static rule covers ``np.asarray`` at review
time.  On TPU every pull crosses the wire through ``__array__`` and is
caught.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Iterator

from hyperspace_tpu.exceptions import DeviceSyncError

_armed = False
_patched = False
_install_lock = threading.Lock()
_local = threading.local()


def _depth() -> int:
    return getattr(_local, "depth", 0)


@contextlib.contextmanager
def allowed() -> Iterator[None]:
    """Allowance window: device→host conversions inside the block are
    attributed (used by the seams below and timeline.kernel_end)."""
    _local.depth = _depth() + 1
    try:
        yield
    finally:
        _local.depth = _depth() - 1


def armed() -> bool:
    return _armed


def arm(conf) -> None:
    """Apply the session conf to the process-global guard (called per
    collect, like the fault injector / tracing conf re-application).
    First arming installs the patch; disarming leaves it installed but
    inert (one module-global read per conversion)."""
    global _armed
    enabled = bool(getattr(conf, "device_guard_enabled", False))
    if enabled and not _patched:
        _install()
    _armed = enabled


def _is_device(x: Any) -> bool:
    cls = type(x)
    return cls.__module__.split(".")[0] in ("jaxlib", "jax")


def pull(x: Any, site: str = "") -> Any:
    """THE sanctioned device→host array pull: ``np.asarray`` inside an
    allowance window, ``exec.transfer.d2h.bytes``-counted and
    ``guard.sync.attributed``-counted.  Host inputs pass through."""
    import numpy as np

    if not _is_device(x):
        return np.asarray(x)
    with allowed():
        out = np.asarray(x)
    _count_attributed(site)
    from hyperspace_tpu.telemetry import timeline

    timeline.record_transfer("d2h", int(out.nbytes))
    return out


def scalar(x: Any, site: str = "") -> Any:
    """The sanctioned dynamic-shape sync point: one scalar (a match
    count, a group count) crossing to host, attributed.  Returns a
    Python number; host numbers pass through."""
    if not _is_device(x):
        return x
    with allowed():
        import numpy as np

        out = np.asarray(x).item()
    _count_attributed(site)
    return out


def _count_attributed(site: str) -> None:
    if not _armed:
        return
    from hyperspace_tpu.telemetry import metrics

    metrics.inc("guard.sync.attributed")


def _violation(kind: str):
    from hyperspace_tpu.telemetry import metrics

    metrics.inc("guard.sync.violations")
    return DeviceSyncError(
        f"unattributed device→host sync via {kind} while "
        f"hyperspace.system.deviceGuard.enabled is on — route the pull "
        f"through execution/sync_guard.pull()/scalar() (or the "
        f"timeline kernel seams) so exec.transfer.*/exec.kernel.* can "
        f"attribute it (docs/18-static-analysis.md)")


def _install() -> None:
    """Patch the concrete jax array type's host-conversion surface.
    Idempotent; never raises (an unpatchable surface just leaves the
    guard static-only, and doctor/tests surface that via the metrics)."""
    global _patched
    with _install_lock:
        if _patched:
            return
        try:
            import jaxlib.xla_extension as _xe

            cls = _xe.ArrayImpl
        except Exception:  # noqa: BLE001 — no jaxlib, nothing to guard
            _patched = True
            return

        def _wrap(name: str):
            orig = getattr(cls, name, None)
            if orig is None:
                return

            def guarded(self, *args, **kwargs):
                if _armed and _depth() == 0:
                    raise _violation(f"{name}()")
                return orig(self, *args, **kwargs)

            guarded.__name__ = name
            try:
                setattr(cls, name, guarded)
            except (AttributeError, TypeError):
                pass  # immutable slot on this jaxlib — partial coverage

        for name in ("item", "tolist", "__float__", "__int__",
                     "__bool__", "__index__", "__array__"):
            _wrap(name)
        _patched = True
