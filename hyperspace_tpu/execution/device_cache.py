"""HBM-resident index-column cache: pay the transfer once, compute many.

Round-3 verdict item 2: every query re-read parquet into host arrow and
re-shipped columns to the accelerator, so the architecture's payoff —
SURVEY §2.4's "per-core XLA data parallelism over HBM-resident columnar
batches" — was structurally unreachable.  Spark gives the reference this
for free through the block manager's RDD caching; here it is explicit: a
process-wide, byte-budgeted LRU of POST-DECODE device arrays keyed by
file identity.

Keys are ``(files_fingerprint, column, kind)`` where the fingerprint
hashes the scan's resolved file list with each file's (size, mtime):
an overwritten or compacted index version can never serve stale arrays —
its fingerprint differs, and the dead entries age out of the LRU.

Residency changes ROUTING, not just speed: once a scan's referenced
columns are resident, the device path's cost is kernel time plus
round-trip latency (no per-row shipping), so the executor compares row
counts against the much smaller ``resident_min_rows`` derived from the
measured profile (utils/calibrate.py) instead of the cold-transfer
threshold.  Population policy (conf ``deviceCachePolicy``):

  - ``auto`` (default): populate whenever the device path runs anyway —
    free on locally attached chips where the calibrated cold threshold
    routes large scans to the device organically.
  - ``eager``: ship eligible scan columns on FIRST use even when the
    cold cost model would stay on host — an explicit opt-in for
    repeat-heavy workloads behind a slow attachment (pay the tunnel
    once, serve every later query from HBM).
  - ``off``: never cache.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Dict, Iterable, Optional, Tuple

Key = Tuple[str, str, str]  # (files fingerprint, column name, kind)


def files_fingerprint(paths: Iterable[str]) -> Optional[str]:
    """Content-identity hash of a resolved scan file list: path order plus
    each file's size and mtime_ns.  None when any file is unstat-able
    (races with vacuum — safer to skip caching than to key on guesses)."""
    h = hashlib.md5()
    try:
        for p in paths:
            st = os.stat(p)
            h.update(p.encode())
            h.update(f":{st.st_size}:{st.st_mtime_ns};".encode())
    except OSError:
        return None
    return h.hexdigest()


class ByteBudgetLRU:
    """Thread-safe byte-budgeted LRU — the eviction mechanism shared by
    the device-column cache below and the serving layer's optimize-result
    plan cache (execution/plan_cache.py): one policy (LRU within an
    explicit byte budget, oversize entries rejected and tombstoned),
    one metric shape (``<prefix>.hits/misses/evictions`` counters plus a
    ``<prefix>.bytes`` gauge when ``metric_prefix`` is set)."""

    _REJECTED_MAX = 4096  # bound the tombstone set; clear-all on overflow

    def __init__(self, metric_prefix: Optional[str] = None) -> None:
        self._entries: "OrderedDict[object, object]" = OrderedDict()
        self._nbytes: Dict[object, int] = {}
        # Keys whose values did not fit the byte budget: callers that
        # make ROUTING decisions off cache presence (the device cache's
        # eager policy) must stop retrying them, or every repeat pays the
        # full cost forever.
        self._rejected: set = set()
        self._lock = threading.Lock()
        self._prefix = metric_prefix
        self.bytes_cached = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def _inc(self, name: str) -> None:
        if self._prefix is not None:
            from hyperspace_tpu.telemetry import metrics

            metrics.inc(f"{self._prefix}.{name}")

    def get(self, key):
        with self._lock:
            value = self._entries.get(key)
            if value is None:
                self.misses += 1
                self._inc("misses")
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            self._inc("hits")
            return value

    def contains(self, key) -> bool:
        """Presence probe — no hit/miss accounting (the actual fetch
        follows if the caller decides to use the cache)."""
        with self._lock:
            return key in self._entries

    def peek(self, key):
        """Value without hit/miss accounting or a recency update — for
        callers that must validate an entry before deciding whether the
        lookup counts as a hit (the plan cache's staleness check)."""
        with self._lock:
            return self._entries.get(key)

    def was_rejected(self, key) -> bool:
        with self._lock:
            return key in self._rejected

    def put(self, key, value, nbytes: int, budget_bytes: int) -> bool:
        """Insert ``value`` accounted at ``nbytes``, evicting LRU entries
        to stay within ``budget_bytes``.  Returns False (and tombstones
        the key) when the entry can never fit."""
        nbytes = int(nbytes or 0)
        if nbytes <= 0 or nbytes > budget_bytes:
            with self._lock:
                if len(self._rejected) >= self._REJECTED_MAX:
                    self._rejected.clear()
                self._rejected.add(key)
            return False
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return True
            while self.bytes_cached + nbytes > budget_bytes and self._entries:
                old_key, _old = self._entries.popitem(last=False)
                self.bytes_cached -= self._nbytes.pop(old_key)
                self.evictions += 1
                self._inc("evictions")
            self._entries[key] = value
            self._nbytes[key] = nbytes
            self.bytes_cached += nbytes
            if self._prefix is not None:
                from hyperspace_tpu.telemetry import metrics

                metrics.set_gauge(f"{self._prefix}.bytes", self.bytes_cached)
        return True

    def pop(self, key) -> None:
        """Drop one entry (invalidation)."""
        with self._lock:
            if key in self._entries:
                del self._entries[key]
                self.bytes_cached -= self._nbytes.pop(key)
            self._rejected.discard(key)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._nbytes.clear()
            self._rejected.clear()
            self.bytes_cached = 0

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "entries": len(self._entries),
                    "bytes": self.bytes_cached}


class DeviceColumnCache(ByteBudgetLRU):
    """Byte-budgeted LRU of device arrays (thread-safe).  The byte cost
    of an entry is the array's own ``nbytes``."""

    def __init__(self) -> None:
        super().__init__(metric_prefix="cache.device")

    def put(self, key: Key, arr, budget_bytes: int) -> None:  # type: ignore[override]
        super().put(key, arr, int(getattr(arr, "nbytes", 0) or 0),
                    budget_bytes)


# One cache per process: device memory is a process-level resource, and
# fingerprint keys are content-based so sessions can safely share entries.
_CACHE = DeviceColumnCache()


def global_cache() -> DeviceColumnCache:
    return _CACHE
