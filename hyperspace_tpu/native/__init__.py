"""ctypes binding to the native host runtime (native/hs_native.cc).

The native library accelerates the metadata-side hot loops — the per-query
file walk + stat + md5 fingerprint fold behind index-validity signatures
(FileBasedSignatureProvider.scala:38-61; SURVEY §3.2's driver bottleneck).
Loading is best-effort: a prebuilt ``native/build/libhs_native.so`` is used
if present, otherwise the library is compiled once with g++ into a cache
directory; on any failure every entry point returns None and callers fall
back to the pure-Python implementations, which are byte-identical.

Set ``HS_NATIVE=0`` to disable the native path entirely.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import List, Optional, Sequence, Tuple

# The C++ source ships INSIDE the package so pip installs keep the native
# fast path (it compiles on demand wherever g++ exists).
_SOURCE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "hs_native.cc")
_PREBUILT = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native", "build", "libhs_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False
_compile_thread: Optional[threading.Thread] = None
_waited_for_compile = False
# How long the FIRST caller waits for an in-flight compile before falling
# back to pure Python (the compile keeps running; a later call picks up the
# result).  Keeps a cold cache from stalling a user query on g++ -O2.
_FIRST_CALL_WAIT_S = 5.0

_SCAN_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.c_longlong, ctypes.c_longlong)


def _cache_so_path() -> str:
    with open(_SOURCE, "rb") as f:
        digest = hashlib.md5(f.read()).hexdigest()[:12]
    cache_dir = os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.join(os.path.expanduser("~"), ".cache")),
        "hyperspace_tpu")
    os.makedirs(cache_dir, exist_ok=True)
    return os.path.join(cache_dir, f"libhs_native-{digest}.so")


def _compile(out_path: str) -> bool:
    tmp = out_path + f".tmp{os.getpid()}"
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
             "-o", tmp, _SOURCE],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, out_path)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.hs_scan_files.restype = ctypes.c_int
    lib.hs_scan_files.argtypes = [ctypes.POINTER(ctypes.c_char_p),
                                  ctypes.c_int, _SCAN_CB, ctypes.c_void_p]
    lib.hs_scan_fingerprint.restype = ctypes.c_longlong
    lib.hs_scan_fingerprint.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_char_p,
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_longlong)]
    lib.hs_fold_md5.restype = None
    lib.hs_fold_md5.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_longlong,
        ctypes.c_char_p, ctypes.c_char_p]
    lib.hs_md5.restype = None
    lib.hs_md5.argtypes = [ctypes.c_char_p, ctypes.c_longlong,
                           ctypes.c_char_p]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None when unavailable/disabled.

    A missing cache triggers ONE background compile; callers get the Python
    fallback (None) after a short bounded wait instead of blocking a user
    query on g++.
    """
    global _lib, _lib_failed, _compile_thread
    if os.environ.get("HS_NATIVE", "1") == "0":
        return None
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        for candidate in (_PREBUILT,):
            if os.path.isfile(candidate):
                try:
                    _lib = _declare(ctypes.CDLL(candidate))
                    return _lib
                except OSError:
                    pass
        if not os.path.isfile(_SOURCE):
            _lib_failed = True
            return None
        cached = _cache_so_path()
        thread = None
        if not os.path.isfile(cached):
            if _compile_thread is None:
                _compile_thread = threading.Thread(
                    target=_compile, args=(cached,), daemon=True)
                _compile_thread.start()
            # Only ONE caller pays the bounded wait; while the compile is
            # still running everyone else gets the Python fallback at once.
            global _waited_for_compile
            if not _waited_for_compile:
                _waited_for_compile = True
                thread = _compile_thread
    if thread is not None:
        thread.join(_FIRST_CALL_WAIT_S)
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        cached = _cache_so_path()
        if not os.path.isfile(cached):
            # Observe the thread dead FIRST, then re-check the file —
            # os.replace may land between the two looks otherwise.
            thread_dead = (_compile_thread is not None
                           and not _compile_thread.is_alive())
            if not os.path.isfile(cached):
                if thread_dead:
                    _lib_failed = True  # finished and produced nothing
                return None  # failed, or still compiling: Python fallback
        try:
            _lib = _declare(ctypes.CDLL(cached))
        except OSError:
            _lib_failed = True
    return _lib


def available() -> bool:
    return get_lib() is not None


def scan_files(root_paths: Sequence[str]
               ) -> Optional[List[Tuple[str, int, int]]]:
    """(path, size, mtime_ns) for every data file under the roots, or None
    when the native library is unavailable.  Order is unspecified; callers
    sort (as io/files.list_data_files always has)."""
    lib = get_lib()
    if lib is None:
        return None
    out: List[Tuple[str, int, int]] = []

    @_SCAN_CB
    def cb(_ctx, path, size, mtime_ns):
        out.append((path.decode("utf-8", "surrogateescape"), size, mtime_ns))

    roots = (ctypes.c_char_p * len(root_paths))(
        *[p.encode("utf-8", "surrogateescape") for p in root_paths])
    lib.hs_scan_files(roots, len(root_paths), cb, None)
    return out


def scan_fingerprint(root_paths: Sequence[str], init: str = ""
                     ) -> Optional[Tuple[str, int, int]]:
    """(md5 hex, file count, total bytes) over the sorted data files of the
    roots — walk + stat + fold in one native pass.  None when unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    roots = (ctypes.c_char_p * len(root_paths))(
        *[p.encode("utf-8", "surrogateescape") for p in root_paths])
    out_hex = ctypes.create_string_buffer(33)
    total = ctypes.c_longlong(0)
    count = lib.hs_scan_fingerprint(roots, len(root_paths),
                                    init.encode("utf-8"), out_hex,
                                    ctypes.byref(total))
    return out_hex.value.decode("ascii"), int(count), int(total.value)


def fold_md5_files(files: Sequence[Tuple[str, int, int]], init: str = ""
                   ) -> Optional[str]:
    """Native fold over (path, size, mtime) triples in the given order;
    byte-identical to utils.hashing.fold_md5 over f"{size}{mtime}{path}"."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(files)
    paths = (ctypes.c_char_p * n)(
        *[f[0].encode("utf-8", "surrogateescape") for f in files])
    sizes = (ctypes.c_longlong * n)(*[f[1] for f in files])
    mtimes = (ctypes.c_longlong * n)(*[f[2] for f in files])
    out_hex = ctypes.create_string_buffer(33)
    lib.hs_fold_md5(paths, sizes, mtimes, n, init.encode("utf-8"), out_hex)
    return out_hex.value.decode("ascii")
