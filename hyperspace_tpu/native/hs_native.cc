// Native host runtime for hyperspace_tpu: the metadata-side hot loops.
//
// The per-query index-validity check folds an md5 over (size, mtime, path)
// of EVERY source file (the reference does this on the Spark driver,
// FileBasedSignatureProvider.scala:38-61, flagged in SURVEY §3.2 as the
// metadata-side scaling bottleneck).  In Python that is one os.walk + stat
// + hashlib round-trip per file; this library does walk + stat + sort +
// fold in one C++ pass, exposed through a C ABI consumed via ctypes
// (hyperspace_tpu/native/__init__.py).  Results are byte-identical to the
// Python implementations (same decimal formatting, same lexicographic
// ordering, same data-file filter), so signatures computed with and without
// the native path agree.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -o libhs_native.so hs_native.cc

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <string>
#include <sys/stat.h>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// MD5 (RFC 1321).  Self-contained so the library has zero dependencies.
// ---------------------------------------------------------------------------
struct Md5 {
  uint32_t a = 0x67452301, b = 0xefcdab89, c = 0x98badcfe, d = 0x10325476;
  uint64_t total = 0;
  unsigned char buf[64];
  size_t buf_len = 0;

  static uint32_t rotl(uint32_t x, int s) { return (x << s) | (x >> (32 - s)); }

  void process(const unsigned char* p) {
    static const uint32_t K[64] = {
        0xd76aa478, 0xe8c7b756, 0x242070db, 0xc1bdceee, 0xf57c0faf,
        0x4787c62a, 0xa8304613, 0xfd469501, 0x698098d8, 0x8b44f7af,
        0xffff5bb1, 0x895cd7be, 0x6b901122, 0xfd987193, 0xa679438e,
        0x49b40821, 0xf61e2562, 0xc040b340, 0x265e5a51, 0xe9b6c7aa,
        0xd62f105d, 0x02441453, 0xd8a1e681, 0xe7d3fbc8, 0x21e1cde6,
        0xc33707d6, 0xf4d50d87, 0x455a14ed, 0xa9e3e905, 0xfcefa3f8,
        0x676f02d9, 0x8d2a4c8a, 0xfffa3942, 0x8771f681, 0x6d9d6122,
        0xfde5380c, 0xa4beea44, 0x4bdecfa9, 0xf6bb4b60, 0xbebfbc70,
        0x289b7ec6, 0xeaa127fa, 0xd4ef3085, 0x04881d05, 0xd9d4d039,
        0xe6db99e5, 0x1fa27cf8, 0xc4ac5665, 0xf4292244, 0x432aff97,
        0xab9423a7, 0xfc93a039, 0x655b59c3, 0x8f0ccc92, 0xffeff47d,
        0x85845dd1, 0x6fa87e4f, 0xfe2ce6e0, 0xa3014314, 0x4e0811a1,
        0xf7537e82, 0xbd3af235, 0x2ad7d2bb, 0xeb86d391};
    static const int S[64] = {7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22,
                              7, 12, 17, 22, 5, 9,  14, 20, 5, 9,  14, 20,
                              5, 9,  14, 20, 5, 9,  14, 20, 4, 11, 16, 23,
                              4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23,
                              6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21,
                              6, 10, 15, 21};
    uint32_t m[16];
    for (int i = 0; i < 16; i++)
      m[i] = (uint32_t)p[i * 4] | ((uint32_t)p[i * 4 + 1] << 8) |
             ((uint32_t)p[i * 4 + 2] << 16) | ((uint32_t)p[i * 4 + 3] << 24);
    uint32_t A = a, B = b, C = c, D = d;
    for (int i = 0; i < 64; i++) {
      uint32_t f;
      int g;
      if (i < 16) {
        f = (B & C) | (~B & D);
        g = i;
      } else if (i < 32) {
        f = (D & B) | (~D & C);
        g = (5 * i + 1) % 16;
      } else if (i < 48) {
        f = B ^ C ^ D;
        g = (3 * i + 5) % 16;
      } else {
        f = C ^ (B | ~D);
        g = (7 * i) % 16;
      }
      uint32_t tmp = D;
      D = C;
      C = B;
      B = B + rotl(A + f + K[i] + m[g], S[i]);
      A = tmp;
    }
    a += A;
    b += B;
    c += C;
    d += D;
  }

  void update(const void* data, size_t len) {
    const unsigned char* p = (const unsigned char*)data;
    total += len;
    if (buf_len) {
      size_t need = 64 - buf_len;
      size_t take = len < need ? len : need;
      memcpy(buf + buf_len, p, take);
      buf_len += take;
      p += take;
      len -= take;
      if (buf_len == 64) {
        process(buf);
        buf_len = 0;
      }
    }
    while (len >= 64) {
      process(p);
      p += 64;
      len -= 64;
    }
    if (len) {
      memcpy(buf, p, len);
      buf_len = len;
    }
  }

  void hex(char out[33]) {
    unsigned char pad[72];
    size_t pad_len = 0;
    pad[pad_len++] = 0x80;
    size_t rem = (buf_len + 1) % 64;
    size_t zeros = (rem <= 56) ? 56 - rem : 120 - rem;
    memset(pad + pad_len, 0, zeros);
    pad_len += zeros;
    uint64_t bits = total * 8;
    for (int i = 0; i < 8; i++) pad[pad_len++] = (bits >> (8 * i)) & 0xff;
    update(pad, pad_len);  // total is now wrong, but we're done
    uint32_t out_words[4] = {a, b, c, d};
    for (int i = 0; i < 16; i++) {
      snprintf(out + 2 * i, 3, "%02x",
               (out_words[i / 4] >> (8 * (i % 4))) & 0xff);
    }
    out[32] = 0;
  }
};

void md5_string(const std::string& s, char out[33]) {
  Md5 h;
  h.update(s.data(), s.size());
  h.hex(out);
}

// ---------------------------------------------------------------------------
// Directory walk with the engine's data-file filter
// ---------------------------------------------------------------------------
struct Entry {
  std::string path;
  long long size;
  long long mtime_ns;
};

bool is_data_file(const char* name) {
  // Spark convention (util/PathUtils.scala:31-36): '_'/'.' prefixed names
  // are metadata.
  return name[0] != '_' && name[0] != '.';
}

void walk(const std::string& root, std::vector<Entry>& out) {
  struct stat st;
  if (stat(root.c_str(), &st) != 0) return;
  if (S_ISREG(st.st_mode)) {
    out.push_back({root, (long long)st.st_size,
                   (long long)st.st_mtim.tv_sec * 1000000000LL +
                       st.st_mtim.tv_nsec});
    return;
  }
  if (!S_ISDIR(st.st_mode)) return;
  DIR* dir = opendir(root.c_str());
  if (!dir) return;
  std::vector<std::string> subdirs;
  std::vector<Entry> files;
  for (struct dirent* e; (e = readdir(dir)) != nullptr;) {
    if (strcmp(e->d_name, ".") == 0 || strcmp(e->d_name, "..") == 0) continue;
    std::string child = root + "/" + e->d_name;
    // Match Python os.walk(followlinks=False): a symlink to a file is
    // listed (stat follows it), a symlink to a directory is NOT recursed.
    struct stat lst;
    if (lstat(child.c_str(), &lst) != 0) continue;
    bool is_link = S_ISLNK(lst.st_mode);
    struct stat cst;
    if (stat(child.c_str(), &cst) != 0) continue;
    if (S_ISDIR(cst.st_mode)) {
      if (!is_link) subdirs.push_back(child);
    } else if (S_ISREG(cst.st_mode) && is_data_file(e->d_name)) {
      // One stat per file: keep size/mtime from this look.
      files.push_back({child, (long long)cst.st_size,
                       (long long)cst.st_mtim.tv_sec * 1000000000LL +
                           cst.st_mtim.tv_nsec});
    }
  }
  closedir(dir);
  std::sort(files.begin(), files.end(),
            [](const Entry& a, const Entry& b) { return a.path < b.path; });
  for (auto& f : files) out.push_back(std::move(f));
  std::sort(subdirs.begin(), subdirs.end());
  for (const auto& d : subdirs) walk(d, out);
}

void fold(const std::vector<Entry>& entries, const char* init, char out[33]) {
  // h_{i+1} = md5(h_i + "{size}{mtime}{name}") — identical to
  // utils/hashing.fold_md5 over io/files.list_data_files output.
  std::string acc = init ? init : "";
  char hex[33];
  for (const auto& e : entries) {
    char nums[48];
    snprintf(nums, sizeof(nums), "%lld%lld", e.size, e.mtime_ns);
    std::string part = acc + nums + e.path;
    md5_string(part, hex);
    acc.assign(hex, 32);
  }
  memcpy(out, acc.c_str(), acc.size() + 1);
}

}  // namespace

extern "C" {

// Walk every root (file or directory), calling cb once per data file.
// Emission order: per-directory sorted, directories recursed in sorted
// order (callers re-sort globally by path, as the Python path does).
int hs_scan_files(const char** roots, int n_roots,
                  void (*cb)(void* ctx, const char* path, long long size,
                             long long mtime_ns),
                  void* ctx) {
  std::vector<Entry> out;
  for (int i = 0; i < n_roots; i++) walk(roots[i], out);
  for (const auto& e : out) cb(ctx, e.path.c_str(), e.size, e.mtime_ns);
  return (int)out.size();
}

// One-shot fingerprint: walk + global path sort + md5 fold.  Returns the
// file count; out_hex must hold 33 bytes; out_total_bytes may be null.
long long hs_scan_fingerprint(const char** roots, int n_roots,
                              const char* init, char* out_hex,
                              long long* out_total_bytes) {
  std::vector<Entry> entries;
  for (int i = 0; i < n_roots; i++) walk(roots[i], entries);
  std::sort(entries.begin(), entries.end(),
            [](const Entry& a, const Entry& b) { return a.path < b.path; });
  long long total = 0;
  for (const auto& e : entries) total += e.size;
  fold(entries, init, out_hex);
  if (out_total_bytes) *out_total_bytes = total;
  return (long long)entries.size();
}

// Fold md5 over caller-provided (size, mtime, path) triples, in order.
void hs_fold_md5(const char** paths, const long long* sizes,
                 const long long* mtimes, long long n, const char* init,
                 char* out_hex) {
  std::vector<Entry> entries;
  entries.reserve((size_t)n);
  for (long long i = 0; i < n; i++)
    entries.push_back({paths[i], sizes[i], mtimes[i]});
  fold(entries, init, out_hex);
}

// md5 of a UTF-8 string (util/HashingUtils.scala:24-35 analog).
void hs_md5(const char* data, long long len, char* out_hex) {
  Md5 h;
  h.update(data, (size_t)len);
  h.hex(out_hex);
}

}  // extern "C"
