"""Resolve the index system path and per-index paths.

Reference contract: index/PathResolver.scala:30-76 — the system path comes
from conf (default ``<warehouse>/indexes``); index lookup is
case-insensitive against existing directory names (:39-63).
"""

from __future__ import annotations

import os

from hyperspace_tpu.config import HyperspaceConf

DEFAULT_SYSTEM_DIR = "spark-warehouse/indexes"  # PathResolver.scala:65-75 analog


class PathResolver:
    def __init__(self, conf: HyperspaceConf) -> None:
        self._conf = conf

    @property
    def system_path(self) -> str:
        path = self._conf.system_path
        if not path:
            path = os.path.join(os.getcwd(), DEFAULT_SYSTEM_DIR)
        return os.path.abspath(path)

    def get_index_path(self, name: str) -> str:
        """Case-insensitive match against existing index dirs
        (PathResolver.scala:39-63); falls back to the given name."""
        root = self.system_path
        if os.path.isdir(root):
            lowered = name.lower()
            for existing in os.listdir(root):
                if existing.lower() == lowered:
                    return os.path.join(root, existing)
        return os.path.join(root, name)
