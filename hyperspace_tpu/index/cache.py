"""Metadata cache: TTL-cached index log entries, cleared on mutation.

Reference contract: index/CachingIndexCollectionManager.scala:38-170 — a
creation-time-based cache of the latest stable entries with a 300 s default
TTL (IndexConstants.scala:61-63), cleared by every mutating API so the same
session always sees its own writes.
"""

from __future__ import annotations

import time
from typing import List, Optional

from hyperspace_tpu.index.log_entry import IndexLogEntry
from hyperspace_tpu.index.manager import IndexCollectionManager


class CreationTimeBasedCache:
    """Cache[T] analog (index/Cache.scala:22,
    CachingIndexCollectionManager.scala:124-170)."""

    def __init__(self) -> None:
        self._entries: Optional[List[IndexLogEntry]] = None
        self._created_at: float = 0.0

    def get(self, expiry_seconds: float) -> Optional[List[IndexLogEntry]]:
        if self._entries is None:
            return None
        if time.monotonic() - self._created_at > expiry_seconds:
            return None
        return self._entries

    def set(self, entries: List[IndexLogEntry]) -> None:
        self._entries = entries
        self._created_at = time.monotonic()

    def clear(self) -> None:
        self._entries = None


class CachingIndexCollectionManager(IndexCollectionManager):
    """IndexCollectionManager whose get_indexes serves from a session-scoped
    TTL cache; every mutating API clears it first
    (CachingIndexCollectionManager.scala:38-105)."""

    def __init__(self, session) -> None:
        super().__init__(session)
        if not hasattr(session, "_index_entry_cache"):
            session._index_entry_cache = CreationTimeBasedCache()
        self._cache: CreationTimeBasedCache = session._index_entry_cache

    def get_indexes(self, states=None) -> List[IndexLogEntry]:
        cached = self._cache.get(self.session.conf.cache_expiry_seconds)
        if cached is None:
            cached = super().get_indexes(None)
            # A degraded listing (an unreadable index was skipped) is
            # never cached: pinning the partial view for the TTL would
            # hide a recovered store — and keep strict mode from raising.
            if not self.last_listing_degraded:
                self._cache.set(cached)
        if states is None:
            return list(cached)
        return [e for e in cached if e.state in states]

    def clear_cache(self) -> None:
        self._cache.clear()

    def create(self, dataset, config) -> None:
        self.clear_cache()
        super().create(dataset, config)
        self.clear_cache()

    def delete(self, name: str) -> None:
        self.clear_cache()
        super().delete(name)
        self.clear_cache()

    def restore(self, name: str) -> None:
        self.clear_cache()
        super().restore(name)
        self.clear_cache()

    def vacuum(self, name: str) -> None:
        self.clear_cache()
        super().vacuum(name)
        self.clear_cache()

    def cancel(self, name: str) -> None:
        self.clear_cache()
        super().cancel(name)
        self.clear_cache()

    def refresh(self, name: str, mode: str = "full"):
        self.clear_cache()
        try:
            return super().refresh(name, mode)
        finally:
            self.clear_cache()

    def optimize(self, name: str, mode: str = "quick"):
        self.clear_cache()
        try:
            return super().optimize(name, mode)
        finally:
            self.clear_cache()
