"""User-facing index specification.

Reference contract: index/IndexConfig.scala:28-158 — name + indexed columns +
included columns, with validation (non-empty name/indexed, no duplicate
columns across the two lists, case-insensitive) and a builder-style API.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

from hyperspace_tpu.exceptions import HyperspaceError


LAYOUTS = ("lexicographic", "zorder")


@dataclasses.dataclass(frozen=True)
class IndexConfig:
    index_name: str
    indexed_columns: List[str]
    included_columns: List[str] = dataclasses.field(default_factory=list)
    # Row order within buckets: "lexicographic" (the reference's layout) or
    # "zorder" — Morton-interleaved indexed columns, clustering EVERY
    # indexed dimension so per-file min/max pruning works for range queries
    # on any of them (ops/zorder.py; beyond reference parity).
    layout: str = "lexicographic"

    def __init__(self, index_name: str, indexed_columns: Sequence[str],
                 included_columns: Sequence[str] = (),
                 layout: str = "lexicographic") -> None:
        object.__setattr__(self, "index_name", index_name)
        object.__setattr__(self, "indexed_columns", list(indexed_columns))
        object.__setattr__(self, "included_columns", list(included_columns))
        object.__setattr__(self, "layout", layout)
        self._validate()

    def _validate(self) -> None:
        # IndexConfig.scala:32-53
        if not self.index_name or not self.index_name.strip():
            raise HyperspaceError("Index name cannot be empty")
        if not self.indexed_columns:
            raise HyperspaceError("Indexed columns cannot be empty")
        if self.layout not in LAYOUTS:
            raise HyperspaceError(
                f"Unknown layout {self.layout!r}; expected one of {LAYOUTS}")
        if self.layout == "zorder" and len(self.indexed_columns) > 4:
            raise HyperspaceError("Z-order supports at most 4 indexed columns")
        lowered_indexed = [c.lower() for c in self.indexed_columns]
        lowered_included = [c.lower() for c in self.included_columns]
        if len(set(lowered_indexed)) != len(lowered_indexed):
            raise HyperspaceError("Duplicate indexed column names are not allowed")
        if len(set(lowered_included)) != len(lowered_included):
            raise HyperspaceError("Duplicate included column names are not allowed")
        if set(lowered_indexed) & set(lowered_included):
            raise HyperspaceError(
                "Duplicate column names in indexed/included columns are not allowed")

    def __eq__(self, other: object) -> bool:
        # Case-insensitive equality (IndexConfig.scala:55-66).
        if not isinstance(other, IndexConfig):
            return NotImplemented
        return (
            self.index_name.lower() == other.index_name.lower()
            and [c.lower() for c in self.indexed_columns]
            == [c.lower() for c in other.indexed_columns]
            and sorted(c.lower() for c in self.included_columns)
            == sorted(c.lower() for c in other.included_columns)
        )

    def __hash__(self) -> int:
        return hash((
            self.index_name.lower(),
            tuple(c.lower() for c in self.indexed_columns),
            tuple(sorted(c.lower() for c in self.included_columns)),
        ))

    @property
    def all_columns(self) -> List[str]:
        return list(self.indexed_columns) + list(self.included_columns)


SKETCH_TYPES = ("MinMax", "ValueList", "BloomFilter")


@dataclasses.dataclass(frozen=True)
class DataSkippingIndexConfig:
    """Spec for a data-skipping index: per-source-file sketches over
    ``sketched_columns``.  Unlike the covering index, no data is copied —
    queries scan the source with a pruned file list.

    Per-column sketch families:
      - "MinMax" (default): value range from Parquet footers — O(footer)
        build, prunes range and point predicates on clustered columns.
      - "ValueList": the distinct values when few (<=64) — reads the column
        at build, prunes EQUALITY/IN on low-cardinality columns whose
        min/max spans everything (category/status columns).
      - "BloomFilter": an 8192-bit bloom over the distinct values — reads
        the column at build, prunes EQUALITY/IN at ANY cardinality with
        false positives only (never false negatives)."""

    index_name: str
    sketched_columns: List[str]
    sketch_types: List[str] = dataclasses.field(default_factory=list)

    def __init__(self, index_name: str,
                 sketched_columns: Sequence[str],
                 sketch_types: Optional[Sequence[str]] = None) -> None:
        object.__setattr__(self, "index_name", index_name)
        object.__setattr__(self, "sketched_columns", list(sketched_columns))
        object.__setattr__(
            self, "sketch_types",
            list(sketch_types) if sketch_types is not None
            else ["MinMax"] * len(self.sketched_columns))
        self._validate()

    def _validate(self) -> None:
        if not self.index_name or not self.index_name.strip():
            raise HyperspaceError("Index name cannot be empty")
        if not self.sketched_columns:
            raise HyperspaceError("Sketched columns cannot be empty")
        lowered = [c.lower() for c in self.sketched_columns]
        if len(set(lowered)) != len(lowered):
            raise HyperspaceError("Duplicate sketched column names are not allowed")
        if len(self.sketch_types) != len(self.sketched_columns):
            raise HyperspaceError(
                "sketch_types must match sketched_columns in length")
        bad = [t for t in self.sketch_types if t not in SKETCH_TYPES]
        if bad:
            raise HyperspaceError(
                f"Unknown sketch type(s) {bad}; expected {SKETCH_TYPES}")

    # Case-insensitive equality/hash — the same contract as IndexConfig
    # (IndexConfig.scala:55-66); the generated dataclass pair would be
    # case-sensitive and unhashable (list field).
    def __eq__(self, other: object) -> bool:
        if not isinstance(other, DataSkippingIndexConfig):
            return NotImplemented
        return (self.index_name.lower() == other.index_name.lower()
                and [c.lower() for c in self.sketched_columns]
                == [c.lower() for c in other.sketched_columns]
                and self.sketch_types == other.sketch_types)

    def __hash__(self) -> int:
        return hash((self.index_name.lower(),
                     tuple(c.lower() for c in self.sketched_columns),
                     tuple(self.sketch_types)))
