"""Operation-log manager for stores WITHOUT atomic rename.

``IndexLogManager`` (the default) needs two POSIX gifts: ``O_EXCL``
create-if-absent for numbered entries and atomic rename for the
``latestStable`` pointer.  Object stores (GCS/S3) offer neither — what
they offer instead is per-key generations and conditional puts, and this
manager rebuilds the same protocol from those primitives, the way Delta
Lake's log does (Armbrust et al., VLDB 2020):

  - a numbered entry commits with ``put_if_absent`` — the same
    exactly-one-winner arbitration, now server-side;
  - ``latestStable`` is maintained by a **generation-CAS loop**: read
    (pointer, generation), then ``put_if_generation_match``.  A lost CAS
    re-reads; a pointer that already names a NEWER stable entry wins
    outright (monotonic ids ⇒ no lost update, no ABA);
  - listing may be stale (the store's visibility window), so
    ``get_latest_id`` treats the listing as a hint and **probes forward
    with point reads** — which are strongly consistent — until the first
    miss.  Correctness never rests on listing freshness: a stale-derived
    id collides at ``put_if_absent`` and the action layer's transaction
    loop rebases and retries.

Plugs into ``hyperspace.index.logManagerClass``; the store backend itself
is a second seam (``hyperspace.index.logStoreClass``), so tests can run
the identical protocol over :class:`PosixLogStore` and
:class:`EmulatedObjectStore`.  The failure envelope matches the POSIX
manager: transient store errors retry (``hyperspace.system.io.retry.*``),
a torn put burns its id and is skipped by every reader, and the pointer
is a cache — the numbered entries stay the truth.
"""

from __future__ import annotations

import json
import os
import random
import time
from typing import List, Optional

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.log_entry import IndexLogEntry, States
from hyperspace_tpu.index.log_manager import (
    HYPERSPACE_LOG_DIR,
    LATEST_STABLE,
    IndexLogManager,
)
from hyperspace_tpu.io.log_store import LogStore

# Bound on CAS re-read loops: each iteration means a concurrent pointer
# writer won an update in the read-CAS window; with monotonic-id yielding
# the loop converges long before this (the bound only caps pathological
# fault-injection storms).
_CAS_ATTEMPTS = 16


class ObjectStoreLogManager(IndexLogManager):
    """IndexLogManager over a :class:`LogStore` (conditional puts, no
    rename).  Keeps the ``(index_path)``-only constructor contract of the
    ``logManagerClass`` seam; the collection manager pushes conf through
    :meth:`configure` after construction."""

    store_class: str = "hyperspace_tpu.io.log_store.EmulatedObjectStore"
    stale_list_s: float = 0.0

    def __init__(self, index_path: str) -> None:
        super().__init__(index_path)
        self._store: Optional[LogStore] = None

    def configure(self, conf) -> None:
        self.store_class = conf.log_store_class
        self.stale_list_s = float(conf.object_store_stale_list_ms) / 1000.0

    @property
    def store(self) -> LogStore:
        if self._store is None:
            from hyperspace_tpu.utils.reflection import load_class

            cls = load_class(self.store_class, LogStore, HyperspaceError)
            self._store = cls(os.path.join(self.index_path,
                                           HYPERSPACE_LOG_DIR),
                              stale_list_s=self.stale_list_s)
        return self._store

    # -- reads --------------------------------------------------------------
    def _parse(self, data: Optional[bytes]) -> Optional[IndexLogEntry]:
        """None for absent AND for torn/corrupt payloads (a burned id)."""
        if data is None:
            return None
        try:
            return IndexLogEntry.from_dict(json.loads(data.decode("utf-8")))
        except (ValueError, KeyError, UnicodeDecodeError):
            return None

    def get_log(self, log_id: int) -> Optional[IndexLogEntry]:
        def attempt() -> Optional[IndexLogEntry]:
            try:
                return self._parse(self.store.read(str(log_id)))
            except FileNotFoundError:
                return None

        return self.retry.call(attempt)

    def _probe_past(self, latest: Optional[int]) -> Optional[int]:
        """Walk point reads past ``latest`` until the first miss.  Ids are
        contiguous (every writer commits at base+1/base+2 and collisions
        rebase), except that the action protocol never writes id 0 — so an
        empty hint probes both 0 and 1 before concluding the log is empty."""
        starts = [0, 1] if latest is None else [latest + 1]
        for start in starts:
            probe = start
            while self.store.exists(str(probe)):
                latest = probe
                probe += 1
            if latest is not None:
                break
        return latest

    def get_latest_id(self) -> Optional[int]:
        """Listing as a hint, point reads as the truth: probe ids past the
        listed maximum until the first miss, so a stale list can delay a
        reader by at most one probe round — never yield a colliding id to
        a writer (put_if_absent arbitrates regardless)."""
        def attempt() -> Optional[int]:
            ids = [int(k) for k in self.store.list_keys() if k.isdigit()]
            return self._probe_past(max(ids) if ids else None)

        return self.retry.call(attempt)

    def get_latest_stable_log(self) -> Optional[IndexLogEntry]:
        def read_pointer() -> Optional[IndexLogEntry]:
            try:
                return self._parse(self.store.read(LATEST_STABLE))
            except FileNotFoundError:
                return None

        entry = self.retry.call(read_pointer)
        if entry is not None and entry.state in States.STABLE:
            return entry
        latest = self.get_latest_id()
        if latest is None:
            return None
        for log_id in range(latest, -1, -1):
            entry = self.get_log(log_id)
            if entry is not None and entry.state in States.STABLE:
                return entry
        return None

    def log_ids(self) -> List[int]:
        def attempt() -> List[int]:
            ids = {int(k) for k in self.store.list_keys() if k.isdigit()}
            # Same forward probe as get_latest_id: ids the stale listing
            # hides are still discoverable by point reads.
            latest = self._probe_past(max(ids) if ids else None)
            if latest is not None:
                ids.update(i for i in range(latest + 1)
                           if i in ids or self.store.exists(str(i)))
            return sorted(ids)

        return self.retry.call(attempt)

    # -- writes -------------------------------------------------------------
    def write_log(self, log_id: int, entry: IndexLogEntry) -> bool:
        from hyperspace_tpu.index.log_manager import _refuse_hypothetical

        _refuse_hypothetical(entry)
        entry.id = log_id
        payload = json.dumps(entry.to_dict(), indent=2).encode("utf-8")

        def attempt() -> bool:
            return self.store.put_if_absent(str(log_id), payload)

        return self.retry.call(attempt)

    def create_latest_stable_log(self, log_id: int) -> bool:
        """Point ``latestStable`` at entry ``log_id`` via generation-CAS.

        Loop invariant: the pointer only ever moves to a stable entry
        with an id ≥ its current one.  A racer that commits a newer
        stable entry between our read and CAS makes our CAS fail; the
        re-read then sees their pointer and we YIELD (ids are monotonic,
        so "newer id wins" is exactly "no lost update")."""
        try:
            payload = self.retry.call(lambda: self.store.read(str(log_id)))
        except FileNotFoundError:
            return False
        rng = random.Random()
        for attempt in range(_CAS_ATTEMPTS):
            cur, gen = self.retry.call(
                lambda: self.store.read_with_generation(LATEST_STABLE))
            cur_entry = self._parse(cur)
            if cur_entry is not None and cur_entry.state in States.STABLE \
                    and (cur_entry.id or 0) >= log_id:
                return True  # a newer stable pointer already won
            # A torn/corrupt pointer (cur_entry None with gen > 0) is
            # OVERWRITTEN here — the generation check still makes the
            # overwrite race-safe.
            if self.retry.call(lambda: self.store.put_if_generation_match(
                    LATEST_STABLE, payload, gen)):
                return True
            time.sleep(self.retry.delay_s(min(attempt, 4), rng))
        # Pointer update lost a pathological storm: the pointer is only a
        # cache, get_latest_stable_log's reverse scan stays correct.
        return False

    def delete_latest_stable_log(self) -> bool:
        """No-op by design: every caller (Action.end, cancel) immediately
        recreates the pointer, and the CAS overwrite in
        create_latest_stable_log subsumes delete+create WITHOUT the
        pointer-absent window a rename-less store could not close
        atomically."""
        return True
