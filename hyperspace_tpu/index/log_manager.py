"""Append-only, id-numbered JSON operation log with optimistic concurrency.

Reference contract: index/IndexLogManager.scala:33-166 —
  - log lives under ``<indexPath>/_hyperspace_log/<id>`` (one JSON file per id)
  - ``write_log(id, entry)`` MUST fail if the id already exists (multi-writer
    safety comes from exactly this create-if-absent semantic, :149-165)
  - ``latestStable`` is a copy of the newest entry whose state is stable
    (:115-147), with ``get_latest_stable_log`` falling back to a reverse scan
    (:94-113).

On a local POSIX filesystem, ``open(path, 'x')`` gives the atomic
create-if-absent we need; object-store backends can subclass and use
conditional puts.

Failure envelope (exercised by tests/test_log_manager.py's fault-injection
cases, via io/faults.py):
  - transient IO errors (EIO/ENOSPC/...) retry with bounded exponential
    backoff + jitter (utils/retry.py; tuned by ``hyperspace.system.io.retry.*``)
  - a torn/corrupt entry — a writer died mid-write — is DETECTED AND
    SKIPPED by every reader (reads fall back to the newest parseable
    entry), never repaired in place: the file keeps its id so the
    append-only numbering stays collision-free
  - a crash around the latestStable rename leaves either the old pointer,
    no pointer, or the new pointer — all three resolve correctly (the
    pointer is a cache; the numbered entries are the truth).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

from hyperspace_tpu.exceptions import ConcurrentWriteError
from hyperspace_tpu.index.log_entry import IndexLogEntry, States
from hyperspace_tpu.io import faults
from hyperspace_tpu.utils.retry import RetryPolicy

HYPERSPACE_LOG_DIR = "_hyperspace_log"  # IndexConstants.scala:66
LATEST_STABLE = "latestStable"


def _refuse_hypothetical(entry: IndexLogEntry) -> None:
    """What-if entries (advisor/hypothetical.py) are plan-only artifacts
    with zero data files; persisting one would make later queries trust
    an index that cannot serve a single row.  Guarded at the write seam
    of EVERY log backend so no caller can leak one into the log."""
    if entry.is_hypothetical:
        from hyperspace_tpu.exceptions import HyperspaceError

        raise HyperspaceError(
            f"Refusing to persist hypothetical index entry "
            f"{entry.name!r}: what-if entries are never written to the "
            f"operation log (docs/17-advisor.md)")


class IndexLogManager:
    """Manages the operation log of one index (IndexLogManager.scala:33-55)."""

    # Transient-IO retry budget; the collection manager overrides the
    # instance attribute from session conf (subclass __init__ signatures —
    # the logManagerClass seam — stay (index_path) only).
    retry: RetryPolicy = RetryPolicy()

    def __init__(self, index_path: str) -> None:
        self.index_path = index_path
        self.log_dir = os.path.join(index_path, HYPERSPACE_LOG_DIR)

    def configure(self, conf) -> None:
        """Post-construction conf hook: the collection manager calls this
        after the (index_path)-only constructor so pluggable subclasses
        (e.g. ObjectStoreLogManager's store class / staleness window) can
        read session conf without widening the constructor seam."""

    # -- reads --------------------------------------------------------------
    def get_log(self, log_id: int) -> Optional[IndexLogEntry]:
        """Entry ``log_id``, or None when missing OR torn/corrupt (a
        writer that died mid-write leaves a partial JSON file; readers
        skip it — the id itself stays burned for numbering)."""
        path = os.path.join(self.log_dir, str(log_id))
        if not os.path.isfile(path):
            return None
        try:
            with open(path, "r", encoding="utf-8") as f:
                return IndexLogEntry.from_dict(json.load(f))
        except (ValueError, KeyError):
            return None

    def get_latest_id(self) -> Optional[int]:
        """Highest committed id (IndexLogManager.scala:83-92).  Torn
        entries COUNT: their id is burned, so writers derived from this
        never collide with a partial file."""
        from hyperspace_tpu.io.files import list_dir

        ids = [int(n) for n in list_dir(self.log_dir, self.retry)
               if n.isdigit()]
        return max(ids) if ids else None

    def get_latest_log(self) -> Optional[IndexLogEntry]:
        """Newest PARSEABLE entry: a torn trailing record (crashed
        writer) must not make the whole index look absent."""
        latest = self.get_latest_id()
        if latest is None:
            return None
        for log_id in range(latest, -1, -1):
            entry = self.get_log(log_id)
            if entry is not None:
                return entry
        return None

    def get_latest_stable_log(self) -> Optional[IndexLogEntry]:
        """The latestStable pointer file if valid, else reverse-scan
        (IndexLogManager.scala:94-113).  Torn numbered entries are
        skipped by the scan (get_log returns None for them)."""
        stable_path = os.path.join(self.log_dir, LATEST_STABLE)
        if os.path.isfile(stable_path):
            try:
                with open(stable_path, "r", encoding="utf-8") as f:
                    entry = IndexLogEntry.from_dict(json.load(f))
            except (ValueError, KeyError):
                # Invalid/stale pointer is treated as absent
                # (IndexLogManager.scala:94-113) — fall through to the scan.
                entry = None
            if entry is not None and entry.state in States.STABLE:
                return entry
        latest = self.get_latest_id()
        if latest is None:
            return None
        for log_id in range(latest, -1, -1):
            entry = self.get_log(log_id)
            if entry is not None and entry.state in States.STABLE:
                return entry
        return None

    # -- writes -------------------------------------------------------------
    def write_log(self, log_id: int, entry: IndexLogEntry) -> bool:
        """Atomically create log file ``log_id``; False if it already exists
        (the optimistic-concurrency check, IndexLogManager.scala:149-165).
        Transient IO errors retry — each attempt unlinks its partial file
        first, so the create-if-absent probe stays honest."""
        _refuse_hypothetical(entry)
        os.makedirs(self.log_dir, exist_ok=True)
        path = os.path.join(self.log_dir, str(log_id))
        entry.id = log_id
        payload = json.dumps(entry.to_dict(), indent=2).encode("utf-8")

        def attempt() -> bool:
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                return False
            try:
                with os.fdopen(fd, "wb") as f:
                    faults.write_payload(f, payload, "log.write")
                    f.flush()
                    os.fsync(f.fileno())
            except faults.InjectedCrash:
                # Simulated process death: a real crash runs no cleanup,
                # so the partial file STAYS (that torn state is exactly
                # what the readers above must survive).
                raise
            except BaseException:
                os.unlink(path)
                raise
            return True

        return self.retry.call(attempt)

    def write_log_or_raise(self, log_id: int, entry: IndexLogEntry) -> None:
        if not self.write_log(log_id, entry):
            raise ConcurrentWriteError(
                f"Log id {log_id} for index at {self.index_path!r} was "
                "committed by a concurrent writer")

    def create_latest_stable_log(self, log_id: int) -> bool:
        """Copy entry ``log_id`` to the latestStable pointer file
        (IndexLogManager.scala:115-147).  tmp + atomic rename: a crash on
        either side of the rename leaves a resolvable pointer state."""
        src = os.path.join(self.log_dir, str(log_id))
        if not os.path.isfile(src):
            return False
        dst = os.path.join(self.log_dir, LATEST_STABLE)
        tmp = dst + ".tmp"

        def attempt() -> bool:
            with open(src, "rb") as f_in, open(tmp, "wb") as f_out:
                f_out.write(f_in.read())
                f_out.flush()
                os.fsync(f_out.fileno())
            faults.atomic_replace(tmp, dst, "log.rename")
            return True

        return self.retry.call(attempt)

    def delete_latest_stable_log(self) -> bool:
        path = os.path.join(self.log_dir, LATEST_STABLE)
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        return True

    def log_ids(self) -> List[int]:
        from hyperspace_tpu.io.files import list_dir

        return sorted(int(n) for n in list_dir(self.log_dir, self.retry)
                      if n.isdigit())
