"""The on-disk metadata model: content trees, source snapshot, fingerprints,
covering-index spec, tags, and the stable file-id tracker.

This is the Python re-expression of the reference's entire metadata schema
(index/IndexLogEntry.scala:43-686 and index/LogEntry.scala:22-46):

  - ``FileInfo``            — (name, size, mtime, id)          (:321)
  - ``Directory``/``Content`` — recursive dir tree of index/source files
                                with ``merge`` (:43-316, merge :149)
  - ``CoveringIndex``       — derived-dataset spec (:347-360)
  - ``Signature``/``Fingerprint`` — validity fingerprint (:363-377)
  - ``Update``              — appended/deleted file lists for quick refresh
                              and hybrid scan (:379-382)
  - ``Relation``/``Source`` — snapshot of the source relation (:409-431)
  - ``IndexLogEntry``       — the versioned log record (:433-612)
  - ``FileIdTracker``       — stable (path,size,mtime)→id map (:617-686)

Serialization is plain JSON via ``to_dict``/``from_dict`` with a ``version``
discriminator, like LogEntry.fromJson (index/LogEntry.scala:33-46).
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Tuple

from hyperspace_tpu.utils.paths import is_data_file

LOG_ENTRY_VERSION = "0.1"  # IndexLogEntry.scala:609

# Property key marking a what-if entry (advisor/hypothetical.py).  Lives
# here, next to the entry model, so the persistence guards in the log
# managers and the executor's scan guard can never drift from the tag the
# advisor sets.
HYPOTHETICAL_PROPERTY = "hypothetical"


# ---------------------------------------------------------------------------
# States (actions/Constants.scala:19-33)
# ---------------------------------------------------------------------------
class States:
    ACTIVE = "ACTIVE"
    CREATING = "CREATING"
    DELETED = "DELETED"
    DELETING = "DELETING"
    REFRESHING = "REFRESHING"
    VACUUMING = "VACUUMING"
    RESTORING = "RESTORING"
    OPTIMIZING = "OPTIMIZING"
    DOESNOTEXIST = "DOESNOTEXIST"

    STABLE: FrozenSet[str] = frozenset({"ACTIVE", "DELETED", "DOESNOTEXIST"})


# ---------------------------------------------------------------------------
# File / directory / content tree
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class FileInfo:
    """One leaf file (IndexLogEntry.scala:321-345). ``id`` comes from the
    FileIdTracker and is stable across log versions.  ``digest`` is the
    optional content digest (``"<algo>:<hex>"``, io/integrity.py) recorded
    at write time for index data files; source files — and every entry
    serialized before digests existed — carry None, which a scrub reports
    as ``status="unknown"`` rather than a mismatch."""

    name: str
    size: int
    mtime: int
    id: int = -1
    digest: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        d = {"name": self.name, "size": self.size,
             "modifiedTime": self.mtime, "id": self.id}
        if self.digest is not None:
            # Digest-less entries keep the exact pre-digest JSON shape:
            # old readers (and golden files) never see a new key.
            d["digest"] = self.digest
        return d

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "FileInfo":
        return FileInfo(d["name"], d["size"], d["modifiedTime"],
                        d.get("id", -1), d.get("digest"))


@dataclasses.dataclass
class Directory:
    """Recursive directory node (IndexLogEntry.scala:118-316)."""

    name: str
    files: List[FileInfo] = dataclasses.field(default_factory=list)
    subdirs: List["Directory"] = dataclasses.field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "files": [f.to_dict() for f in self.files],
            "subDirs": [d.to_dict() for d in self.subdirs],
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Directory":
        return Directory(
            d["name"],
            [FileInfo.from_dict(f) for f in d.get("files", [])],
            [Directory.from_dict(s) for s in d.get("subDirs", [])],
        )

    def merge(self, other: "Directory") -> "Directory":
        """Merge two trees rooted at the same name (IndexLogEntry.scala:149-171).

        Files are unioned (dedup by full FileInfo); same-named subdirs merge
        recursively.
        """
        if self.name != other.name:
            raise ValueError(f"Directory merge root mismatch: {self.name!r} vs {other.name!r}")
        seen = {(f.name, f.size, f.mtime): f for f in self.files}
        for f in other.files:
            seen.setdefault((f.name, f.size, f.mtime), f)
        by_name = {d.name: d for d in self.subdirs}
        merged_subdirs: List[Directory] = []
        other_names = set()
        for sub in other.subdirs:
            other_names.add(sub.name)
            if sub.name in by_name:
                merged_subdirs.append(by_name[sub.name].merge(sub))
            else:
                merged_subdirs.append(sub)
        for sub in self.subdirs:
            if sub.name not in other_names:
                merged_subdirs.append(sub)
        return Directory(self.name, sorted(seen.values(), key=lambda f: f.name),
                         sorted(merged_subdirs, key=lambda d: d.name))

    @staticmethod
    def from_leaf_files(files: Sequence[FileInfo]) -> "Directory":
        """Build the minimal tree containing exactly ``files``
        (IndexLogEntry.scala:229-275).  File names must be absolute paths;
        leaves store the basename.
        """
        root = Directory(name="/")
        for f in files:
            parts = [p for p in os.path.dirname(f.name).split(os.sep) if p]
            node = root
            for part in parts:
                nxt = next((d for d in node.subdirs if d.name == part), None)
                if nxt is None:
                    nxt = Directory(name=part)
                    node.subdirs.append(nxt)
                node = nxt
            node.files.append(FileInfo(os.path.basename(f.name), f.size,
                                       f.mtime, f.id, f.digest))
        return root

    @staticmethod
    def from_directory(path: str, file_id_tracker: "FileIdTracker",
                       throw_if_not_exists: bool = False) -> "Directory":
        """Recursively list ``path`` (IndexLogEntry.scala:193-227), skipping
        non-data files, registering each leaf with the tracker.  The result is
        rooted at "/" with the full ancestor chain so absolute leaf paths
        reconstruct."""
        path = os.path.abspath(path)
        if not os.path.isdir(path) and throw_if_not_exists:
            raise FileNotFoundError(path)
        node = Directory._scan(path, file_id_tracker)
        parent = os.path.dirname(path)
        for part in reversed([p for p in parent.split(os.sep) if p]):
            node = Directory(part, [], [node])
        return Directory("/", [], [node]) if node.name != "/" else node

    @staticmethod
    def _scan(path: str, file_id_tracker: "FileIdTracker") -> "Directory":
        files: List[FileInfo] = []
        subdirs: List[Directory] = []
        if os.path.isdir(path):
            for entry in sorted(os.scandir(path), key=lambda e: e.name):
                if entry.is_dir():
                    subdirs.append(Directory._scan(entry.path, file_id_tracker))
                elif is_data_file(entry.name):
                    from hyperspace_tpu.io import integrity

                    st = entry.stat()
                    fid = file_id_tracker.add_file(
                        os.path.abspath(entry.path), st.st_size, int(st.st_mtime_ns))
                    # Index data writers record content digests at write
                    # time (io/integrity.py); source files were never
                    # recorded and keep digest=None.
                    files.append(FileInfo(
                        entry.name, st.st_size, int(st.st_mtime_ns), fid,
                        integrity.recorded_digest(os.path.abspath(entry.path))))
        return Directory(os.path.basename(path) or "/", files, subdirs)


@dataclasses.dataclass
class Content:
    """A directory tree plus accessors over its leaf files
    (IndexLogEntry.scala:43-113)."""

    root: Directory

    def to_dict(self) -> Dict[str, Any]:
        return {"root": self.root.to_dict()}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Content":
        return Content(Directory.from_dict(d["root"]))

    def files(self) -> List[str]:
        """All leaf file paths, absolute (IndexLogEntry.scala:56-63)."""
        return [f.name for f in self.file_infos()]

    def file_infos(self) -> List[FileInfo]:
        """Leaf files with absolute-path names (IndexLogEntry.scala:65-72)."""
        out: List[FileInfo] = []

        def walk(node: Directory, prefix: str) -> None:
            base = node.name if prefix == "" else (
                prefix if node.name == "/" else os.path.join(prefix, node.name))
            if node.name == "/":
                base = "/"
            for f in node.files:
                out.append(FileInfo(os.path.join(base, f.name), f.size,
                                    f.mtime, f.id, f.digest))
            for sub in node.subdirs:
                walk(sub, base)

        walk(self.root, "")
        return out

    @staticmethod
    def from_directory(path: str, file_id_tracker: "FileIdTracker") -> "Content":
        return Content(Directory.from_directory(path, file_id_tracker))

    @staticmethod
    def from_leaf_files(files: Sequence[FileInfo]) -> Optional["Content"]:
        if not files:
            return None
        return Content(Directory.from_leaf_files(files))

    def merge(self, other: "Content") -> "Content":
        return Content(self.root.merge(other.root))


# ---------------------------------------------------------------------------
# Derived dataset (covering index) spec
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class CoveringIndex:
    """Covering-index spec (IndexLogEntry.scala:347-360): data bucketed by
    hash of ``indexed_columns`` into ``num_buckets`` files, sorted within
    buckets by the same columns, plus stored ``included_columns``."""

    KIND = "CoveringIndex"
    KIND_ABBR = "CI"

    indexed_columns: List[str]
    included_columns: List[str]
    num_buckets: int
    schema: Dict[str, str]  # column name -> dtype string (arrow dtype names)
    properties: Dict[str, str] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.KIND,
            "properties": {
                "columns": {
                    "indexed": self.indexed_columns,
                    "included": self.included_columns,
                },
                "numBuckets": self.num_buckets,
                "schema": self.schema,
                "properties": self.properties,
            },
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "CoveringIndex":
        p = d["properties"]
        return CoveringIndex(
            list(p["columns"]["indexed"]),
            list(p["columns"]["included"]),
            p["numBuckets"],
            dict(p["schema"]),
            dict(p.get("properties", {})),
        )

    @property
    def all_columns(self) -> List[str]:
        return self.indexed_columns + self.included_columns


@dataclasses.dataclass
class DataSkippingIndex:
    """Data-skipping index spec: per-source-file sketches (min/max today)
    over ``sketched_columns``.  Queries keep scanning the SOURCE data; the
    rule only shrinks the file list.  This kind is the reference roadmap's
    "more index types" (ROADMAP.md:92-94) realized — the v0.5 snapshot has
    only the covering index, so this is capability beyond reference parity
    (BASELINE.json's Z-order/data-skipping config)."""

    KIND = "DataSkippingIndex"
    KIND_ABBR = "DS"

    sketched_columns: List[str]
    sketch_types: List[str]  # per-column family; "MinMax" today
    schema: Dict[str, str]  # sketched column name -> arrow dtype string
    properties: Dict[str, str] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.KIND,
            "properties": {
                "sketches": [
                    {"column": c, "type": t}
                    for c, t in zip(self.sketched_columns, self.sketch_types)
                ],
                "schema": self.schema,
                "properties": self.properties,
            },
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "DataSkippingIndex":
        p = d["properties"]
        return DataSkippingIndex(
            [s["column"] for s in p["sketches"]],
            [s["type"] for s in p["sketches"]],
            dict(p.get("schema", {})),
            dict(p.get("properties", {})),
        )

    @property
    def all_columns(self) -> List[str]:
        return list(self.sketched_columns)


_DERIVED_DATASET_KINDS = {
    CoveringIndex.KIND: CoveringIndex,
    DataSkippingIndex.KIND: DataSkippingIndex,
}


def derived_dataset_from_dict(d: Dict[str, Any]):
    kind = d.get("kind")
    cls = _DERIVED_DATASET_KINDS.get(kind)
    if cls is None:
        raise ValueError(f"Unknown derived dataset kind: {kind!r}")
    return cls.from_dict(d)


# ---------------------------------------------------------------------------
# Signatures / fingerprints / source snapshot
# ---------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class Signature:
    provider: str
    value: str

    def to_dict(self) -> Dict[str, Any]:
        return {"provider": self.provider, "value": self.value}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Signature":
        return Signature(d["provider"], d["value"])


@dataclasses.dataclass
class LogicalPlanFingerprint:
    """Fingerprint of the source plan at index-build time
    (IndexLogEntry.scala:366-377)."""

    signatures: List[Signature]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": "LogicalPlan",
            "properties": {"signatures": [s.to_dict() for s in self.signatures]},
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "LogicalPlanFingerprint":
        return LogicalPlanFingerprint(
            [Signature.from_dict(s) for s in d["properties"]["signatures"]])


@dataclasses.dataclass
class Update:
    """Appended/deleted source files recorded by quick refresh
    (IndexLogEntry.scala:379-382)."""

    appended_files: Optional[Content] = None
    deleted_files: Optional[Content] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "appendedFiles": self.appended_files.to_dict() if self.appended_files else None,
            "deletedFiles": self.deleted_files.to_dict() if self.deleted_files else None,
        }

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> Optional["Update"]:
        if d is None:
            return None
        return Update(
            Content.from_dict(d["appendedFiles"]) if d.get("appendedFiles") else None,
            Content.from_dict(d["deletedFiles"]) if d.get("deletedFiles") else None,
        )


@dataclasses.dataclass
class Relation:
    """Snapshot of one source relation (IndexLogEntry.scala:409-415):
    root paths, the file content tree at build time, schema, format, options,
    and any pending update from a quick refresh."""

    root_paths: List[str]
    content: Content
    schema: Dict[str, str]
    file_format: str
    options: Dict[str, str] = dataclasses.field(default_factory=dict)
    update: Optional[Update] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rootPaths": self.root_paths,
            "data": {
                "properties": {
                    "content": self.content.to_dict(),
                    "update": self.update.to_dict() if self.update else None,
                }
            },
            "dataSchemaJson": self.schema,
            "fileFormat": self.file_format,
            "options": self.options,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Relation":
        props = d["data"]["properties"]
        return Relation(
            list(d["rootPaths"]),
            Content.from_dict(props["content"]),
            dict(d["dataSchemaJson"]),
            d["fileFormat"],
            dict(d.get("options", {})),
            Update.from_dict(props.get("update")),
        )


@dataclasses.dataclass
class Source:
    """Source plan snapshot: relations + fingerprint
    (IndexLogEntry.scala:417-431)."""

    relations: List[Relation]
    fingerprint: LogicalPlanFingerprint

    def to_dict(self) -> Dict[str, Any]:
        return {
            "plan": {
                "properties": {
                    "relations": [r.to_dict() for r in self.relations],
                    "fingerprint": self.fingerprint.to_dict(),
                }
            }
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Source":
        p = d["plan"]["properties"]
        return Source(
            [Relation.from_dict(r) for r in p["relations"]],
            LogicalPlanFingerprint.from_dict(p["fingerprint"]),
        )


# ---------------------------------------------------------------------------
# The log entry
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class IndexLogEntry:
    """One record in the operation log (IndexLogEntry.scala:433-612)."""

    name: str
    derived_dataset: CoveringIndex
    content: Content
    source: Source
    properties: Dict[str, str] = dataclasses.field(default_factory=dict)
    state: str = States.DOESNOTEXIST
    id: int = 0
    timestamp: int = dataclasses.field(default_factory=lambda: int(time.time() * 1000))
    # In-memory only (never serialized): per-entry memo tags
    # (IndexLogEntry.scala:560-603, IndexLogEntryTags.scala:21-56).
    _tags: Dict[str, Any] = dataclasses.field(default_factory=dict, repr=False, compare=False)

    VERSION = LOG_ENTRY_VERSION

    # -- serialization ------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": self.VERSION,
            "id": self.id,
            "state": self.state,
            "timestamp": self.timestamp,
            "name": self.name,
            "derivedDataset": self.derived_dataset.to_dict(),
            "content": self.content.to_dict(),
            "source": self.source.to_dict(),
            "properties": self.properties,
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "IndexLogEntry":
        if d.get("version") != LOG_ENTRY_VERSION:
            raise ValueError(f"Unsupported log entry version: {d.get('version')!r}")
        return IndexLogEntry(
            name=d["name"],
            derived_dataset=derived_dataset_from_dict(d["derivedDataset"]),
            content=Content.from_dict(d["content"]),
            source=Source.from_dict(d["source"]),
            properties=dict(d.get("properties", {})),
            state=d["state"],
            id=d["id"],
            timestamp=d["timestamp"],
        )

    # -- accessors mirroring the reference ---------------------------------
    @property
    def is_covering(self) -> bool:
        return isinstance(self.derived_dataset, CoveringIndex)

    @property
    def is_hypothetical(self) -> bool:
        """True for what-if entries synthesized by the advisor
        (advisor/hypothetical.py): ACTIVE-looking but with zero data
        files.  The optimizer only sees them when they are passed
        explicitly to ``session.optimize(hypothetical=[...])``; the log
        managers refuse to persist them and the executor refuses to run
        scans over them."""
        return self.properties.get(HYPOTHETICAL_PROPERTY, "").lower() \
            == "true"

    @property
    def indexed_columns(self) -> List[str]:
        # Data-skipping entries expose their sketched columns here so
        # kind-agnostic display code (statistics, explain) works; the
        # rewrite rules filter by kind before touching these.
        if not self.is_covering:
            return list(self.derived_dataset.sketched_columns)
        return self.derived_dataset.indexed_columns

    @property
    def included_columns(self) -> List[str]:
        if not self.is_covering:
            return []
        return self.derived_dataset.included_columns

    @property
    def num_buckets(self) -> int:
        return getattr(self.derived_dataset, "num_buckets", 0)

    @property
    def kind_abbr(self) -> str:
        return self.derived_dataset.KIND_ABBR

    def signature(self) -> Signature:
        """The (single) stored signature (IndexLogEntry.scala:532-536)."""
        sigs = self.source.fingerprint.signatures
        if len(sigs) != 1:
            raise ValueError(f"Expected exactly one signature, got {len(sigs)}")
        return sigs[0]

    @property
    def relations(self) -> List[Relation]:
        return self.source.relations

    def has_lineage_column(self) -> bool:
        """IndexLogEntry.scala:538-541."""
        return self.properties.get("lineage", "false").lower() == "true"

    def source_file_infos(self) -> List[FileInfo]:
        """All source files recorded at build/refresh time."""
        out: List[FileInfo] = []
        for rel in self.relations:
            out.extend(rel.content.file_infos())
        return out

    def source_files_size(self) -> int:
        return sum(f.size for f in self.source_file_infos())

    def appended_files(self) -> List[FileInfo]:
        """Files recorded as appended by quick refresh (for hybrid scan)."""
        out: List[FileInfo] = []
        for rel in self.relations:
            if rel.update and rel.update.appended_files:
                out.extend(rel.update.appended_files.file_infos())
        return out

    def deleted_files(self) -> List[FileInfo]:
        out: List[FileInfo] = []
        for rel in self.relations:
            if rel.update and rel.update.deleted_files:
                out.extend(rel.update.deleted_files.file_infos())
        return out

    def has_source_update(self) -> bool:
        """True when a quick refresh recorded pending appends/deletes."""
        return bool(self.appended_files() or self.deleted_files())

    def copy_with_update(self, fingerprint: LogicalPlanFingerprint,
                         appended: Sequence[FileInfo],
                         deleted: Sequence[FileInfo]) -> "IndexLogEntry":
        """New entry recording appended/deleted files without touching index
        data (IndexLogEntry.scala:483-505); used by quick refresh."""
        if len(self.relations) != 1:
            raise ValueError("copy_with_update supports single-relation sources")
        rel = self.relations[0]
        new_rel = dataclasses.replace(
            rel,
            update=Update(
                appended_files=Content.from_leaf_files(list(appended)),
                deleted_files=Content.from_leaf_files(list(deleted)),
            ),
        )
        return dataclasses.replace(
            self,
            source=Source([new_rel], fingerprint),
            _tags={},
        )

    # -- tags (in-memory memoization, IndexLogEntry.scala:560-603) ----------
    # Tags are keyed by (tag, plan node) like the reference's
    # setTagValue(plan, tag, value): the same entry can be a signature match
    # for one relation and not another within a single rule invocation.
    def set_tag(self, key: str, value: Any, plan: Any = None) -> None:
        self._tags[(key, id(plan))] = value

    def get_tag(self, key: str, plan: Any = None) -> Optional[Any]:
        return self._tags.get((key, id(plan)))

    def unset_tag(self, key: str, plan: Any = None) -> None:
        self._tags.pop((key, id(plan)), None)


class IndexLogEntryTags:
    """Tag keys (index/IndexLogEntryTags.scala:21-56)."""

    SIGNATURE_MATCHED = "signatureMatched"
    IS_HYBRIDSCAN_CANDIDATE = "isHybridScanCandidate"
    HYBRIDSCAN_RELATED_CONFIGS = "hybridScanRelatedConfigs"
    COMMON_BYTES = "commonBytes"


# ---------------------------------------------------------------------------
# FileIdTracker
# ---------------------------------------------------------------------------
class FileIdTracker:
    """Stable (path, size, mtime) → id map (IndexLogEntry.scala:617-686).

    Ids are handed out monotonically and survive refreshes because the
    tracker is seeded from the previous log entry; a changed (size, mtime)
    for the same path gets a fresh id, which is what makes lineage-based
    deleted-row filtering sound.
    """

    def __init__(self) -> None:
        self._ids: Dict[Tuple[str, int, int], int] = {}
        self._max_id = -1

    @property
    def max_id(self) -> int:
        return self._max_id

    def add_file(self, path: str, size: int, mtime: int) -> int:
        key = (path, size, mtime)
        fid = self._ids.get(key)
        if fid is None:
            self._max_id += 1
            fid = self._max_id
            self._ids[key] = fid
        return fid

    def add_file_info(self, f: FileInfo) -> None:
        """Seed from a previous entry's recorded files, keeping their ids
        (IndexLogEntry.scala:648-668)."""
        if f.id < 0:
            raise ValueError(f"FileInfo without id: {f.name}")
        key = (f.name, f.size, f.mtime)
        existing = self._ids.get(key)
        if existing is not None and existing != f.id:
            raise ValueError(f"Conflicting id for {f.name}: {existing} vs {f.id}")
        self._ids[key] = f.id
        self._max_id = max(self._max_id, f.id)

    def get_file_id(self, path: str, size: int, mtime: int) -> Optional[int]:
        return self._ids.get((path, size, mtime))

    def file_to_id_map(self) -> Dict[Tuple[str, int, int], int]:
        return dict(self._ids)

    @staticmethod
    def from_log_entry(entry: "IndexLogEntry") -> "FileIdTracker":
        tracker = FileIdTracker()
        for f in entry.source_file_infos():
            tracker.add_file_info(f)
        return tracker
