"""Per-file quarantine: the containment layer of the integrity subsystem.

A corrupt index data file used to cost the whole index (PR 2's degraded
fallback re-plans every query against the source).  Quarantine shrinks
the blast radius to the damaged BUCKET: a file that fails verification
(actions/verify.py) or dies mid-query (dataset.collect's containment
path) is recorded here, the rewrite rules then exclude its bucket from
the index side and re-read only that bucket's rows from source
(rules/hybrid.py), and ``refresh_index(mode="repair")`` rebuilds exactly
the quarantined buckets and clears the records.

Records persist through the :class:`~hyperspace_tpu.io.log_store.LogStore`
seam — one key per quarantined file under
``<indexPath>/_hyperspace_quarantine/`` — so the same code works over
:class:`PosixLogStore` and :class:`EmulatedObjectStore` (the backend
follows ``hyperspace.index.logStoreClass``), survives restarts, and is
visible to every process serving the index.  Keys are percent-encoded
relative paths (flat — PosixLogStore keys must not contain ``/``);
values are small JSON records (reason, observed size, timestamp).
``put_if_absent`` makes quarantining idempotent under concurrent
discoverers.
"""

from __future__ import annotations

import json
import os
import time
import urllib.parse
from typing import Dict, List, Optional, Set

from hyperspace_tpu.io.log_store import LogStore

QUARANTINE_DIR = "_hyperspace_quarantine"


def quarantine_manager_for(conf, index_path: str) -> "QuarantineManager":
    """The one constructor everyone uses (collection manager, rules,
    repair, vacuum): store backend from ``hyperspace.index.logStoreClass``
    rooted inside the index directory."""
    from hyperspace_tpu.exceptions import HyperspaceError
    from hyperspace_tpu.utils.reflection import load_class

    cls = load_class(conf.log_store_class, LogStore, HyperspaceError)
    store = cls(os.path.join(index_path, QUARANTINE_DIR),
                stale_list_s=float(getattr(
                    conf, "object_store_stale_list_ms", 0.0)) / 1000.0)
    return QuarantineManager(index_path, store)


class QuarantineManager:
    def __init__(self, index_path: str, store: LogStore) -> None:
        self.index_path = os.path.abspath(index_path)
        self.store = store

    # -- key mapping ---------------------------------------------------------
    def _key(self, file_path: str) -> str:
        rel = os.path.relpath(os.path.abspath(file_path), self.index_path)
        return urllib.parse.quote(rel, safe="")

    def _path_of_key(self, key: str) -> str:
        return os.path.join(self.index_path, urllib.parse.unquote(key))

    # -- mutations -----------------------------------------------------------
    def add(self, file_path: str, reason: str,
            size: Optional[int] = None) -> bool:
        """Record ``file_path`` as quarantined (idempotent: a concurrent
        discoverer's record wins and this returns False)."""
        record = {"reason": reason, "ts": time.time()}
        if size is not None:
            record["size"] = int(size)
        payload = json.dumps(record).encode("utf-8")
        return self.store.put_if_absent(self._key(file_path), payload)

    def remove(self, file_path: str) -> None:
        self.store.delete(self._key(file_path))

    def clear(self) -> None:
        for key in self.store.list_keys():
            self.store.delete(key)

    def clear_version(self, version: int) -> None:
        """Drop records for files under ``v__=<version>/`` — called by
        ``IndexDataManager.delete`` so a vacuumed version never leaves
        orphaned quarantine keys behind."""
        from hyperspace_tpu.index.data_manager import INDEX_VERSION_DIR_PREFIX

        prefix = f"{INDEX_VERSION_DIR_PREFIX}{version}{os.sep}"
        for key in self.store.list_keys():
            rel = urllib.parse.unquote(key)
            if rel.startswith(prefix):
                self.store.delete(key)

    # -- reads ---------------------------------------------------------------
    def paths(self) -> Set[str]:
        """Absolute paths of every quarantined file."""
        return {self._path_of_key(k) for k in self.store.list_keys()}

    def records(self) -> List[Dict]:
        """[{"path": abs, "reason": ..., ...}] for reporting."""
        out: List[Dict] = []
        for key in self.store.list_keys():
            rec: Dict = {"path": self._path_of_key(key)}
            try:
                rec.update(json.loads(self.store.read(key).decode("utf-8")))
            except (FileNotFoundError, ValueError, UnicodeDecodeError):
                rec.setdefault("reason", "unreadable quarantine record")
            out.append(rec)
        return out

    def is_quarantined(self, file_path: str) -> bool:
        return self.store.exists(self._key(file_path))
