"""User-visible index statistics.

Reference contract: index/IndexStatistics.scala:43-196 — one summary row per
index: name, indexed/included columns, bucket count, state, size, file
counts, appended/deleted counts, location.

Beyond the reference: the NON-extended summary also carries
``numIndexFiles``/``sizeIndexFiles`` so the advisor's cost model and
``hs.indexes()`` read the same numbers, and ``indexLocation`` falls back
to the path resolver's index root for an entry that lists no content
files yet (a just-created index, or a what-if entry) instead of
rendering empty.
"""

from __future__ import annotations

import os
from typing import List

import pyarrow as pa

from hyperspace_tpu.index.log_entry import IndexLogEntry

INDEX_SUMMARY_COLUMNS = [
    "name", "indexedColumns", "includedColumns", "numBuckets", "schema",
    "indexLocation", "state", "numIndexFiles", "sizeIndexFiles",
]

# Extended field set mirrors IndexStatistics.scala:43-61.
EXTENDED_COLUMNS = INDEX_SUMMARY_COLUMNS + [
    "kind", "hasLineage",
    "numSourceFiles", "sizeSourceFiles", "numAppendedFiles",
    "sizeAppendedFiles", "numDeletedFiles", "sizeDeletedFiles",
    "indexContentPaths",
]


def index_statistics_table(entries: List[IndexLogEntry],
                           extended: bool = False,
                           path_resolver=None) -> pa.Table:
    rows = {c: [] for c in (EXTENDED_COLUMNS if extended else INDEX_SUMMARY_COLUMNS)}
    for e in entries:
        index_files = e.content.file_infos()
        location = os.path.dirname(index_files[0].name) if index_files else ""
        if not location and path_resolver is not None:
            # No content files listed yet (fresh create mid-lifecycle, a
            # hypothetical entry): the index ROOT is still well-defined.
            location = path_resolver.get_index_path(e.name)
        rows["name"].append(e.name)
        rows["indexedColumns"].append(e.indexed_columns)
        rows["includedColumns"].append(e.included_columns)
        rows["numBuckets"].append(e.num_buckets)
        rows["schema"].append(str(e.derived_dataset.schema))
        rows["indexLocation"].append(location)
        rows["state"].append(e.state)
        rows["numIndexFiles"].append(len(index_files))
        rows["sizeIndexFiles"].append(sum(f.size for f in index_files))
        if extended:
            source_files = e.source_file_infos()
            appended = e.appended_files()
            deleted = e.deleted_files()
            rows["kind"].append(e.derived_dataset.KIND)
            rows["hasLineage"].append(e.has_lineage_column())
            rows["numSourceFiles"].append(len(source_files))
            rows["sizeSourceFiles"].append(sum(f.size for f in source_files))
            rows["numAppendedFiles"].append(len(appended))
            rows["sizeAppendedFiles"].append(sum(f.size for f in appended))
            rows["numDeletedFiles"].append(len(deleted))
            rows["sizeDeletedFiles"].append(sum(f.size for f in deleted))
            rows["indexContentPaths"].append(
                sorted({os.path.dirname(f.name) for f in index_files}))
    return pa.table(rows)
