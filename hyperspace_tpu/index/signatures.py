"""Index-validity fingerprinting: is this index still valid for this plan?

Reference contract: index/LogicalPlanSignatureProvider.scala:27-63 (pluggable
provider registry), index/FileBasedSignatureProvider.scala:30-62 (md5 fold
over (size, mtime, path) of every file of every supported leaf relation),
index/PlanSignatureProvider.scala:28-44 (hash of the operator-type chain),
index/IndexSignatureProvider.scala:33-51 (default: md5(file-sig + plan-sig)).

Providers are looked up by name (the conf-driven pluggability of
LogicalPlanSignatureProvider.scala:55-62) from ``PROVIDERS``.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from hyperspace_tpu.index.log_entry import FileInfo
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan
from hyperspace_tpu.utils.hashing import fold_md5, md5_hex


class SignatureProvider:
    name: str = ""

    def signature(self, plan: LogicalPlan, all_files_of: Callable[[Scan], List[FileInfo]]
                  ) -> Optional[str]:
        """None when the plan contains an unsupported leaf
        (FileBasedSignatureProvider.scala:40-47)."""
        raise NotImplementedError


class FileBasedSignatureProvider(SignatureProvider):
    """md5 fold over (size, mtime, name) of every leaf file
    (FileBasedSignatureProvider.scala:38-61)."""

    name = "FileBasedSignatureProvider"

    def signature(self, plan, all_files_of):
        from hyperspace_tpu import native

        leaves = plan.leaf_relations()
        if not leaves:
            return None
        fused = self._fused_native_signature(leaves)
        if fused is not None:
            return fused
        infos: List[FileInfo] = []
        for scan in leaves:
            files = all_files_of(scan)
            if files is None:
                return None
            infos.extend(files)
        folded = native.fold_md5_files(
            [(f.name, f.size, f.mtime) for f in infos])
        if folded is not None:
            return folded
        return fold_md5(f"{f.size}{f.mtime}{f.name}" for f in infos)

    @staticmethod
    def _fused_native_signature(leaves: List[Scan]) -> Optional[str]:
        """Walk + stat + sort + fold in ONE native pass — no per-file Python
        objects.  Applies to the common hot case only: a single plain-file
        leaf whose listing is a directory walk (lake formats resolve files
        through their snapshot metadata; multi-leaf plans fold per leaf, a
        different order than one global sort)."""
        from hyperspace_tpu import native
        from hyperspace_tpu.io.files import expand_globs
        from hyperspace_tpu.sources.interfaces import LAKE_DATA_FORMATS
        from hyperspace_tpu.utils.paths import normalize_path

        if len(leaves) != 1:
            return None
        rel = leaves[0].relation
        if rel.file_paths is not None or rel.index_scan_of \
                or rel.file_format.lower() in LAKE_DATA_FORMATS:
            return None
        roots = [normalize_path(r) for r in expand_globs(rel.root_paths)]
        fp = native.scan_fingerprint(roots)
        return fp[0] if fp is not None else None


class PlanSignatureProvider(SignatureProvider):
    """Hash of the operator-type chain (PlanSignatureProvider.scala:28-44)."""

    name = "PlanSignatureProvider"

    def signature(self, plan, all_files_of):
        types: List[str] = []

        def walk(node: LogicalPlan) -> None:
            types.append(type(node).__name__)
            for c in node.children:
                walk(c)

        walk(plan)
        return md5_hex("".join(types))


class IndexSignatureProvider(SignatureProvider):
    """Default provider: md5(file_signature + plan_signature)
    (IndexSignatureProvider.scala:33-51)."""

    name = "IndexSignatureProvider"

    def __init__(self) -> None:
        self._files = FileBasedSignatureProvider()
        self._plan = PlanSignatureProvider()

    def signature(self, plan, all_files_of):
        fs = self._files.signature(plan, all_files_of)
        if fs is None:
            return None
        ps = self._plan.signature(plan, all_files_of)
        return md5_hex(fs + ps)


PROVIDERS: Dict[str, Callable[[], SignatureProvider]] = {
    FileBasedSignatureProvider.name: FileBasedSignatureProvider,
    PlanSignatureProvider.name: PlanSignatureProvider,
    IndexSignatureProvider.name: IndexSignatureProvider,
}


def get_provider(name: str) -> SignatureProvider:
    try:
        return PROVIDERS[name]()
    except KeyError:
        raise ValueError(f"Unknown signature provider: {name!r}") from None
