"""Versioned index-data directory manager.

Reference contract: index/IndexDataManager.scala:23-74 — index data for each
rebuild lives in a hive-style ``v__=<N>/`` subdirectory of the index path:

    <systemPath>/<indexName>/
      _hyperspace_log/0,1,...,latestStable
      v__=0/part-*.parquet
      v__=1/...

``get_latest_version`` discovers the highest N present; ``delete`` removes a
version directory (used by VacuumAction, actions/VacuumAction.scala:46-52).
"""

from __future__ import annotations

import os
from typing import List, Optional

INDEX_VERSION_DIR_PREFIX = "v__="  # IndexConstants.scala:67


class IndexDataManager:
    def __init__(self, index_path: str, quarantine=None) -> None:
        self.index_path = index_path
        # Optional QuarantineManager (index/quarantine.py): when attached
        # (the collection manager always does), deleting a version also
        # drops that version's quarantine records.
        self.quarantine = quarantine

    def version_path(self, version: int) -> str:
        return os.path.join(self.index_path, f"{INDEX_VERSION_DIR_PREFIX}{version}")

    def versions(self) -> List[int]:
        if not os.path.isdir(self.index_path):
            return []
        out = []
        for name in os.listdir(self.index_path):
            if name.startswith(INDEX_VERSION_DIR_PREFIX):
                suffix = name[len(INDEX_VERSION_DIR_PREFIX):]
                # Directories only: a stray FILE named v__=N (a partial
                # upload, a tool's scratch) must not inflate the version
                # counter or feed delete() a non-directory.
                if suffix.isdigit() and os.path.isdir(
                        os.path.join(self.index_path, name)):
                    out.append(int(suffix))
        return sorted(out)

    def get_latest_version(self) -> Optional[int]:
        versions = self.versions()
        return versions[-1] if versions else None

    def get_next_version(self) -> int:
        latest = self.get_latest_version()
        return 0 if latest is None else latest + 1

    def delete(self, version: int) -> None:
        from hyperspace_tpu.io.files import remove_tree

        path = self.version_path(version)
        if os.path.isdir(path):
            remove_tree(path)
        if self.quarantine is not None:
            # A vacuumed version must not leave orphaned quarantine keys:
            # the files are gone, the records would read as eternally
            # "missing" to every future scrub.
            self.quarantine.clear_version(version)
