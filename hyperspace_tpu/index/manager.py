"""Index collection manager: name → managers, dispatch to actions.

Reference contract: index/IndexManager.scala:24-116 (trait) and
index/IndexCollectionManager.scala:28-170 — create/delete/restore/vacuum/
refresh/optimize/cancel dispatch to Action instances over per-index log/data
managers; ``get_indexes`` scans the system path for latest stable entries.

Robustness beyond the reference:
  - every dispatched action is armed with the optimistic transaction loop
    (``hyperspace.index.concurrency.maxRetries``; actions/base.py) so a
    concurrent-write conflict rebases and retries instead of aborting;
  - ``get_indexes`` is the query path's one gateway to index metadata, so
    DEGRADED MODE lives here: an index whose log is unreadable, torn past
    recovery, or whose store is erroring is skipped (telemetry records an
    IndexDegradedEvent) rather than breaking the query — the Hyperspace
    contract that a damaged index only stops accelerating.  Disable the
    fallback (``hyperspace.system.degraded.fallbackToSource=false``) to
    get a strict DegradedIndexError instead.
"""

from __future__ import annotations

import os
from typing import List, Optional

from hyperspace_tpu.exceptions import DegradedIndexError, HyperspaceError
from hyperspace_tpu.index.data_manager import IndexDataManager
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.index.log_entry import IndexLogEntry, States
from hyperspace_tpu.index.log_manager import IndexLogManager
from hyperspace_tpu.index.path_resolver import PathResolver


def _resolve_log_manager_class(name: str) -> type:
    """Conf-pluggable operation-log backend (the object-store seam:
    stores without atomic rename plug ObjectStoreLogManager — or their
    own conditional-put subclass — into ``hyperspace.index.logManagerClass``)."""
    from hyperspace_tpu.utils.reflection import load_class

    return load_class(name, IndexLogManager, HyperspaceError)


class IndexCollectionManager:
    def __init__(self, session) -> None:
        self.session = session
        self.path_resolver = PathResolver(session.conf)
        # True when the most recent get_indexes skipped at least one
        # unreadable index — the caching subclass refuses to cache such a
        # listing so a recovered store is picked up immediately.
        self.last_listing_degraded: bool = False

    # -- manager plumbing (index/factories.scala:24-54) ---------------------
    def _log_manager(self, name: str) -> IndexLogManager:
        from hyperspace_tpu.utils.retry import policy_from_conf

        cls = _resolve_log_manager_class(self.session.conf.log_manager_class)
        mgr = cls(self.path_resolver.get_index_path(name))
        # Attribute, not constructor kwarg: pluggable subclasses keep the
        # (index_path)-only __init__ contract; configure() is the richer
        # post-construction conf hook (store class, staleness window).
        mgr.retry = policy_from_conf(self.session.conf)
        mgr.configure(self.session.conf)
        return mgr

    def _dispatch(self, action) -> str:
        """Arm the optimistic transaction loop from session conf, then
        run: a ConcurrentWriteError rebases + re-validates + retries with
        jittered backoff up to ``hyperspace.index.concurrency.maxRetries``
        times, composing with _maybe_recover's rollback (which already
        ran before the action was built).  Returns the run outcome
        (``"ok"``/``"noop"``, actions/base.py)."""
        from hyperspace_tpu.utils.retry import policy_from_conf

        action.concurrency_max_retries = int(
            self.session.conf.concurrency_max_retries)
        action.conflict_backoff = policy_from_conf(self.session.conf)
        return action.run()

    def _maybe_recover(self, name: str) -> None:
        """With ``hyperspace.index.autoRecovery.enabled``, roll a
        transient latest entry (a prior action died mid-flight) back to
        the last stable state before dispatching — an implicit cancel()
        (actions/CancelAction.scala:25-58).  Safe against a merely SLOW
        concurrent action: the rollback and that action's commit race on
        the same log id, and the create-if-absent write arbitrates."""
        if not self.session.conf.auto_recovery_enabled:
            return
        from hyperspace_tpu.actions.cancel import CancelAction

        mgr = self._log_manager(name)
        latest = mgr.get_latest_log()
        if latest is not None and latest.state not in States.STABLE:
            self._dispatch(CancelAction(mgr))

    def _data_manager(self, name: str) -> IndexDataManager:
        # The quarantine manager rides along so version deletion (vacuum)
        # also drops that version's quarantine records — no orphaned keys.
        return IndexDataManager(self.path_resolver.get_index_path(name),
                                quarantine=self.quarantine_manager(name))

    def quarantine_manager(self, name: str):
        """Per-index quarantine set (index/quarantine.py), persisted
        through the LogStore seam (``hyperspace.index.logStoreClass``)."""
        from hyperspace_tpu.index.quarantine import quarantine_manager_for

        return quarantine_manager_for(self.session.conf,
                                      self.path_resolver.get_index_path(name))

    def verify(self, name: str, mode: str = "quick"):
        """Scrub ``name``'s data files against its log entry
        (actions/verify.py); returns the per-file report table."""
        from hyperspace_tpu.actions.verify import VerifyIndexAction

        return VerifyIndexAction(self._log_manager(name),
                                 self._data_manager(name),
                                 self.quarantine_manager(name),
                                 mode=mode).run()

    # -- lifecycle APIs (IndexCollectionManager.scala:36-107) ---------------
    def create(self, dataset, config: IndexConfig) -> None:
        from hyperspace_tpu.actions.create import CreateAction
        from hyperspace_tpu.actions.data_skipping import CreateDataSkippingAction
        from hyperspace_tpu.index.index_config import DataSkippingIndexConfig

        self._maybe_recover(config.index_name)
        action_cls = CreateDataSkippingAction \
            if isinstance(config, DataSkippingIndexConfig) else CreateAction
        self._dispatch(action_cls(self._log_manager(config.index_name),
                                  self._data_manager(config.index_name),
                                  self.session, dataset.plan, config))

    def delete(self, name: str) -> None:
        from hyperspace_tpu.actions.delete import DeleteAction

        self._maybe_recover(name)
        self._dispatch(DeleteAction(self._log_manager(name)))

    def restore(self, name: str) -> None:
        from hyperspace_tpu.actions.restore import RestoreAction

        self._maybe_recover(name)
        self._dispatch(RestoreAction(self._log_manager(name)))

    def vacuum(self, name: str) -> None:
        from hyperspace_tpu.actions.vacuum import VacuumAction

        self._maybe_recover(name)
        self._dispatch(VacuumAction(self._log_manager(name),
                                    self._data_manager(name)))

    def cancel(self, name: str) -> None:
        from hyperspace_tpu.actions.cancel import CancelAction

        self._dispatch(CancelAction(self._log_manager(name)))

    def refresh(self, name: str, mode: str = "full"):
        """Dispatch one refresh; returns a
        :class:`~hyperspace_tpu.actions.refresh.RefreshSummary` — what
        the diff saw and what was committed (``outcome="noop"`` for an
        unchanged source, not an exception)."""
        from hyperspace_tpu.actions.data_skipping import RefreshDataSkippingAction
        from hyperspace_tpu.actions.refresh import (
            RefreshAction,
            RefreshIncrementalAction,
            RefreshQuickAction,
            RefreshSummary,
        )

        if mode == "repair":
            # Integrity self-heal: rebuild only the quarantined buckets
            # and clear their records (actions/repair.py).
            from hyperspace_tpu.actions.repair import RepairAction

            self._maybe_recover(name)
            action = RepairAction(
                self._log_manager(name), self._data_manager(name),
                self.session,
                previous=self._log_manager(name).get_latest_stable_log(),
                quarantine=self.quarantine_manager(name))
            return action.summary(self._dispatch(action))
        cls = {"full": RefreshAction,
               "incremental": RefreshIncrementalAction,
               "quick": RefreshQuickAction}.get(mode)
        if cls is None:
            raise HyperspaceError(f"Unknown refresh mode {mode!r}")
        self._maybe_recover(name)
        # Data-skipping sketches are rebuilt/patched by their own action
        # (quick refresh is kind-agnostic: metadata only).  The stable entry
        # read here is handed to the action so the log parses once.
        stable = self._log_manager(name).get_latest_stable_log()
        if stable is not None and not stable.is_covering and mode != "quick":
            cls = RefreshDataSkippingAction
        action = cls(self._log_manager(name), self._data_manager(name),
                     self.session, previous=stable)
        outcome = self._dispatch(action)
        if hasattr(action, "summary"):
            return action.summary(outcome)
        # The data-skipping refresh predates RefreshSummary; synthesize
        # one from the requested mode and the committed id.
        return RefreshSummary(
            index=name, mode=mode,
            outcome="ok" if outcome == "ok" else "noop",
            version=action.base_id + 2 if outcome == "ok" else None)

    def optimize(self, name: str, mode: str = "quick"):
        """Dispatch one compaction; returns an
        :class:`~hyperspace_tpu.actions.optimize.OptimizeSummary` —
        what was merged and the committed version (``outcome="noop"``
        when no bucket held mergeable files, not an exception)."""
        from hyperspace_tpu.actions.optimize import OptimizeAction

        if mode not in ("quick", "full"):
            raise HyperspaceError(f"Unknown optimize mode {mode!r}")
        self._maybe_recover(name)
        action = OptimizeAction(self._log_manager(name),
                                self._data_manager(name),
                                self.session, mode)
        return action.summary(self._dispatch(action))

    # -- queries (IndexCollectionManager.scala:109-170) ---------------------
    def _degrade(self, name: str, reason: str) -> None:
        """Record (or, in strict mode, raise) one index's degradation."""
        if not self.session.conf.degraded_fallback_to_source:
            raise DegradedIndexError(
                f"Index {name!r} is unreadable ({reason}) and "
                "hyperspace.system.degraded.fallbackToSource is disabled")
        self.last_listing_degraded = True
        from hyperspace_tpu.telemetry.events import (
            IndexDegradedEvent,
            emit_event,
        )

        emit_event(IndexDegradedEvent(
            index_name=name, reason=reason,
            message=f"index {name!r} skipped: {reason}"))

    def get_indexes(self, states: Optional[List[str]] = None) -> List[IndexLogEntry]:
        from hyperspace_tpu.io.files import list_dir

        self.last_listing_degraded = False
        root = self.path_resolver.system_path
        out: List[IndexLogEntry] = []
        try:
            # Underscore-prefixed dirs are SYSTEM state, not indexes (the
            # parquet convention): _hyperspace_workload (advisor capture),
            # _hyperspace_perf (perf ledger) live beside the index dirs.
            names = sorted(n for n in list_dir(root)
                           if not n.startswith("_")
                           and os.path.isdir(os.path.join(root, n)))
        except OSError as e:
            self._degrade("", f"system path listing failed: {e}")
            return out
        for name in names:
            mgr = self._log_manager(name)
            try:
                entry = mgr.get_latest_stable_log()
                if entry is None and mgr.log_ids() \
                        and mgr.get_latest_log() is None:
                    # Entries exist but NONE parses: torn past recovery
                    # (an empty log or a mid-lifecycle transient state is
                    # NOT corruption — those read as absent/unstable).
                    self._degrade(name, "operation log torn past recovery")
                    continue
            except DegradedIndexError:
                raise  # strict mode: _degrade already diagnosed it
            except Exception as e:  # noqa: BLE001 — InjectedCrash is a
                # BaseException and still propagates (a crash is a crash).
                self._degrade(name, f"operation log unreadable: {e}")
                continue
            if entry is not None and (states is None or entry.state in states):
                out.append(entry)
        return out

    def get_index(self, name: str,
                  version: Optional[int] = None) -> Optional[IndexLogEntry]:
        """Latest stable entry, or a specific log version
        (IndexCollectionManager.scala:165-170)."""
        if version is None:
            return self._log_manager(name).get_latest_stable_log()
        return self._log_manager(name).get_log(version)

    def indexes(self):
        """Summary table of all indexes (IndexStatistics DataFrame analog,
        IndexCollectionManager.scala:109-118)."""
        from hyperspace_tpu.index.statistics import index_statistics_table

        return index_statistics_table(self.get_indexes(),
                                      path_resolver=self.path_resolver)
